"""Bisection ladder for the flat-kernel gradient wedge (VERDICT r4 #2).

Round-4 finding: rerouting the pipeline's in-manual-region attention
('ring-shard' / 'ulysses-shard') onto the projection-layout flat kernels
makes the GRADIENT abort the XLA:CPU runtime (flat ring) or hang (flat
ulysses) inside the pp x sp x tp nested manual region, while the plain
shard_mapped flat paths are green (models/llama.py Attention comment,
docs/round4-notes.md). This script isolates which ingredient kills it.

Each stage is a tiny differentiated program (B=1, S=16, H=2, D=8,
block 8 — small enough that pallas interpret mode runs in seconds,
which is what round 4's attempt got wrong) run in a SUBPROCESS with a
timeout, so an abort or hang is classified instead of taking the
driver down:

    python hack/wedge_repro.py          # run the whole ladder, print table
    python hack/wedge_repro.py STAGE    # run one stage inline (may crash!)

Stages build up the nesting one ingredient at a time:

    flat_sp            flat ring, shard_map manual over sp        (green)
    bhsd_sp            [B,H,S,D] ring, same                       (control)
    flat_sp_tp         + tp as a GSPMD AUTO axis (partial manual)
    flat_sp_pp         + outer lax.scan with ppermute over pp (full manual)
    flat_sp_pp_tp      + both (the pipeline's exact nesting)
    bhsd_sp_pp_tp      control at full nesting
    ulysses_sp_pp_tp   flat ulysses at full nesting
    llama_pp_ring         the real llama_pp step (flat '-shard' with
                          tp-manual kernel regions — the fix)
    llama_pp_ulysses      same for ulysses
    llama_pp_flat_raw_ring    NEGATIVE CONTROL: the round-4 reroute
                          (direct flat kernels, no tp-manual wrap) —
                          expected ABORT: the auto-axis partitioner
                          splits the interpret-mode kernel's head
                          slices over tp and plants halo
                          collective-permutes inside device-varying
                          pl.when branches; devices join different
                          rendezvous and XLA:CPU CHECK-fails
    llama_pp_flat_raw_ulysses same; INTERMITTENT — its kernel's causal
                          clamp is uniform (block-index-based), so the
                          failure needs the executor to order the
                          GSPMD-inserted collectives differently
                          across devices (round 4 observed a hang;
                          some runs pass)

ROOT CAUSE (found by this ladder + an HLO dump of the negative
control): NOT the nesting itself — every synthetic stage is green.
The round-4 wedge was GSPMD partitioning the pallas kernels'
interpret-mode internals over the AUTO tp axis: the in-kernel head
slices over the tp-sharded [H·D] dim become tiny halo
collective-permutes INSIDE `pl.when` branches whose predicates are
device-varying (id-masked causal clamps depend on axis_index(sp)), so
devices disagree about which collective to run next and the runtime
deadlocks. Fix: complete the kernel region to manual over tp
(`ring_attention._flash_bshd_tp_manual`), which removes every
auto-visible op from the kernel internals. On real TPU hardware the
kernels are opaque Mosaic custom calls either way; interpret mode
(chipless CI, the multichip dryrun) is where the partitioner could see
inside.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time

B, S, H, HKV, D = 1, 16, 2, 1, 8
BLOCK = 8
TIMEOUT_S = float(os.environ.get("WEDGE_TIMEOUT_S", "600"))


def _env_cpu(n_devices: int) -> dict:
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from mpi_operator_tpu.utils.env import cpu_subprocess_env

    return cpu_subprocess_env(n_devices)


# --------------------------------------------------------------------------
# Stage bodies (run inline in the child process)
# --------------------------------------------------------------------------


def _setup(n_devices: int):
    import jax

    jax.config.update("jax_platforms", "cpu")
    devs = jax.devices()[:n_devices]
    assert len(devs) == n_devices, f"need {n_devices}, have {len(devs)}"
    return jax, devs


def _qkv(jnp):
    import numpy as np

    r = np.random.RandomState(0)
    q = jnp.asarray(r.standard_normal((B, S, H, D)), jnp.float32)
    k = jnp.asarray(r.standard_normal((B, S, HKV, D)), jnp.float32)
    v = jnp.asarray(r.standard_normal((B, S, HKV, D)), jnp.float32)
    return q, k, v


def _ring_flat(q, k, v):
    from mpi_operator_tpu.ops.ring_attention import ring_attention_bshd

    return ring_attention_bshd(
        q, k, v, "sp", causal=True, block_q=BLOCK, block_k=BLOCK
    )


def _ring_bhsd(q, k, v):
    # [B,S,H,D] -> [B,H,S,D] per-shard, ring, back — what the pipeline
    # runs today (the transposes the flat path exists to remove).
    from mpi_operator_tpu.ops.ring_attention import ring_attention

    qt, kt, vt = (t.transpose(0, 2, 1, 3) for t in (q, k, v))
    out = ring_attention(
        qt, kt, vt, "sp", causal=True, block_q=BLOCK, block_k=BLOCK
    )
    return out.transpose(0, 2, 1, 3)


def _ulysses_flat(q, k, v):
    from mpi_operator_tpu.ops.ulysses import ulysses_attention_bshd

    return ulysses_attention_bshd(
        q, k, v, "sp", causal=True, block_q=BLOCK, block_k=BLOCK
    )


def _grad_stage(attn, manual_axes, mesh_axes, pp_scan: bool):
    """Differentiate sum(attn-or-pipeline(q,k,v)) through shard_map."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    n = 1
    for s in mesh_axes.values():
        n *= s
    jax_, devs = _setup(n)
    import numpy as np

    mesh = jax.sharding.Mesh(
        np.asarray(devs).reshape(*mesh_axes.values()),
        tuple(mesh_axes.keys()),
    )

    def per_shard(q, k, v):
        if not pp_scan:
            return attn(q, k, v)

        def tick(state, t):
            o = attn(state, k, v)
            perm = [(i, (i + 1) % mesh_axes["pp"])
                    for i in range(mesh_axes["pp"])]
            return jax.lax.ppermute(o.astype(state.dtype), "pp", perm), None

        state, _ = jax.lax.scan(tick, q, jnp.arange(3))
        return state

    spec = P(None, "sp", None, None)
    kw = {}
    if manual_axes is not None:
        kw["axis_names"] = frozenset(manual_axes)
    fn = jax.shard_map(
        per_shard, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False, **kw,
    )

    def loss(q, k, v):
        return jnp.sum(fn(q, k, v))

    q, k, v = _qkv(jnp)
    with mesh:
        grads = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)
    jax.block_until_ready(grads)
    gnorm = sum(float(jnp.sum(jnp.abs(g))) for g in grads)
    assert gnorm > 0.0, "zero gradient"
    print(f"grads ok, |g|_1 = {gnorm:.4f}")


def _patch_flat_shard():
    """NEGATIVE CONTROL: reroute the pipeline's in-manual-region impls
    onto the RAW flat kernels with no tp-manual wrap — the exact
    round-4 change that wedged (see module docstring for the root
    cause this preserves a repro of)."""
    # `mpi_operator_tpu.ops.__init__` re-exports the ring_attention
    # FUNCTION, shadowing the submodule attribute on the package — go
    # through sys.modules for the module object itself.
    import importlib

    ra = importlib.import_module("mpi_operator_tpu.ops.ring_attention")
    orig = ra.sp_attention_bshd

    def patched(q, k, v, mesh, impl, *, causal, zigzag=False,
                block_q=128, block_k=128):
        if impl == "ring-shard":
            return ra.ring_attention_bshd(
                q, k, v, ra.SP, causal=causal, zigzag=zigzag,
                block_q=block_q, block_k=block_k,
            )
        if impl == "ulysses-shard":
            from mpi_operator_tpu.ops.ulysses import ulysses_attention_bshd

            return ulysses_attention_bshd(
                q, k, v, ra.SP, causal=causal,
                block_q=block_q, block_k=block_k,
            )
        return orig(q, k, v, mesh, impl, causal=causal, zigzag=zigzag,
                    block_q=block_q, block_k=block_k)

    ra.sp_attention_bshd = patched


def _llama_pp_stage(impl: str, flat: bool):
    """The dryrun's sp2 x tp2 x pp2 config (the one that wedged), tiny."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    _setup(8)
    if flat:
        _patch_flat_shard()
    from mpi_operator_tpu.models import llama as llama_lib
    from mpi_operator_tpu.models import llama_pp as pp_lib
    from mpi_operator_tpu.parallel import create_mesh, shard_batch

    devices = jax.devices()[:8]
    mesh = create_mesh(dp=-1, sp=2, tp=2, pp=2, devices=devices)
    cfg = llama_lib.tiny(n_layers=2, attention_impl=impl, dim=64)
    params = pp_lib.shard_pp_params(
        pp_lib.init_pp_params(cfg, 2, jax.random.PRNGKey(5)), mesh
    )
    opt = optax.sgd(1e-2)
    opt_state = pp_lib.shard_pp_opt_state(opt.init(params), mesh)
    tokens = shard_batch(
        jnp.asarray(
            np.random.RandomState(6).randint(0, cfg.vocab_size, (4, 16)),
            jnp.int32,
        ),
        mesh, sequence_axis=1,
    )
    step = jax.jit(pp_lib.make_pp_train_step(cfg, mesh, opt, 1))
    with mesh:
        params2, _, loss = step(params, opt_state, tokens)
    jax.block_until_ready(loss)
    assert jnp.isfinite(loss), f"non-finite loss {loss}"
    print(f"llama_pp {impl} flat={flat} loss={float(loss):.4f}")


STAGES = {
    "flat_sp": (2, lambda: _grad_stage(
        _ring_flat, None, {"sp": 2}, pp_scan=False)),
    "bhsd_sp": (2, lambda: _grad_stage(
        _ring_bhsd, None, {"sp": 2}, pp_scan=False)),
    "flat_sp_tp": (4, lambda: _grad_stage(
        _ring_flat, {"sp"}, {"sp": 2, "tp": 2}, pp_scan=False)),
    "flat_sp_pp": (4, lambda: _grad_stage(
        _ring_flat, None, {"sp": 2, "pp": 2}, pp_scan=True)),
    "flat_sp_pp_tp": (8, lambda: _grad_stage(
        _ring_flat, {"sp", "pp"}, {"sp": 2, "pp": 2, "tp": 2},
        pp_scan=True)),
    "bhsd_sp_pp_tp": (8, lambda: _grad_stage(
        _ring_bhsd, {"sp", "pp"}, {"sp": 2, "pp": 2, "tp": 2},
        pp_scan=True)),
    "ulysses_sp_pp_tp": (8, lambda: _grad_stage(
        _ulysses_flat, {"sp", "pp"}, {"sp": 2, "pp": 2, "tp": 2},
        pp_scan=True)),
    "llama_pp_ring": (8, lambda: _llama_pp_stage("ring", flat=False)),
    "llama_pp_ulysses": (8, lambda: _llama_pp_stage("ulysses", flat=False)),
    "llama_pp_flat_raw_ring": (
        8, lambda: _llama_pp_stage("ring", flat=True)),
    "llama_pp_flat_raw_ulysses": (
        8, lambda: _llama_pp_stage("ulysses", flat=True)),
}


def main() -> int:
    if len(sys.argv) > 1:
        name = sys.argv[1]
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        sys.path.insert(0, repo)
        STAGES[name][1]()
        return 0

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    print(f"{'stage':24} {'devices':>7} {'verdict':>8} {'secs':>6}  detail")
    for name, (n, _) in STAGES.items():
        t0 = time.time()
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__), name],
                env=_env_cpu(n), cwd=repo, timeout=TIMEOUT_S,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            )
            dt = time.time() - t0
            tail = (proc.stdout or "").strip().splitlines()
            tail = tail[-1][:90] if tail else ""
            if proc.returncode == 0:
                verdict = "OK"
            elif proc.returncode < 0:
                verdict = f"ABORT({-proc.returncode})"
            else:
                verdict = f"FAIL({proc.returncode})"
        except subprocess.TimeoutExpired:
            dt = time.time() - t0
            verdict, tail = "HANG", f"no exit in {TIMEOUT_S:.0f}s"
        print(f"{name:24} {n:>7} {verdict:>8} {dt:>6.1f}  {tail}",
              flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
