"""Hardware probe for the head-dim-64 kernel tax (PERF.md round-5).

The round-5 winner traces measured the flat pallas attention kernels at
~33.6% of BERT's device time and ~25% of ViT's — against llama's 12.1%
— at roughly 8-10% of FLOPs. Both families run 64-wide heads; llama
runs 128. Two candidate mechanisms, both fixed by the same kernel
layout change:

  (a) the in-kernel head loop slices operand lanes at 64-element
      offsets (``ref[:, hh*64:(hh+1)*64]``) — every ODD head starts at
      lane 64, an unaligned lane slice Mosaic must realign before the
      MXU can consume it;
  (b) each per-head matmul is half-width on the 128-lane MXU
      (contraction 64 for q·kᵀ, output 64 for p·v), and tile padding
      burns the other half.

The PACKED layout processes pack = 128//d heads per iteration:
  - q/k/v pair slices are ``[:, p*128:(p+1)*128]`` — always aligned;
  - k and v are expanded to BLOCK-DIAGONAL ``[pack*block_k, 128]``
    tiles via lane masks (cheap VPU selects, no shifts), so
    q·kbdᵀ = [s_h0 | s_h1] in one full-width (K=128) matmul and
    p·vbd accumulates both heads' outputs in one full-width (N=128)
    matmul. Tile arithmetic says MXU cycles are EQUAL either way
    (zeros in the block-diag buy exactly the tiles padding wasted), so
    any measured win is the realignment + per-op overhead — which is
    why this needs a hardware A/B, not a model.

Usage (never under a killable timeout — a killed client can wedge the
tunnel, see PERF.md):

    python hack/headdim_probe.py bert   # b=64 x s=512, h=12 d=64, fb256
    python hack/headdim_probe.py vit    # b=128 x s=196, h=12 d=64, fb256
    python hack/headdim_probe.py dots   # raw matmul ladder (cost model)

Prints one line per variant: ms/call and TFLOP/s, plus max|Δ| vs the
current kernel. PROBE_OK on completion.
"""

from __future__ import annotations

import functools
import sys
import time

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from mpi_operator_tpu.ops.attention import (  # noqa: E402
    NEG_INF, _block_mask, _flash_flat_fwd_impl, _pad_to,
)


# --------------------------------------------------------------------------
# Packed-pair forward kernel prototype (pack = 128 // d heads per block)
# --------------------------------------------------------------------------


def _fwd_packed_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
    *, sm_scale, causal, q_len, kv_len, block_q, block_k, h, d, pack,
):
    i, j = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)
    npair = h // pack

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    mask, live = _block_mask(
        i, j, None, None, causal=causal, q_len=q_len, kv_len=kv_len,
        block_q=block_q, block_k=block_k,
    )
    # Lane coordinate of a [block_k, 128] k/v tile and of a
    # [block_q, 128] output tile; slot t owns lanes [t*d, (t+1)*d).
    lane_k = jax.lax.broadcasted_iota(jnp.int32, (block_k, 128), 1)
    lane_q = jax.lax.broadcasted_iota(jnp.int32, (block_q, 128), 1)
    if mask is not None:
        maskw = jnp.concatenate([mask] * pack, axis=1)

    def _lane_select(per_slot):
        """[bq,1] per slot -> [bq,128] with slot t's value on its lanes."""
        out = jnp.broadcast_to(per_slot[0], (block_q, 128))
        for t in range(1, pack):
            out = jnp.where(lane_q >= t * d,
                            jnp.broadcast_to(per_slot[t], (block_q, 128)),
                            out)
        return out

    def compute():
        for p in range(npair):
            qp = q_ref[0][:, p * 128:(p + 1) * 128]
            kp = k_ref[0][:, p * 128:(p + 1) * 128]
            vp = v_ref[0][:, p * 128:(p + 1) * 128]
            kbd = jnp.concatenate(
                [jnp.where((lane_k >= t * d) & (lane_k < (t + 1) * d),
                           kp, jnp.zeros_like(kp))
                 for t in range(pack)], axis=0)
            vbd = jnp.concatenate(
                [jnp.where((lane_k >= t * d) & (lane_k < (t + 1) * d),
                           vp, jnp.zeros_like(vp))
                 for t in range(pack)], axis=0)
            s = jax.lax.dot_general(
                qp, kbd, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * sm_scale                                  # [bq, pack*bk]
            if mask is not None:
                s_masked = jnp.where(maskw, s, NEG_INF)
            else:
                s_masked = s
            corr_slots, p_cols = [], []
            for t in range(pack):
                hh = p * pack + t
                st = s_masked[:, t * block_k:(t + 1) * block_k]
                m_prev = m_ref[:, hh:hh + 1]
                l_prev = l_ref[:, hh:hh + 1]
                m_cur = jnp.max(st, axis=1, keepdims=True)
                m_new = jnp.maximum(m_prev, m_cur)
                # Re-mask after the subtraction (same as _fwd_flat_kernel):
                # a row whose running max is still NEG_INF must produce
                # pt=0, not exp(0)=1, or dead rows defeat the l>0 guard.
                if mask is not None:
                    pt = jnp.exp(jnp.where(mask, st - m_new, NEG_INF))
                else:
                    pt = jnp.exp(st - m_new)
                corr = jnp.exp(m_prev - m_new)
                l_ref[:, hh:hh + 1] = (
                    corr * l_prev + jnp.sum(pt, axis=1, keepdims=True)
                )
                m_ref[:, hh:hh + 1] = m_new
                corr_slots.append(corr)
                p_cols.append(pt)
            p_mat = jnp.concatenate(p_cols, axis=1)        # [bq, pack*bk]
            corr_bcast = _lane_select(corr_slots)          # [bq, 128]
            acc_ref[p] = acc_ref[p] * corr_bcast + jax.lax.dot(
                p_mat.astype(v_ref.dtype), vbd,
                preferred_element_type=jnp.float32,
            )

    if live is None:
        compute()
    else:
        pl.when(live)(compute)

    @pl.when(j == nk - 1)
    def _finalize():
        for p in range(npair):
            l_slots = [l_ref[:, p * pack + t:p * pack + t + 1]
                       for t in range(pack)]
            safe = [jnp.where(l > 0.0, l, 1.0) for l in l_slots]
            l_bcast = _lane_select(safe)
            o_ref[0, :, p * 128:(p + 1) * 128] = (
                acc_ref[p] / l_bcast
            ).astype(o_ref.dtype)
            for t in range(pack):
                hh = p * pack + t
                l = l_slots[t]
                safe_l = safe[t]
                lse_ref[0, :, hh:hh + 1] = jnp.where(
                    l > 0.0, m_ref[:, hh:hh + 1] + jnp.log(safe_l), NEG_INF
                )


def flash_packed_fwd(qf, kf, vf, h, sm_scale, causal, block_q, block_k,
                     interpret=False):
    b, q_len, hd_total = qf.shape
    d = hd_total // h
    assert d <= 128 and 128 % d == 0, f"head dim {d} must divide 128"
    pack = 128 // d
    assert h % pack == 0 and kf.shape[-1] == hd_total, "MHA, h % pack == 0"
    kv_len = kf.shape[1]
    qp = _pad_to(qf, 1, block_q)
    kp = _pad_to(kf, 1, block_k)
    vp = _pad_to(vf, 1, block_k)
    nq, nk = qp.shape[1] // block_q, kp.shape[1] // block_k
    npair = h // pack
    kernel = functools.partial(
        _fwd_packed_kernel,
        sm_scale=sm_scale, causal=causal, q_len=q_len, kv_len=kv_len,
        block_q=block_q, block_k=block_k, h=h, d=d, pack=pack,
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=(b, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, h * d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, h * d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, h * d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, h * d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, h), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(qp.shape, qf.dtype),
            jax.ShapeDtypeStruct((b, qp.shape[1], h), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((npair, block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        ),
        interpret=interpret,
    )(qp, kp, vp)
    return out[:, :q_len], lse[:, :q_len]


# --------------------------------------------------------------------------
# Harness
# --------------------------------------------------------------------------


def _timed(fn, *args, steps=20):
    """Two-window difference quotient with readback barrier (PERF.md)."""
    out = fn(*args)
    np.asarray(jax.tree_util.tree_leaves(out)[0].ravel()[:1])
    n1 = max(steps // 4, 1)
    t0 = time.perf_counter()
    for _ in range(n1):
        out = fn(*args)
    np.asarray(jax.tree_util.tree_leaves(out)[0].ravel()[:1])
    t1 = time.perf_counter()
    for _ in range(steps):
        out = fn(*args)
    np.asarray(jax.tree_util.tree_leaves(out)[0].ravel()[:1])
    t2 = time.perf_counter()
    sec = ((t2 - t1) - (t1 - t0)) / (steps - n1)
    if sec <= 0:
        sec = (t2 - t1) / steps
    return out, sec


def run_attn(shape_name: str) -> None:
    # A single standalone kernel dispatch over the tunnel is launch-
    # latency-bound (measured 1.7 TF/s at b=8 — nonsense vs the ~30 TF/s
    # the same kernel shows inside the bench program), so each timed
    # unit is ONE jitted program chaining REPS kernel calls through the
    # carry (q_{n+1} = o_n, so nothing is loop-invariant and XLA cannot
    # hoist the call).
    REPS = 50
    if shape_name == "bert":
        b, s, h, d, causal = 64, 512, 12, 64, False
    elif shape_name == "vit":
        b, s, h, d, causal = 128, 196, 12, 64, False
    else:
        raise SystemExit(f"unknown shape {shape_name}")
    bq = bk = 256
    key = jax.random.PRNGKey(0)
    kq, kk, kv = jax.random.split(key, 3)
    qf = jax.random.normal(kq, (b, s, h * d), jnp.bfloat16)
    kf = jax.random.normal(kk, (b, s, h * d), jnp.bfloat16)
    vf = jax.random.normal(kv, (b, s, h * d), jnp.bfloat16)
    sm = 1.0 / (d ** 0.5)
    flops = 4.0 * b * h * s * s * d * (0.5 if causal else 1.0)

    def chained(kernel_fn):
        @jax.jit
        def f(q, k, v):
            def body(carry, _):
                o, lse = kernel_fn(carry, k, v)
                return o.astype(carry.dtype), lse
            o, lses = jax.lax.scan(body, q, None, length=REPS)
            return o, lses[-1]
        return f

    cur = chained(lambda q, k, v: _flash_flat_fwd_impl(
        q, k, v, None, None, h, sm, causal, bq, bk, False))
    pkd = chained(lambda q, k, v: flash_packed_fwd(
        q, k, v, h, sm, causal, bq, bk))

    (o_cur, lse_cur), sec_cur = _timed(cur, qf, kf, vf, steps=5)
    sec_cur /= REPS
    print(f"  current flat fwd : {sec_cur*1e3:8.3f} ms  "
          f"{flops/sec_cur/1e12:6.1f} TF/s", flush=True)
    (o_pkd, lse_pkd), sec_pkd = _timed(pkd, qf, kf, vf, steps=5)
    sec_pkd /= REPS
    print(f"  packed-pair fwd  : {sec_pkd*1e3:8.3f} ms  "
          f"{flops/sec_pkd/1e12:6.1f} TF/s", flush=True)
    do = np.max(np.abs(np.asarray(o_cur, np.float32)
                       - np.asarray(o_pkd, np.float32)))
    dl = np.max(np.abs(np.asarray(lse_cur) - np.asarray(lse_pkd)))
    print(f"  max|Δo| {do:.3e}  max|Δlse| {dl:.3e}  "
          f"speedup {sec_cur/sec_pkd:5.2f}x", flush=True)


def run_dots() -> None:
    """Raw MXU cost model: is a K=64 (or N=64) matmul tile-padded?"""
    bq = bk = 256

    def ladder(label, m, k, n, reps):
        a = jax.random.normal(jax.random.PRNGKey(1), (m, k), jnp.bfloat16)
        bmat = jax.random.normal(jax.random.PRNGKey(2), (k, n), jnp.bfloat16)

        @jax.jit
        def f(a, bmat):
            def body(c, _):
                return c + jnp.dot(a, bmat,
                                   preferred_element_type=jnp.float32), None
            c0 = jnp.zeros((m, n), jnp.float32)
            c, _ = jax.lax.scan(body, c0, None, length=reps)
            return c

        _, sec = _timed(f, a, bmat)
        fl = 2.0 * m * k * n * reps
        print(f"  {label:28s}: {sec*1e3:8.3f} ms  {fl/sec/1e12:6.1f} TF/s",
              flush=True)

    ladder(f"[{bq},64]x[64,{bk}] x256", bq, 64, bk, 256)
    ladder(f"[{bq},128]x[128,{bk}] x256", bq, 128, bk, 256)
    ladder(f"[{bq},512]x[512,64] x256", bq, 512, 64, 256)
    ladder(f"[{bq},512]x[512,128] x256", bq, 512, 128, 256)


def main() -> int:
    what = sys.argv[1] if len(sys.argv) > 1 else "bert"
    dev = jax.devices()[0]
    print(f"device: {dev.device_kind}", flush=True)
    if what == "dots":
        run_dots()
    else:
        run_attn(what)
    print("PROBE_OK", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
