#!/usr/bin/env python3
"""Run the registered analyzer rules over the repo (golangci-lint-style
driver for ``mpi_operator_tpu/analysis``).

Usage:

    hack/analyze.py                       # text report, all rules
    hack/analyze.py --format json         # machine-readable report
    hack/analyze.py --fail-on-new         # exit 1 on non-baselined findings
    hack/analyze.py --select TPU4         # one rule family (prefix match)
    hack/analyze.py --update-baseline     # re-snapshot legacy findings
    hack/analyze.py --list-rules          # the rule catalog

The committed baseline (``hack/analysis_baseline.json``) tracks legacy
findings by ``rule|file|message`` key so they stay visible without
failing CI; anything beyond the baselined count is "new" and fails
``--fail-on-new`` (the ``make analyze`` / CI mode).  Suppress a single
site with ``# noqa: TPUxxx`` — see docs/static-analysis.md.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from mpi_operator_tpu.analysis import framework  # noqa: E402

DEFAULT_BASELINE = REPO / "hack" / "analysis_baseline.json"


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--format", choices=("text", "json", "github"),
                        default="text",
                        help="github emits Actions workflow annotations "
                             "(::error for new findings, ::notice for "
                             "baselined) so CI failures are clickable")
    parser.add_argument("--fail-on-new", action="store_true",
                        help="exit 1 when findings exceed the baseline")
    parser.add_argument("--update-baseline", action="store_true",
                        help="rewrite the baseline from current findings")
    parser.add_argument("--baseline", type=Path, default=DEFAULT_BASELINE)
    parser.add_argument("--select", default="",
                        help="comma-separated rule-ID prefixes (TPU4,TPU101)")
    parser.add_argument("--list-rules", action="store_true")
    parser.add_argument("--root", type=Path, default=REPO)
    args = parser.parse_args(argv)

    if args.list_rules:
        for r in framework.all_rules():
            alias = framework.LEGACY_ALIASES.get(r.id)
            alias_txt = f" (alias {alias})" if alias else ""
            print(f"{r.id}{alias_txt}  {r.name}: {r.description}")
        return 0

    # The registry gate: a rule module silently dropping out of the
    # import chain must fail loudly, not pass with fewer rules.
    missing = framework.missing_rule_families()
    if missing:
        print("analyze: FATAL required rule families missing from the "
              f"registry: {', '.join(missing)}", file=sys.stderr)
        return 2

    select = [s.strip() for s in args.select.split(",") if s.strip()] or None
    repo = framework.RepoView(args.root)
    findings = framework.run(repo, select=select)

    if args.update_baseline:
        # The baseline always snapshots the FULL rule set — a selected
        # subset would silently drop every other family's legacy keys.
        if select:
            findings = framework.run(repo)
        old = framework.load_baseline(args.baseline)
        new = framework.baseline_payload(findings)["findings"]
        framework.write_baseline(args.baseline, findings)
        added = sorted(k for k in new if k not in old)
        removed = sorted(k for k in old if k not in new)
        print(f"baseline: wrote {len(findings)} finding(s) to "
              f"{args.baseline} (+{len(added)} added, "
              f"-{len(removed)} stale)")
        for key in added:
            print(f"  + {key}")
        for key in removed:
            print(f"  - {key}")
        return 0

    baseline = framework.load_baseline(args.baseline)
    fresh = framework.new_findings(findings, baseline)
    syntax = [f for f in findings
              if f.rule_id == framework.SYNTAX_RULE_ID]

    if args.format == "github":
        # GitHub Actions workflow commands: new findings annotate the
        # diff as errors; baselined ones stay visible as notices.
        for f in findings:
            level = "error" if f in fresh else "notice"
            print(f"::{level} file={f.file},line={f.line},"
                  f"title={f.rule_id}::{f.message}")
        print(f"analyze: {len(repo.files)} files, {len(findings)} "
              f"finding(s), {len(fresh)} new vs baseline")
    elif args.format == "json":
        print(json.dumps({
            "files": len(repo.files),
            "rules": [r.id for r in framework.all_rules()],
            "findings": [f.to_dict() for f in findings],
            "new": [f.to_dict() for f in fresh],
            "baselined": len(findings) - len(fresh),
            "baseline": str(args.baseline),
        }, indent=2))
    else:
        for f in findings:
            marker = "NEW " if f in fresh else "base"
            print(f"[{marker}] {f.render()}")
        print(f"analyze: {len(repo.files)} files, {len(findings)} "
              f"finding(s), {len(fresh)} new vs baseline")

    if syntax:
        return 1
    if args.fail_on_new and fresh:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
