#!/bin/bash
# One-command TPU bench capture for the moment the axon tunnel answers.
#
# Probes the chip with a tiny naturally-exiting matmul first (never run
# TPU work under a killable timeout — a killed client wedges the remote
# runtime for hours), then runs every suite with a profile dir and
# appends the JSON lines to BENCH_CAPTURE.jsonl plus markdown rows to
# PERF_CAPTURE.md for PERF.md. Also A/Bs the fused pallas BN kernels
# against XLA on the ResNet suite.
#
#   ./hack/tpu_bench_all.sh            # full capture
#   ./hack/tpu_bench_all.sh probe      # probe only
set -u
cd "$(dirname "$0")/.."

# One shared probe implementation (bench.py --probe-only): child process
# with a deadman that self-exits — a wedged tunnel blocks init forever,
# and externally killing a TPU client can wedge the remote runtime.
# Budget 1s = a single attempt here; callers wanting retry set it higher.
probe() {
  BENCH_PROBE_BUDGET_S="${BENCH_PROBE_BUDGET_S:-1}" python bench.py --probe-only
}

echo "== probing the TPU =="
if ! probe; then
  echo "tunnel not answering; try again later"; exit 2
fi
[ "${1:-}" = "probe" ] && exit 0

stamp=$(date -u +%Y%m%dT%H%M%S)
out=BENCH_CAPTURE.jsonl
md=PERF_CAPTURE.md
echo "## TPU capture $stamp" >> "$md"

run() {
  label="$1"; shift
  echo "== $label =="
  log=$(mktemp)
  # NO timeout wrapper — see the header. The probe above already ran, so
  # skip bench.py's own probe-retry loop (~20 s of extra init per suite).
  BENCH_PROBE_BUDGET_S=0 python bench.py "$@" 2>&1 | tee "$log"
  line=$(grep -E '^\{' "$log" | tail -1)
  if [ -n "$line" ]; then
    echo "{\"label\": \"$label\", \"stamp\": \"$stamp\", \"result\": $line}" >> "$out"
    echo "- \`$label\`: \`$line\`" >> "$md"
  else
    echo "- \`$label\`: FAILED (see driver log)" >> "$md"
  fi
}

run resnet101-s2d      --suite resnet --profile-dir /tmp/trace-resnet
run bert-base          --suite bert --profile-dir /tmp/trace-bert
run llama-0p7b         --suite llama --profile-dir /tmp/trace-llama
run vit-b16            --suite vit --profile-dir /tmp/trace-vit
run moe-0p7b-a0p25     --suite moe --profile-dir /tmp/trace-moe
# Capacity-factor A/B (r5: cf 1.0 = +15.9% tok/s over the 1.25
# default - every E x C slot computes whether filled; cf stays a
# quality knob, this line keeps the padding cost visible run to run).
run moe-b8-cf1.0       --suite moe --moe-capacity-factor 1.0
run seq2seq-t5large    --suite seq2seq --profile-dir /tmp/trace-seq2seq
run startup            --suite startup
run decode             --suite decode
# Kernel-vs-compiler A/Bs (each isolates one hypothesis from the
# round-3 MFU gap analysis; see docs/round3-notes.md). The suites above
# already run the flat [B,S,H·D] kernels (the round-4 default); the
# bhsd lines time the old transpose-convention layout against them.
# (A/B rows pin tiles/chunk explicitly — same rule as tpu_tune.py —
# so their labels stay comparable with the r5 rows even though the
# suite defaults moved to fb256/xc1024.)
run bert-flash-bhsd    --suite bert --attention-impl flash-bhsd \
    --flash-block-q 128 --flash-block-k 128
run llama-flash-bhsd   --suite llama --attention-impl flash-bhsd \
    --flash-block-q 128 --flash-block-k 128 --xent-chunk 512
run bert-dense-attn    --suite bert --attention-impl dense
run llama-dense-attn   --suite llama --attention-impl dense --xent-chunk 512
# Batch-8 via bf16 adam first moment: REFUTED r5 — activation temps
# blow 16G at remote compile even with bf16 mu (receipt in PERF.md).
# Kept as a canary for future HBM-larger parts.
run llama-b8-mu-bf16   --suite llama --llama-batch 8 --adam-mu-dtype bf16
# Tile controls: suite defaults are the measured winners (fb256 +
# xc1024, TUNE_CAPTURE r5) — these pin the round-4 values so the
# kernel-internal k/v re-read delta stays visible run over run.
run bert-fb128-ctrl    --suite bert --flash-block-q 128 --flash-block-k 128
run llama-fb128-xc512-ctrl --suite llama --flash-block-q 128 \
    --flash-block-k 128 --xent-chunk 512
# ViT batch points (r5: batch does NOT amortize — b128 wins; kept to
# watch for regressions against that verdict).
run vit-b256           --suite vit --vit-batch 256 \
    --flash-block-q 256 --flash-block-k 256
run vit-b256-remat     --suite vit --vit-batch 256 --vit-remat \
    --flash-block-q 256 --flash-block-k 256
# ResNet A/Bs: scanned stages and pallas BN. R5 hardware verdicts:
# xla-scan OOMs HBM by 25M at batch 128 (scan carries stage buffers);
# pallas BN loses to XLA's fusion in the isolated ladder (114 vs
# 132 GB/s) and whole-model (855.9 img/s vs 1865.1). Defaults
# (bn=xla, unrolled) are the measured winners; lines kept as
# regression canaries against those verdicts.
run resnet101-scan     --suite resnet --scan-stages
python hack/bn_probe.py 1 && python hack/bn_probe.py 5 \
  && run resnet101-bn-pallas-scan --suite resnet --bn-kernel pallas --scan-stages

echo "== sweeps (in-process; every point appended to TUNE_CAPTURE.jsonl) =="
python hack/tpu_tune.py llama --profile-best /tmp/trace-llama-best
python hack/tpu_tune.py bert
python hack/tpu_tune.py vit

echo "== done; commit $out, TUNE_CAPTURE.jsonl, and fold $md into PERF.md =="
