{{- define "tpu-operator.name" -}}
{{- default .Chart.Name .Values.nameOverride | trunc 63 | trimSuffix "-" -}}
{{- end -}}

{{- define "tpu-operator.fullname" -}}
{{- if .Values.fullnameOverride -}}
{{- .Values.fullnameOverride | trunc 63 | trimSuffix "-" -}}
{{- else -}}
{{- printf "%s-%s" .Release.Name (include "tpu-operator.name" .) | trunc 63 | trimSuffix "-" -}}
{{- end -}}
{{- end -}}

{{- define "tpu-operator.serviceAccountName" -}}
{{- if .Values.serviceAccount.create -}}
{{- default (include "tpu-operator.fullname" .) .Values.serviceAccount.name -}}
{{- else -}}
{{- default "default" .Values.serviceAccount.name -}}
{{- end -}}
{{- end -}}

{{- define "tpu-operator.labels" -}}
app: {{ include "tpu-operator.name" . }}
app.kubernetes.io/name: {{ include "tpu-operator.name" . }}
app.kubernetes.io/instance: {{ .Release.Name }}
app.kubernetes.io/component: tpujob
{{- end -}}
