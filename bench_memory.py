#!/usr/bin/env python3
"""Device-memory observatory benchmark: how early the HBM watermark
trend flags a leaking gang, with zero false alarms on a healthy fleet.

``bench_straggler.py`` grades the *time* dimension of gang health; this
harness grades the *memory* dimension — a worker whose HBM footprint
grows every window until the allocator OOM-kills the gang.  It drives N
TPUJob gangs on a simulated clock, injects ``MemoryLeak`` chaos
(chaos/policy.py) through the same ``LeakInjector`` → ``leak_worker``
surface production uses, and feeds each worker's per-window HBM samples
(the real ``DeviceMemorySampler`` with the deterministic fake backend)
through the kube-native path: device-memory annotation → pod informer →
``MemoryMatrix`` (utils/devstats.py) → ``MemoryPressure`` condition.

Per arm (control = no leak, leak = fixed bytes/window) it reports:

- **detection lead** — closed windows between the ``MemoryPressure``
  condition first flipping True and the injected exhaustion (reported
  bytes-in-use crossing the HBM limit); the acceptance gate is lead >=
  the detector's ``pressure_horizon_windows``, i.e. the operator gets
  the whole checkpoint-and-resize budget it promises;
- **false-positive rate** — jobs flagged ``MemoryPressure`` that had no
  leaking worker (must be zero, including the whole control arm, whose
  fake backend carries a trendless allocator ripple);
- **watermark fidelity** — fleet peak bytes and final headroom as the
  matrix joined them.

Determinism: control logic runs on the simulated clock, chaos victims
come from one seeded RNG, and the fake backend is a pure function of the
window index — so the same seed reproduces BENCH_MEMORY.json
bit-for-bit.

Run:  python bench_memory.py --jobs 8 --seed 42
      python bench_memory.py --leak-bytes 1073741824 --lock-trace
Emits BENCH_MEMORY.json (schema-checked; see docs/observability.md)
and prints one JSON summary line.
"""

from __future__ import annotations

import argparse
import json
import random
import sys
import time

from mpi_operator_tpu import chaos
from mpi_operator_tpu.api.v2beta1 import (
    REPLICA_TYPE_WORKER,
    ReplicaSpec,
    TPUJob,
    TPUJobSpec,
    TPUSpec,
)
from mpi_operator_tpu.api.v2beta1 import constants
from mpi_operator_tpu.api.v2beta1.types import JOB_MEMORY_PRESSURE
from mpi_operator_tpu.controller.tpu_job_controller import TPUJobController
from mpi_operator_tpu.runtime import locktrace, retry
from mpi_operator_tpu.runtime.apiserver import ApiError, InMemoryAPIServer
from mpi_operator_tpu.utils import devstats, flightrecorder, metrics
from mpi_operator_tpu.utils import logging as logutil

TEMPLATE = {"spec": {"containers": [{"name": "main", "image": "tpu-image"}]}}
NOW = 1000.0
# v5e-16 = 4x4 chips = 4 hosts = a 4-worker gang per job.
WORKERS_PER_JOB = 4
# Sim seconds per heartbeat-window round.
ROUND_S = 2.5
# Allocator-churn ripple on the fake backend: visible, trendless — the
# control arm's false-positive bait.
RIPPLE_BYTES = 32 * 1024**2
# Default injected leak: 480 MiB/window against the fake backend's
# 12 GiB of free HBM => exhaustion at window 25, detection expected
# pressure_horizon_windows earlier.
LEAK_BYTES = 480 * 1024**2

SCHEMA_VERSION = 1


def log(*args):
    print(*args, file=sys.stderr, flush=True)


class MemoryRunner:
    """The bench's kubelet sim: flips created pods Running (recording
    flight-recorder POD entries, as LocalPodRunner does), exposes the
    ``leak_worker`` surface ``LeakInjector`` drives, and emits each
    worker's per-window HBM sample — produced by the *real*
    ``DeviceMemorySampler`` over the deterministic fake backend — as pod
    annotation patches, exactly the transport the live runner tails out
    of pod logs."""

    def __init__(
        self,
        api: InMemoryAPIServer,
        recorder: flightrecorder.FlightRecorder,
    ):
        self.api = api
        self.recorder = recorder
        # (namespace, pod-name) -> that worker's sampler; leak_worker
        # swaps in a leaking sampler, modelling the env-injected restart.
        self._samplers: dict[tuple[str, str], devstats.DeviceMemorySampler] = {}
        self._window: dict[tuple[str, str], int] = {}
        # job-name -> first window its reported bytes-in-use crossed the
        # HBM limit (the injected exhaustion the detector must beat).
        self.exhausted_at: dict[str, int] = {}

    def _sampler(self, leak: int = 0) -> devstats.DeviceMemorySampler:
        return devstats.DeviceMemorySampler(
            backend=devstats.FakeMemoryBackend(ripple_bytes=RIPPLE_BYTES),
            leak_bytes_per_window=leak,
        )

    def tick(self) -> None:
        for pod in self.api.list("pods"):
            meta = pod.get("metadata") or {}
            if ((pod.get("status") or {}).get("phase") or "Pending") != "Pending":
                continue
            status = dict(pod.get("status") or {})
            status["phase"] = "Running"
            pod["status"] = status
            self.api.update_status("pods", pod)
            job_name = (meta.get("labels") or {}).get(constants.JOB_NAME_LABEL)
            if job_name:
                self.recorder.record(
                    meta.get("namespace", ""), job_name, flightrecorder.POD,
                    reason="Running", pod=meta.get("name", ""),
                    phase="Running",
                )

    # -- LeakInjector surface -------------------------------------------

    def leak_worker(
        self, namespace: str, name: str, bytes_per_window: int
    ) -> bool:
        if bytes_per_window <= 0:
            return False
        try:
            self.api.get("pods", namespace, name)
        except ApiError:
            return False
        self._samplers[(namespace, name)] = self._sampler(bytes_per_window)
        return True

    # -- sample emission -------------------------------------------------

    def emit_window(self) -> int:
        """One device-memory window for every running worker: the
        worker's sampler (leaking or not) produces the record, which
        lands as the pod's device-memory annotation (the informer
        delivers it to the MemoryMatrix from there)."""
        emitted = 0
        for pod in sorted(
            self.api.list("pods"),
            key=lambda p: (p.get("metadata") or {}).get("name", ""),
        ):
            meta = pod.get("metadata") or {}
            if (pod.get("status") or {}).get("phase") != "Running":
                continue
            key = (meta.get("namespace", ""), meta.get("name", ""))
            window = self._window.get(key, 0)
            sampler = self._samplers.get(key)
            if sampler is None:
                sampler = self._samplers[key] = self._sampler()
            record = sampler.sample(window)
            limit = record["hbm_limit_bytes"]
            if limit > 0 and record["hbm_bytes_in_use"] >= limit:
                job_name = (meta.get("labels") or {}).get(
                    constants.JOB_NAME_LABEL
                )
                if job_name:
                    self.exhausted_at.setdefault(job_name, window)
            fresh = self.api.get("pods", key[0], key[1])
            annotations = fresh["metadata"].setdefault("annotations", {})
            annotations[constants.DEVICE_MEMORY_ANNOTATION] = json.dumps(
                record, sort_keys=True
            )
            self.api.update("pods", fresh)
            self._window[key] = window + 1
            emitted += 1
        return emitted


def memory_job(name: str) -> TPUJob:
    job = TPUJob()
    job.metadata.name = name
    job.metadata.namespace = "default"
    job.spec = TPUJobSpec(
        tpu=TPUSpec(accelerator_type="v5e-16"),
        replica_specs={
            REPLICA_TYPE_WORKER: ReplicaSpec(
                replicas=WORKERS_PER_JOB, template=dict(TEMPLATE)
            )
        },
    )
    job.spec.run_policy.clean_pod_policy = "None"
    return job


def _pressure_jobs(api: InMemoryAPIServer) -> set:
    flagged = set()
    for job in api.list("tpujobs", "default"):
        for cond in (job.get("status") or {}).get("conditions") or []:
            if (cond.get("type") == JOB_MEMORY_PRESSURE
                    and cond.get("status") == "True"):
                flagged.add(job["metadata"]["name"])
    return flagged


def run_arm(leak_bytes: int, jobs: int, seed: int, windows: int) -> dict:
    """Drive ``jobs`` gangs through ``windows`` device-memory windows
    with MemoryLeak chaos at one bytes/window increment (0 = control
    arm, chaos disarmed); return the per-arm result block of
    BENCH_MEMORY.json.  Same seed => bit-identical block (every number
    derives from sim time, window indices, or the seeded chaos RNG)."""
    random.Random(seed)  # reserved: the arm itself is jitter-free

    time_ = [NOW]
    clock = lambda: time_[0]  # noqa: E731
    raw = InMemoryAPIServer(clock=clock)
    registry = metrics.Registry()
    recorder = flightrecorder.FlightRecorder(
        capacity_per_job=1024, max_jobs=jobs + 8, clock=clock
    )
    matrix = devstats.MemoryMatrix(recorder, registry=registry, clock=clock)
    controller = TPUJobController(
        raw, registry=registry, clock=clock, flight_recorder=recorder,
        memory_matrix=matrix,
    )
    runner = MemoryRunner(raw, recorder)

    # One MemoryLeak victim per gang on average, budgeted to half the
    # fleet so the control population (never-leaked gangs) stays large
    # enough to measure false positives against.
    engine = chaos.ChaosEngine(chaos.ChaosPolicy(
        seed=seed,
        leak=(chaos.MemoryLeakChaos(
            leak_rate=1.0 / WORKERS_PER_JOB,
            bytes_per_window=leak_bytes,
            namespace="default",
            max_leak=max(1, jobs // 2),
        ),) if leak_bytes > 0 else (),
    ))
    injector = chaos.LeakInjector(engine, raw, runner, flight_recorder=recorder)

    controller.factory.set_resync_interval(1e9)
    for informer in controller.factory._informers.values():
        informer._clock = clock
    controller.queue._clock = clock
    controller.start()

    def pump():
        for _ in range(10):
            if controller.factory.pump_all() == 0:
                return

    def drain():
        for _ in range(jobs * 8 + 100):
            key, _ = controller.queue.get(timeout=0)
            if key is None:
                return
            try:
                controller.sync_handler(key)
            except ApiError:
                controller.queue.add_rate_limited(key)
            else:
                controller.queue.forget(key)
            finally:
                controller.queue.done(key)

    real_sleep = retry.sleep
    retry.sleep = lambda s: None
    wall0 = time.perf_counter()
    detected_at: dict[str, int] = {}
    try:
        for i in range(jobs):
            raw.create("tpujobs", memory_job(f"hbm-{i:04d}").to_dict())

        # Boot: pods created, flipped Running, jobs marked Running.
        for _ in range(4):
            time_[0] += 1.0
            pump()
            drain()
            runner.tick()
            pump()
            drain()

        # Chaos draws its victims once the fleet is up; every later tick
        # is a no-op re-draw against already-leaked or budget-exhausted
        # policies, matching the live soak's pacing loop.
        injector.tick()
        leaked = sorted(
            target.split(" ", 1)[1] for kind, target, _ in engine.timeline()
            if kind == chaos.MEM_LEAK
        )
        leak_jobs = sorted({
            name.split("/", 1)[1].rsplit("-worker-", 1)[0] for name in leaked
        })

        for window in range(windows):
            time_[0] += ROUND_S
            runner.emit_window()
            pump()
            drain()
            for name in _pressure_jobs(raw):
                detected_at.setdefault(name, window)
    finally:
        retry.sleep = real_sleep

    log(f"leak {leak_bytes}B/window: {len(leaked)} leaked worker(s) in "
        f"{len(leak_jobs)} gang(s), {time.perf_counter() - wall0:.2f}s wall")

    flagged_ever = set(detected_at)
    true_positives = flagged_ever & set(leak_jobs)
    false_positives = flagged_ever - set(leak_jobs)
    detections = sorted(detected_at[name] for name in true_positives)
    # Detection lead: windows between the condition flipping True and
    # the injected exhaustion — the checkpoint-and-resize budget the
    # detector actually delivered.
    leads = sorted(
        runner.exhausted_at[name] - detected_at[name]
        for name in true_positives
        if name in runner.exhausted_at
    )

    # Watermark fidelity from the matrix's joined state.
    peak_max = 0
    headroom_min = 1.0
    for name in sorted(set(leak_jobs) or {f"hbm-{i:04d}" for i in range(jobs)}):
        snap = matrix.job_snapshot("default", name)
        if snap is None:
            continue
        peak_max = max(peak_max, snap["hbm_peak_bytes"])
        headroom_min = min(headroom_min, snap["headroom_ratio"])

    return {
        "leak_bytes_per_window": leak_bytes,
        "jobs": jobs,
        "seed": seed,
        "workers_per_job": WORKERS_PER_JOB,
        "windows": windows,
        "sim_seconds": round(time_[0] - NOW, 6),
        "leaked_workers": len(leaked),
        "leaked_jobs": len(leak_jobs),
        "exhausted_jobs": len(runner.exhausted_at),
        "detected_jobs": len(true_positives),
        "false_positive_jobs": len(false_positives),
        "detection_windows": detections,
        "detection_lead_windows": leads,
        "detection_lead_min": min(leads) if leads else 0,
        "hbm_peak_bytes_max": peak_max,
        "headroom_ratio_min": round(headroom_min, 6),
    }


# ----------------------------------------------------------------------
# Artifact schema
# ----------------------------------------------------------------------

_RESULT_KEYS = {
    "leak_bytes_per_window": int,
    "jobs": int,
    "seed": int,
    "workers_per_job": int,
    "windows": int,
    "sim_seconds": float,
    "leaked_workers": int,
    "leaked_jobs": int,
    "exhausted_jobs": int,
    "detected_jobs": int,
    "false_positive_jobs": int,
    "detection_windows": list,
    "detection_lead_windows": list,
    "detection_lead_min": int,
    "hbm_peak_bytes_max": int,
    "headroom_ratio_min": float,
}


def check_schema(doc: dict) -> None:
    """Schema gate for BENCH_MEMORY.json; raises ValueError with a
    path-qualified message on the first violation.  Beyond shape it
    enforces the observatory's invariants: no arm carries false
    positives, the control arm never fires at all, and every leak-arm
    detection leads the injected exhaustion by at least the detector's
    pressure horizon."""
    if doc.get("schema_version") != SCHEMA_VERSION:
        raise ValueError(
            f"schema_version: expected {SCHEMA_VERSION}, "
            f"got {doc.get('schema_version')!r}"
        )
    if doc.get("benchmark") != "memory":
        raise ValueError(f"benchmark: got {doc.get('benchmark')!r}")
    detector = doc.get("detector")
    if not isinstance(detector, dict) or not isinstance(
        detector.get("pressure_horizon_windows"), int
    ):
        raise ValueError("detector.pressure_horizon_windows: missing")
    horizon = detector["pressure_horizon_windows"]
    results = doc.get("results")
    if not isinstance(results, list) or not results:
        raise ValueError("results: expected a non-empty list")
    for i, res in enumerate(results):
        where = f"results[{i}]"
        for key, type_ in _RESULT_KEYS.items():
            if key not in res:
                raise ValueError(f"{where}.{key}: missing")
            value = res[key]
            if type_ is float and isinstance(value, int):
                value = float(value)
            if not isinstance(value, type_):
                raise ValueError(
                    f"{where}.{key}: expected {type_.__name__}, "
                    f"got {type(res[key]).__name__}"
                )
        if res["false_positive_jobs"]:
            raise ValueError(
                f"{where}.false_positive_jobs: "
                f"{res['false_positive_jobs']} gang(s) flagged "
                f"MemoryPressure without a leaking worker"
            )
        if res["leak_bytes_per_window"] == 0:
            if res["leaked_workers"] or res["detected_jobs"]:
                raise ValueError(
                    f"{where}: control arm leaked or detected "
                    f"({res['leaked_workers']} worker(s), "
                    f"{res['detected_jobs']} detection(s))"
                )
        elif res["leaked_jobs"]:
            if res["detected_jobs"] < res["leaked_jobs"]:
                raise ValueError(
                    f"{where}.detected_jobs: {res['detected_jobs']}/"
                    f"{res['leaked_jobs']} leaking gang(s) detected"
                )
            if res["detection_lead_min"] < horizon:
                raise ValueError(
                    f"{where}.detection_lead_min: "
                    f"{res['detection_lead_min']} window(s) < pressure "
                    f"horizon {horizon}"
                )


def build_doc(leak_bytes: int, jobs: int, seed: int, windows: int) -> dict:
    results = []
    for arm in (0, leak_bytes):
        result = run_arm(arm, jobs, seed, windows)
        log(
            f"arm leak={arm}: detected {result['detected_jobs']}/"
            f"{result['leaked_jobs']} leaking gang(s), lead >= "
            f"{result['detection_lead_min']} window(s), "
            f"{result['false_positive_jobs']} false positive(s)"
        )
        results.append(result)
    return {
        "benchmark": "memory",
        "schema_version": SCHEMA_VERSION,
        "jobs": jobs,
        "seed": seed,
        "leak_bytes_per_window": leak_bytes,
        "detector": {
            "pressure_horizon_windows":
                devstats.DEFAULT_PRESSURE_HORIZON_WINDOWS,
            "trend_windows": devstats.DEFAULT_TREND_WINDOWS,
            "min_trend_windows": devstats.MIN_TREND_WINDOWS,
        },
        "results": results,
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="bench-memory",
        description="device-memory pressure-detection benchmark",
    )
    p.add_argument("--jobs", type=int, default=8)
    p.add_argument("--seed", type=int, default=42)
    p.add_argument("--windows", type=int, default=28,
                   help="device-memory windows to drive per arm")
    p.add_argument("--leak-bytes", type=int, default=LEAK_BYTES,
                   help="injected leak increment in bytes/window "
                        "(the control arm always runs leak-free)")
    p.add_argument("--lock-trace", action="store_true",
                   help="arm the lock-order race detector; any inversion "
                        "fails the bench")
    p.add_argument("--out", default="BENCH_MEMORY.json")
    args = p.parse_args(argv)

    logutil.configure(level=logutil.parse_level("warning"))
    if args.lock_trace and not locktrace.enabled():
        locktrace.enable()
    doc = build_doc(args.leak_bytes, args.jobs, args.seed, args.windows)

    ok = True
    try:
        check_schema(doc)
    except ValueError as exc:
        log(f"FAIL: {exc}")
        ok = False
    with open(args.out, "w") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")
    log(f"wrote {args.out}")

    leak_arms = [r for r in doc["results"] if r["leak_bytes_per_window"] > 0]
    print(json.dumps({
        "metric": "memory_pressure_lead_windows",
        "value": min(
            (r["detection_lead_min"] for r in leak_arms), default=0
        ),
        "unit": (
            f"windows of warning before HBM exhaustion at "
            f"{args.leak_bytes} B/window leak "
            f"({doc['jobs']} jobs, seed {doc['seed']})"
        ),
        "false_positives": sum(
            r["false_positive_jobs"] for r in doc["results"]
        ),
        "pressure_horizon_windows":
            doc["detector"]["pressure_horizon_windows"],
    }))

    for res in leak_arms:
        if res["leaked_jobs"] and res["exhausted_jobs"] < res["leaked_jobs"]:
            log(f"FAIL: leak arm: only {res['exhausted_jobs']}/"
                f"{res['leaked_jobs']} leaking gang(s) reached exhaustion "
                f"inside {res['windows']} windows — raise --windows")
            ok = False

    if args.lock_trace:
        tracer = locktrace.tracer()
        report = tracer.report() if tracer is not None else {"inversions": []}
        if report["inversions"]:
            for inv in report["inversions"]:
                log(f"FAIL: lock inversion {inv['forward']} vs "
                    f"{inv['reverse']}")
            ok = False
        else:
            log(f"lock-trace: {report.get('acquisitions', 0)} acquisitions, "
                f"0 inversions")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
