"""TPUJob API client.

Analog of the reference SDK's ``api_client.py`` + ``MPIJobClient``
usage pattern (/root/reference/sdk/python/v1/mpijob/api_client.py,
sdk/python/v1/tensorflow-mnist.py): a thin, typed CRUD surface over a
pluggable backend. The backend protocol is four dict-speaking methods,
so the same SDK code drives:

- the framework's in-memory apiserver (tests, local dev):
  ``operator_runtime_backend()``;
- a real cluster, by adapting the official kubernetes
  ``CustomObjectsApi`` (not imported here — zero hard dependencies).
"""

from __future__ import annotations

import time
from typing import Iterable, Optional, Protocol

from .models import V2beta1TPUJob, V2beta1TPUJobList

GROUP = "kubeflow.org"
VERSION = "v2beta1"
PLURAL = "tpujobs"


class TPUJobBackend(Protocol):
    """Dict-level CRUD for the tpujobs resource."""

    def create(self, namespace: str, body: dict) -> dict: ...

    def get(self, namespace: str, name: str) -> dict: ...

    def list(self, namespace: str) -> Iterable[dict]: ...

    def update(self, namespace: str, name: str, body: dict) -> dict: ...

    def delete(self, namespace: str, name: str) -> None: ...


class TPUJobApi:
    """Typed TPUJob operations over a ``TPUJobBackend``."""

    def __init__(self, backend: TPUJobBackend, namespace: str = "default"):
        self._backend = backend
        self.namespace = namespace

    def _ns(self, namespace: Optional[str]) -> str:
        return namespace or self.namespace

    def create(self, job: V2beta1TPUJob, namespace: Optional[str] = None) -> V2beta1TPUJob:
        return V2beta1TPUJob.from_dict(
            self._backend.create(self._ns(namespace), job.to_dict())
        )

    def get(self, name: str, namespace: Optional[str] = None) -> V2beta1TPUJob:
        return V2beta1TPUJob.from_dict(self._backend.get(self._ns(namespace), name))

    def list(self, namespace: Optional[str] = None) -> V2beta1TPUJobList:
        items = [
            V2beta1TPUJob.from_dict(d) for d in self._backend.list(self._ns(namespace))
        ]
        return V2beta1TPUJobList(
            api_version=f"{GROUP}/{VERSION}", kind="TPUJobList", items=items
        )

    def update(self, job: V2beta1TPUJob, namespace: Optional[str] = None) -> V2beta1TPUJob:
        return V2beta1TPUJob.from_dict(
            self._backend.update(self._ns(namespace), job.name, job.to_dict())
        )

    def patch_worker_replicas(
        self, name: str, replicas: int, namespace: Optional[str] = None
    ) -> V2beta1TPUJob:
        """Elastic resize: the SDK-side of the reference's
        'patch spec.mpiReplicaSpecs.Worker.replicas' flow (SURVEY.md §3.4)."""
        job = self.get(name, namespace)
        job.spec.tpu_replica_specs["Worker"].replicas = replicas
        return self.update(job, namespace)

    def delete(self, name: str, namespace: Optional[str] = None) -> None:
        self._backend.delete(self._ns(namespace), name)

    def wait_for_condition(
        self,
        name: str,
        cond_type: str,
        timeout: float = 300.0,
        poll_interval: float = 0.5,
        namespace: Optional[str] = None,
    ) -> V2beta1TPUJob:
        """Poll until ``cond_type`` is True (the SDK analog of the e2e
        suite's createJobAndWaitForCompletion,
        /root/reference/v2/test/e2e/mpi_job_test.go:213-237)."""
        deadline = time.monotonic() + timeout
        while True:
            job = self.get(name, namespace)
            if job.condition(cond_type) is not None:
                return job
            if job.failed and cond_type != "Failed":
                raise RuntimeError(f"TPUJob {name} failed while waiting for {cond_type}")
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"TPUJob {name} did not reach condition {cond_type} "
                    f"within {timeout:.0f}s"
                )
            time.sleep(poll_interval)


class _OperatorRuntimeBackend:
    """Adapter over the framework's in-memory apiserver (runtime.apiserver)."""

    def __init__(self, api_server):
        self._api = api_server

    def create(self, namespace: str, body: dict) -> dict:
        body.setdefault("metadata", {}).setdefault("namespace", namespace)
        return self._api.create(PLURAL, body)

    def get(self, namespace: str, name: str) -> dict:
        return self._api.get(PLURAL, namespace, name)

    def list(self, namespace: str):
        return self._api.list(PLURAL, namespace, None)

    def update(self, namespace: str, name: str, body: dict) -> dict:
        body.setdefault("metadata", {}).setdefault("namespace", namespace)
        return self._api.update(PLURAL, body)

    def delete(self, namespace: str, name: str) -> None:
        self._api.delete(PLURAL, namespace, name)


def operator_runtime_backend(api_server) -> TPUJobBackend:
    """Wrap an ``mpi_operator_tpu.runtime.apiserver.InMemoryAPIServer``
    (or anything with its surface) as an SDK backend."""
    return _OperatorRuntimeBackend(api_server)


def kube_backend(kubeconfig: Optional[str] = None,
                 context: Optional[str] = None) -> TPUJobBackend:
    """Real-cluster backend over the framework's stdlib REST client
    (mpi_operator_tpu.runtime.kube.KubeAPIServer): kubeconfig /
    in-cluster config, exec credential plugins, no extra dependencies.

    Reference analog: the generated kube-REST SDK client,
    /root/reference/sdk/python/v1/mpijob/api_client.py.
    """
    from mpi_operator_tpu.runtime.kube import KubeAPIServer, load_config

    return _OperatorRuntimeBackend(KubeAPIServer(load_config(
        kubeconfig, context
    )))


class _CustomObjectsBackend:
    """Adapter over the official kubernetes client's CustomObjectsApi,
    for users already standardized on that stack."""

    def __init__(self, custom_objects_api):
        self._api = custom_objects_api

    def create(self, namespace: str, body: dict) -> dict:
        body.setdefault("apiVersion", f"{GROUP}/{VERSION}")
        body.setdefault("kind", "TPUJob")
        return self._api.create_namespaced_custom_object(
            GROUP, VERSION, namespace, PLURAL, body
        )

    def get(self, namespace: str, name: str) -> dict:
        return self._api.get_namespaced_custom_object(
            GROUP, VERSION, namespace, PLURAL, name
        )

    def list(self, namespace: str):
        return self._api.list_namespaced_custom_object(
            GROUP, VERSION, namespace, PLURAL
        ).get("items", [])

    def update(self, namespace: str, name: str, body: dict) -> dict:
        body.setdefault("apiVersion", f"{GROUP}/{VERSION}")
        body.setdefault("kind", "TPUJob")
        return self._api.replace_namespaced_custom_object(
            GROUP, VERSION, namespace, PLURAL, name, body
        )

    def delete(self, namespace: str, name: str) -> None:
        self._api.delete_namespaced_custom_object(
            GROUP, VERSION, namespace, PLURAL, name
        )


def custom_objects_backend(custom_objects_api=None) -> TPUJobBackend:
    """SDK backend over the official ``kubernetes`` package's
    CustomObjectsApi (optional dependency — imported only here)."""
    if custom_objects_api is None:
        import kubernetes  # optional dependency

        kubernetes.config.load_config()
        custom_objects_api = kubernetes.client.CustomObjectsApi()
    return _CustomObjectsBackend(custom_objects_api)
