"""Swagger-style model classes for the TPUJob API.

Conventions follow the reference's generated SDK models
(/root/reference/sdk/python/v1/mpijob/models/v1_mpi_job.py and siblings):
each class declares ``openapi_types`` and ``attribute_map`` (snake_case
attribute → camelCase wire name), and provides ``to_dict`` /
``from_dict`` that round-trip the wire format. Unknown wire fields are
preserved through a round trip so the SDK never strips server-added
fields it does not know about.
"""

from __future__ import annotations

import copy
from typing import Any, Optional


class _Model:
    """Base: wire <-> attribute mapping driven by ``attribute_map``.

    ``openapi_types`` values are either a model class (nested object),
    ``list[Model]``-style tuples ``("list", Model)``, ``("dict", Model)``,
    or a plain python type; plain values pass through untouched.
    """

    openapi_types: dict[str, Any] = {}
    attribute_map: dict[str, str] = {}

    def __init__(self, **kwargs):
        self._extra: dict[str, Any] = {}
        for attr in self.openapi_types:
            setattr(self, attr, kwargs.pop(attr, None))
        if kwargs:
            raise TypeError(
                f"{type(self).__name__} got unexpected arguments {sorted(kwargs)}"
            )

    @staticmethod
    def _serialize(value):
        if isinstance(value, _Model):
            return value.to_dict()
        if isinstance(value, list):
            return [_Model._serialize(v) for v in value]
        if isinstance(value, dict):
            return {k: _Model._serialize(v) for k, v in value.items()}
        return value

    def to_dict(self) -> dict:
        out: dict[str, Any] = {}
        for attr, wire in self.attribute_map.items():
            value = getattr(self, attr)
            if value is None:
                continue
            out[wire] = self._serialize(value)
        for wire, value in self._extra.items():
            out.setdefault(wire, copy.deepcopy(value))
        return out

    @classmethod
    def _deserialize(cls, typ, value):
        if value is None:
            return None
        if isinstance(typ, tuple):
            kind, item = typ
            if kind == "list":
                return [cls._deserialize(item, v) for v in value]
            return {k: cls._deserialize(item, v) for k, v in value.items()}
        if isinstance(typ, type) and issubclass(typ, _Model):
            return typ.from_dict(value)
        return copy.deepcopy(value)

    @classmethod
    def from_dict(cls, d: Optional[dict]):
        d = dict(d or {})
        kwargs = {}
        for attr, wire in cls.attribute_map.items():
            if wire in d:
                kwargs[attr] = cls._deserialize(cls.openapi_types[attr], d.pop(wire))
        obj = cls(**kwargs)
        obj._extra = copy.deepcopy(d)  # preserve unknown server fields
        return obj

    def __eq__(self, other):
        return type(self) is type(other) and self.to_dict() == other.to_dict()

    def __repr__(self):
        return f"{type(self).__name__}({self.to_dict()!r})"


class V2beta1SchedulingPolicy(_Model):
    openapi_types = {
        "min_available": int,
        "queue": str,
        "priority_class": str,
    }
    attribute_map = {
        "min_available": "minAvailable",
        "queue": "queue",
        "priority_class": "priorityClass",
    }


class V2beta1RunPolicy(_Model):
    openapi_types = {
        "clean_pod_policy": str,
        "ttl_seconds_after_finished": int,
        "active_deadline_seconds": int,
        "backoff_limit": int,
        "scheduling_policy": V2beta1SchedulingPolicy,
        "suspend": bool,
    }
    attribute_map = {
        "clean_pod_policy": "cleanPodPolicy",
        "ttl_seconds_after_finished": "ttlSecondsAfterFinished",
        "active_deadline_seconds": "activeDeadlineSeconds",
        "backoff_limit": "backoffLimit",
        "scheduling_policy": "schedulingPolicy",
        "suspend": "suspend",
    }


class V2beta1TPUSpec(_Model):
    openapi_types = {
        "accelerator_type": str,
        "topology": str,
        "num_slices": int,
        "runtime_version": str,
    }
    attribute_map = {
        "accelerator_type": "acceleratorType",
        "topology": "topology",
        "num_slices": "numSlices",
        "runtime_version": "runtimeVersion",
    }


class V2beta1JAXDistributionSpec(_Model):
    openapi_types = {
        "coordinator_port": int,
        "heartbeat_timeout_seconds": int,
    }
    attribute_map = {
        "coordinator_port": "coordinatorPort",
        "heartbeat_timeout_seconds": "heartbeatTimeoutSeconds",
    }


class V2beta1ReplicaSpec(_Model):
    openapi_types = {
        "replicas": int,
        "restart_policy": str,
        "template": dict,
    }
    attribute_map = {
        "replicas": "replicas",
        "restart_policy": "restartPolicy",
        "template": "template",
    }


class V2beta1TPUJobSpec(_Model):
    openapi_types = {
        "tpu": V2beta1TPUSpec,
        "jax_distribution": V2beta1JAXDistributionSpec,
        "run_policy": V2beta1RunPolicy,
        "tpu_replica_specs": ("dict", V2beta1ReplicaSpec),
    }
    attribute_map = {
        "tpu": "tpu",
        "jax_distribution": "jaxDistribution",
        "run_policy": "runPolicy",
        "tpu_replica_specs": "tpuReplicaSpecs",
    }


class V2beta1JobCondition(_Model):
    openapi_types = {
        "type": str,
        "status": str,
        "reason": str,
        "message": str,
        "last_update_time": float,
        "last_transition_time": float,
    }
    attribute_map = {
        "type": "type",
        "status": "status",
        "reason": "reason",
        "message": "message",
        "last_update_time": "lastUpdateTime",
        "last_transition_time": "lastTransitionTime",
    }


class V2beta1ReplicaStatus(_Model):
    openapi_types = {
        "active": int,
        "succeeded": int,
        "failed": int,
        "restarts": int,
    }
    attribute_map = {
        "active": "active",
        "succeeded": "succeeded",
        "failed": "failed",
        "restarts": "restarts",
    }


class V2beta1JobStatus(_Model):
    openapi_types = {
        "conditions": ("list", V2beta1JobCondition),
        "replica_statuses": ("dict", V2beta1ReplicaStatus),
        "start_time": float,
        "completion_time": float,
        "last_reconcile_time": float,
    }
    attribute_map = {
        "conditions": "conditions",
        "replica_statuses": "replicaStatuses",
        "start_time": "startTime",
        "completion_time": "completionTime",
        "last_reconcile_time": "lastReconcileTime",
    }


class V2beta1TPUJob(_Model):
    openapi_types = {
        "api_version": str,
        "kind": str,
        "metadata": dict,
        "spec": V2beta1TPUJobSpec,
        "status": V2beta1JobStatus,
    }
    attribute_map = {
        "api_version": "apiVersion",
        "kind": "kind",
        "metadata": "metadata",
        "spec": "spec",
        "status": "status",
    }

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        if self.api_version is None:
            self.api_version = "kubeflow.org/v2beta1"
        if self.kind is None:
            self.kind = "TPUJob"

    @property
    def name(self) -> str:
        return (self.metadata or {}).get("name", "")

    @property
    def namespace(self) -> str:
        return (self.metadata or {}).get("namespace", "")

    def condition(self, cond_type: str) -> Optional[V2beta1JobCondition]:
        for c in (self.status.conditions if self.status else None) or []:
            if c.type == cond_type and c.status == "True":
                return c
        return None

    @property
    def succeeded(self) -> bool:
        return self.condition("Succeeded") is not None

    @property
    def failed(self) -> bool:
        return self.condition("Failed") is not None


class V2beta1TPUJobList(_Model):
    openapi_types = {
        "api_version": str,
        "kind": str,
        "metadata": dict,
        "items": ("list", V2beta1TPUJob),
    }
    attribute_map = {
        "api_version": "apiVersion",
        "kind": "kind",
        "metadata": "metadata",
        "items": "items",
    }
