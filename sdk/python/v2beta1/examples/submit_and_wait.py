"""Submit a TPUJob to a real cluster and wait for it to finish.

The SDK analog of the reference's usage example
(/root/reference/sdk/python/v1/tensorflow-mnist.py), pointed at the
real-cluster REST backend instead of a fake:

    python submit_and_wait.py --kubeconfig ~/.kube/config \
        --namespace training --accelerator v5e-16 --workers 4

Works against any apiserver the kubeconfig reaches — including the
framework's own envtest-style HTTP frontend
(mpi_operator_tpu.runtime.httpserver) for local rehearsal.
"""

from __future__ import annotations

import argparse
import sys

from tpujob import (
    TPUJobApi,
    V2beta1ReplicaSpec,
    V2beta1TPUJob,
    V2beta1TPUJobSpec,
    V2beta1TPUSpec,
    kube_backend,
)


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--name", default="sdk-train")
    p.add_argument("--namespace", default="default")
    p.add_argument("--kubeconfig", default=None)
    p.add_argument("--accelerator", default="v5e-16")
    p.add_argument("--workers", type=int, default=4)
    p.add_argument("--image", default="tpu-job-operator/base:latest")
    p.add_argument("--model", default="bert-base")
    p.add_argument("--timeout", type=float, default=3600.0)
    args = p.parse_args()

    api = TPUJobApi(kube_backend(args.kubeconfig), namespace=args.namespace)
    job = V2beta1TPUJob(
        metadata={"name": args.name},
        spec=V2beta1TPUJobSpec(
            tpu=V2beta1TPUSpec(accelerator_type=args.accelerator),
            tpu_replica_specs={
                "Worker": V2beta1ReplicaSpec(
                    replicas=args.workers,
                    template={"spec": {"containers": [{
                        "name": "main",
                        "image": args.image,
                        "command": [
                            "python", "-m", "mpi_operator_tpu.cmd.train",
                            f"--model={args.model}",
                        ],
                    }]}},
                ),
            },
        ),
    )
    created = api.create(job)
    print(f"created TPUJob {args.namespace}/{created.name}")

    done = api.wait_for_condition(args.name, "Succeeded",
                                  timeout=args.timeout)
    cond = done.condition("Succeeded")
    print(f"TPUJob {args.name}: Succeeded ({cond.reason})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
