"""SDK usage example: submit a JAX ResNet TPUJob and wait for completion.

Analog of the reference SDK's usage example
(/root/reference/sdk/python/v1/tensorflow-mnist.py), rebuilt for the
TPUJob API: no launcher, no mpirun — every worker runs the same SPMD
entrypoint and rendezvouses through jax.distributed.
"""

import pathlib
import sys

_HERE = pathlib.Path(__file__).resolve()
sys.path.insert(0, str(_HERE.parent.parent))  # the SDK package
sys.path.insert(0, str(_HERE.parents[4]))  # repo root, for the local demo backend

from tpujob import (  # noqa: E402
    TPUJobApi,
    V2beta1ReplicaSpec,
    V2beta1TPUJob,
    V2beta1TPUJobSpec,
    V2beta1TPUSpec,
    operator_runtime_backend,
)


def build_job(name: str = "jax-resnet") -> V2beta1TPUJob:
    worker = V2beta1ReplicaSpec(
        replicas=4,
        restart_policy="Never",
        template={
            "spec": {
                "containers": [
                    {
                        "name": "worker",
                        "image": "my-registry/jax-resnet:latest",
                        "command": ["python", "train_resnet.py"],
                    }
                ]
            }
        },
    )
    return V2beta1TPUJob(
        metadata={"name": name},
        spec=V2beta1TPUJobSpec(
            tpu=V2beta1TPUSpec(accelerator_type="v5e-16", topology="4x4"),
            tpu_replica_specs={"Worker": worker},
        ),
    )


def main() -> int:
    # Local demo: drive the framework's in-memory backend. Against a real
    # cluster, supply a backend adapting kubernetes CustomObjectsApi.
    from mpi_operator_tpu.runtime.apiserver import InMemoryAPIServer

    api = TPUJobApi(operator_runtime_backend(InMemoryAPIServer()))
    job = api.create(build_job())
    print(f"created TPUJob {job.name} ({job.spec.tpu.accelerator_type})")
    listed = api.list()
    print(f"jobs in namespace: {[j.name for j in listed.items]}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
