"""Packaging for the TPUJob SDK (reference analog:
/root/reference/sdk/python/v1/setup.py)."""

from setuptools import find_packages, setup

setup(
    name="tpujob",
    version="0.1.0",
    description="Python SDK for the TPUJob API (kubeflow.org/v2beta1)",
    packages=find_packages(include=["tpujob", "tpujob.*"]),
    python_requires=">=3.10",
    install_requires=[],  # dict-speaking backends keep the SDK dependency-free
)
