"""Gang rendezvous barrier: coordinator-readiness gating.

jax.distributed.initialize wedges when workers dial a coordinator that is
not up yet (SURVEY.md §7 hard part 2); the reference absorbed the same
race with sshd + ``ConnectionAttempts=10`` retry loops
(/root/reference/v2/pkg/controller/mpi_job_controller.go:188-190). Our
replacement is an explicit pre-rendezvous barrier: worker 0 serves,
every rank (0 included) checks in, and nobody calls
``jax.distributed.initialize`` until the whole gang is present.

Two interchangeable engines, same wire protocol
(``"TPUB" u32(rank)`` in, ``"GO!!"`` out):

- **native**: ``native/barrier.cpp`` → ``libtpujob_barrier.so`` via
  ctypes — poll-based C++, no Python threads on the serve path (built by
  ``make -C native``);
- **pure Python**: socket/threading fallback used automatically when the
  shared library is absent.
"""

from __future__ import annotations

import ctypes
import ctypes.util
import os
import pathlib
import socket
import struct
import threading
import time
from typing import Optional

from ..utils import trace
from ..utils.logging import get_logger

log = get_logger("launcher.barrier")

MAGIC = b"TPUB"
GO = b"GO!!"
ENV_NATIVE_LIB = "TPUJOB_BARRIER_LIB"

_REPO_NATIVE = pathlib.Path(__file__).resolve().parents[2] / "native"
_SEARCH_PATHS = (
    os.environ.get(ENV_NATIVE_LIB, ""),
    str(_REPO_NATIVE / "libtpujob_barrier.so"),
    "libtpujob_barrier.so",
)


def _load_native() -> Optional[ctypes.CDLL]:
    for path in _SEARCH_PATHS:
        if not path:
            continue
        try:
            lib = ctypes.CDLL(path)
        except OSError:
            continue
        lib.tpujob_barrier_serve.argtypes = [ctypes.c_int] * 3
        lib.tpujob_barrier_serve.restype = ctypes.c_int
        lib.tpujob_barrier_wait.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ]
        lib.tpujob_barrier_wait.restype = ctypes.c_int
        return lib
    return None


_native = _load_native()


def native_available() -> bool:
    return _native is not None


# ---------------------------------------------------------------------------
# Pure-Python engine (wire-compatible with barrier.cpp)
# ---------------------------------------------------------------------------


_HEADER_TIMEOUT_S = 3.0  # per-connection budget for the 8-byte header


def _py_serve(port: int, world_size: int, timeout_ms: int) -> int:
    import selectors

    deadline = time.monotonic() + timeout_ms / 1000.0
    # conn per rank; a re-check-in (client retry after a dropped connection)
    # replaces the stale conn so the retrying rank still gets its GO.
    conn_by_rank: dict[int, socket.socket] = {}
    # Half-read headers get their own short deadline: a silent connection
    # (port scanner, health probe) is dropped alone instead of serializing
    # the accept loop until the gang deadline (same design as
    # barrier.cpp's PendingConn poll set).
    pending: dict[socket.socket, tuple[bytes, float]] = {}
    sel = selectors.DefaultSelector()
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as srv:
            srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            srv.bind(("0.0.0.0", port))
            srv.listen(world_size + 8)
            srv.setblocking(False)
            sel.register(srv, selectors.EVENT_READ)
            while len(conn_by_rank) < world_size:
                now = time.monotonic()
                if now >= deadline:
                    return -1
                for conn, (buf, conn_deadline) in list(pending.items()):
                    if now >= conn_deadline:
                        sel.unregister(conn)
                        del pending[conn]
                        conn.close()
                for key, _ in sel.select(timeout=0.2):
                    sock = key.fileobj
                    if sock is srv:
                        while True:
                            try:
                                conn, _ = srv.accept()
                            except (BlockingIOError, InterruptedError,
                                    ConnectionAbortedError):
                                break  # drained for now
                            # Hard errors (EMFILE under a flood) propagate
                            # to the outer handler -> rc=-1, not a silent
                            # spin to the gang deadline.
                            conn.setblocking(False)
                            pending[conn] = (
                                b"", time.monotonic() + _HEADER_TIMEOUT_S
                            )
                            sel.register(conn, selectors.EVENT_READ)
                        continue
                    buf, conn_deadline = pending[sock]
                    try:
                        chunk = sock.recv(8 - len(buf))
                    except BlockingIOError:
                        continue
                    except OSError:
                        chunk = b""
                    if not chunk:  # closed before full header
                        sel.unregister(sock)
                        del pending[sock]
                        sock.close()
                        continue
                    buf += chunk
                    if len(buf) < 8:
                        pending[sock] = (buf, conn_deadline)
                        continue
                    sel.unregister(sock)
                    del pending[sock]
                    if buf[:4] != MAGIC:
                        sock.close()
                        continue
                    (rank,) = struct.unpack("<I", buf[4:])
                    if rank >= world_size:
                        sock.close()
                        continue
                    old = conn_by_rank.pop(rank, None)
                    if old is not None:
                        old.close()
                    conn_by_rank[rank] = sock
            for conn in conn_by_rank.values():
                try:
                    # Back to blocking for the 4-byte release write.
                    conn.settimeout(max(deadline - time.monotonic(), 0.01))
                    conn.sendall(GO)
                except OSError:
                    pass  # rank died post-check-in; jax.distributed will see it
            return 0
    except OSError:
        return -1
    finally:
        sel.close()
        for conn in list(conn_by_rank.values()) + list(pending):
            try:
                conn.close()
            except OSError:
                pass


def _py_wait(host: str, port: int, rank: int, timeout_ms: int) -> int:
    deadline = time.monotonic() + timeout_ms / 1000.0
    while time.monotonic() < deadline:
        try:
            with socket.create_connection(
                (host, port), timeout=max(deadline - time.monotonic(), 0.01)
            ) as conn:
                conn.sendall(MAGIC + struct.pack("<I", rank))
                conn.settimeout(max(deadline - time.monotonic(), 0.01))
                go = b""
                while len(go) < 4:
                    chunk = conn.recv(4 - len(go))
                    if not chunk:
                        break
                    go += chunk
                if go == GO:
                    return 0
        except OSError:
            pass
        time.sleep(0.2)
    return -1


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------


def serve(port: int, world_size: int, timeout_s: float = 300.0) -> int:
    """Serve one barrier round (blocking). 0 on success."""
    timeout_ms = int(timeout_s * 1000)
    if _native is not None:
        return _native.tpujob_barrier_serve(port, world_size, timeout_ms)
    return _py_serve(port, world_size, timeout_ms)


def wait(host: str, port: int, rank: int, timeout_s: float = 300.0) -> int:
    """Check in and block until the gang is complete. 0 on success."""
    timeout_ms = int(timeout_s * 1000)
    if _native is not None:
        return _native.tpujob_barrier_wait(
            host.encode(), port, rank, timeout_ms
        )
    return _py_wait(host, port, rank, timeout_ms)


def gang_barrier(
    *,
    coordinator_host: str,
    port: int,
    rank: int,
    world_size: int,
    timeout_s: float = 300.0,
) -> None:
    """Full gang readiness barrier: rank 0 serves (in a thread) and also
    checks in; everyone returns only when all ranks arrived.

    Raises TimeoutError if the gang does not assemble in time.
    """
    engine = "native" if _native is not None else "python"
    with trace.span(
        "launcher.gang_barrier", rank=rank, world_size=world_size, engine=engine
    ):
        server: Optional[threading.Thread] = None
        serve_rc: list[int] = [0]
        if rank == 0:
            def _run():
                serve_rc[0] = serve(port, world_size, timeout_s)

            server = threading.Thread(
                target=_run, daemon=True, name="tpujob-barrier"
            )
            server.start()
            host = "127.0.0.1"  # rank 0 dials its own server locally
        else:
            host = coordinator_host

        log.info(
            "gang barrier (%s): rank %d/%d via %s:%d", engine, rank,
            world_size, host, port,
        )
        rc = wait(host, port, rank, timeout_s)
        if server is not None:
            server.join(timeout=timeout_s)
            if serve_rc[0] != 0:
                raise TimeoutError(
                    f"barrier server failed (rc={serve_rc[0]}): "
                    f"{world_size - 1} peer(s) missing"
                )
        if rc != 0:
            raise TimeoutError(
                f"rank {rank} gang barrier timed out after {timeout_s:.0f}s "
                f"(rc={rc})"
            )
