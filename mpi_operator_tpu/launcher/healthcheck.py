"""Collective smoke test — the default worker command.

Reference analog: the default worker command `/usr/sbin/sshd -De`
(/root/reference/v2/pkg/controller/mpi_job_controller.go:1272-1274) and the
pi MPI_Reduce e2e payload (/root/reference/examples/v2beta1/pi/pi.cc:19-50)
rolled into one TPU-native program: join the jax.distributed world, run a
real cross-host allgather, verify every rank contributed, exit 0.

Failure taxonomy: the common startup races each get a distinct exit code
(below) so a TPUJob ``runPolicy.podFailurePolicy`` rule can match them —
e.g. Restart on DNS-not-ready/connection-refused (the coordinator pod is
simply not up yet) while a genuine collective failure still burns the
backoff budget.  Every preflight probe runs under its own timeout
(``TPUJOB_HEALTHCHECK_PROBE_TIMEOUT_S``, default 5s) so a black-holed
dial cannot eat the whole barrier budget.

Run as ``python -m mpi_operator_tpu.launcher.healthcheck``.
"""

from __future__ import annotations

import os
import socket
import sys

from ..api.v2beta1 import constants
from ..utils.logging import emit_json, get_logger
from .bootstrap import RendezvousConfig, initialize

log = get_logger("launcher.healthcheck")

# Exit codes (stable contract for podFailurePolicy onExitCodes rules).
EXIT_OK = 0
EXIT_UNHEALTHY = 1  # world assembled but the collective check failed
EXIT_DNS_NOT_READY = 12  # coordinator hostname does not resolve yet
EXIT_CONNECTION_REFUSED = 13  # resolves, but nothing is listening yet
EXIT_BARRIER_TIMEOUT = 14  # gang never fully assembled

ENV_PROBE_TIMEOUT = "TPUJOB_HEALTHCHECK_PROBE_TIMEOUT_S"
DEFAULT_PROBE_TIMEOUT_S = 5.0


class ProbeFailure(RuntimeError):
    """A preflight probe failed; carries the exit code to die with."""

    def __init__(self, exit_code: int, message: str):
        super().__init__(message)
        self.exit_code = exit_code


def probe_rendezvous(
    cfg: RendezvousConfig, *, timeout_s: float = DEFAULT_PROBE_TIMEOUT_S
) -> None:
    """Preflight the rendezvous path, one bounded probe at a time.

    1. Resolve the coordinator hostname (headless-service DNS records
       only appear once the coordinator pod has an IP) — failure is
       ``EXIT_DNS_NOT_READY``.
    2. Non-coordinator ranks dial the barrier side port (coordinator
       port + 1) — a refused/unreachable dial is
       ``EXIT_CONNECTION_REFUSED``.  Rank 0 skips this: it hosts the
       barrier itself.

    Each probe gets its own ``timeout_s`` budget; raises ProbeFailure.
    """
    if not cfg.is_distributed or not cfg.coordinator_address:
        return
    host, _, port_str = cfg.coordinator_address.partition(":")
    port = int(port_str or constants.DEFAULT_COORDINATOR_PORT)
    try:
        infos = socket.getaddrinfo(host, port, type=socket.SOCK_STREAM)
    except socket.gaierror as e:
        raise ProbeFailure(
            EXIT_DNS_NOT_READY,
            f"coordinator {host!r} does not resolve yet: {e}",
        )
    if not infos:
        raise ProbeFailure(
            EXIT_DNS_NOT_READY, f"coordinator {host!r} resolved to nothing"
        )
    if cfg.is_coordinator:
        return
    barrier_port = port + 1
    try:
        with socket.create_connection((host, barrier_port), timeout=timeout_s):
            pass  # reachable; the barrier server drops silent probes
    except OSError as e:
        raise ProbeFailure(
            EXIT_CONNECTION_REFUSED,
            f"barrier port {host}:{barrier_port} not accepting: {e}",
        )


def run_healthcheck(
    config: RendezvousConfig | None = None,
    *,
    probe_timeout_s: float = DEFAULT_PROBE_TIMEOUT_S,
    barrier_timeout_s: float = 300.0,
) -> dict:
    cfg = config or RendezvousConfig.from_env()
    probe_rendezvous(cfg, timeout_s=probe_timeout_s)
    try:
        cfg = initialize(
            cfg, initialization_timeout_seconds=int(barrier_timeout_s)
        )
    except TimeoutError as e:
        raise ProbeFailure(EXIT_BARRIER_TIMEOUT, str(e))
    import jax
    import numpy as np

    device_count = jax.device_count()
    local_device_count = jax.local_device_count()

    if cfg.is_distributed:
        from jax.experimental import multihost_utils

        gathered = multihost_utils.process_allgather(np.array([cfg.process_id]))
        seen = sorted(int(x) for x in np.asarray(gathered).ravel())
        ok = seen == list(range(cfg.num_processes))
    else:
        # Single process: a local all-device reduction still proves the
        # chips answer.
        import jax.numpy as jnp

        ok = bool(jnp.ones((local_device_count,)).sum() == local_device_count)

    return {
        "ok": ok,
        "process_id": cfg.process_id,
        "num_processes": cfg.num_processes,
        "device_count": device_count,
        "local_device_count": local_device_count,
    }


def main() -> int:
    try:
        probe_timeout_s = float(
            os.environ.get(ENV_PROBE_TIMEOUT, DEFAULT_PROBE_TIMEOUT_S)
        )
    except ValueError:
        probe_timeout_s = DEFAULT_PROBE_TIMEOUT_S
    try:
        result = run_healthcheck(probe_timeout_s=probe_timeout_s)
    except ProbeFailure as e:
        log.warning("healthcheck probe failed: %s", e)
        emit_json(
            {"ok": False, "error": str(e), "exit_code": e.exit_code},
            stream=sys.stdout,
        )
        return e.exit_code
    # Machine-readable result on stdout (one JSON line, sorted keys) via
    # the shared structured-log writer, so consumers keep a stable shape.
    emit_json(result, stream=sys.stdout)
    return EXIT_OK if result["ok"] else EXIT_UNHEALTHY


if __name__ == "__main__":
    sys.exit(main())
