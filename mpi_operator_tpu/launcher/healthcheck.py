"""Collective smoke test — the default worker command.

Reference analog: the default worker command `/usr/sbin/sshd -De`
(/root/reference/v2/pkg/controller/mpi_job_controller.go:1272-1274) and the
pi MPI_Reduce e2e payload (/root/reference/examples/v2beta1/pi/pi.cc:19-50)
rolled into one TPU-native program: join the jax.distributed world, run a
real cross-host allgather, verify every rank contributed, exit 0.

Run as ``python -m mpi_operator_tpu.launcher.healthcheck``.
"""

from __future__ import annotations

import sys

from ..utils.logging import emit_json
from .bootstrap import RendezvousConfig, initialize


def run_healthcheck(config: RendezvousConfig | None = None) -> dict:
    cfg = initialize(config)
    import jax
    import numpy as np

    device_count = jax.device_count()
    local_device_count = jax.local_device_count()

    if cfg.is_distributed:
        from jax.experimental import multihost_utils

        gathered = multihost_utils.process_allgather(np.array([cfg.process_id]))
        seen = sorted(int(x) for x in np.asarray(gathered).ravel())
        ok = seen == list(range(cfg.num_processes))
    else:
        # Single process: a local all-device reduction still proves the
        # chips answer.
        import jax.numpy as jnp

        ok = bool(jnp.ones((local_device_count,)).sum() == local_device_count)

    return {
        "ok": ok,
        "process_id": cfg.process_id,
        "num_processes": cfg.num_processes,
        "device_count": device_count,
        "local_device_count": local_device_count,
    }


def main() -> int:
    result = run_healthcheck()
    # Machine-readable result on stdout (one JSON line, sorted keys) via
    # the shared structured-log writer, so consumers keep a stable shape.
    emit_json(result, stream=sys.stdout)
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
