"""Hot-spare parking loop — the standby worker command.

A spare pod (``spec.tpu.hotSpares``) must pay the expensive part of worker
startup *before* it is needed: get scheduled, pull the image, warm the
Python runtime. What it must NOT do is join the collective barrier — a
parked spare is invisible to the training gang. So the command is simply:
announce readiness as one JSON line, then sleep until told to stop.

Termination contract: promotion deletes the spare pod, which delivers
SIGTERM; the loop exits 0 immediately (there is no state to drain). Exit 0
matters — a podFailurePolicy must never classify a promoted-away spare as
a worker failure.

Run as ``python -m mpi_operator_tpu.launcher.park``.
"""

from __future__ import annotations

import os
import signal
import sys
import threading

from ..api.v2beta1 import constants
from ..utils.logging import emit_json, get_logger

log = get_logger("launcher.park")

EXIT_OK = 0

ENV_PARK_TIMEOUT = "TPUJOB_PARK_TIMEOUT_S"  # mostly for tests; default: forever
_POLL_INTERVAL_S = 1.0


def main() -> int:
    stop = threading.Event()

    def _on_term(signum: int, frame: object) -> None:
        log.info("park: received signal %d, unparking", signum)
        stop.set()

    signal.signal(signal.SIGTERM, _on_term)
    signal.signal(signal.SIGINT, _on_term)

    emit_json(
        {
            "parked": True,
            "job_name": os.environ.get(constants.ENV_JOB_NAME, ""),
            "job_namespace": os.environ.get(constants.ENV_JOB_NAMESPACE, ""),
            "pid": os.getpid(),
        },
        stream=sys.stdout,
    )

    timeout_raw = os.environ.get(ENV_PARK_TIMEOUT, "")
    deadline: float | None
    try:
        deadline = float(timeout_raw) if timeout_raw else None
    except ValueError:
        deadline = None

    waited = 0.0
    while not stop.is_set():
        if deadline is not None and waited >= deadline:
            break
        stop.wait(_POLL_INTERVAL_S)
        waited += _POLL_INTERVAL_S
    return EXIT_OK


if __name__ == "__main__":
    sys.exit(main())
