"""Rendezvous bootstrap for TPUJob workers.

The controller injects the rendezvous env (builders._worker_env); this
module consumes it.  The equivalent moment in the reference is `mpirun`
reading the hostfile and ssh-ing into workers
(/root/reference/v2/pkg/controller/mpi_job_controller.go:177-191) — here
every worker calls ``initialize()`` itself and the JAX distributed runtime
forms the world.
"""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass
from typing import Mapping, Optional

from ..api.v2beta1 import constants

log = logging.getLogger(__name__)


@dataclass
class RendezvousConfig:
    coordinator_address: str = ""
    num_processes: int = 1
    process_id: int = 0
    worker_id: int = 0
    worker_hostnames: tuple[str, ...] = ()
    accelerator_type: str = ""
    topology: str = ""
    chips_per_host: int = 0
    num_slices: int = 1
    slice_id: int = 0
    job_name: str = ""
    job_namespace: str = ""

    @classmethod
    def from_env(cls, environ: Optional[Mapping[str, str]] = None) -> "RendezvousConfig":
        env = os.environ if environ is None else environ

        def _int(name: str, default: int) -> int:
            try:
                return int(env.get(name, default))
            except (TypeError, ValueError):
                return default

        hostnames = tuple(
            h for h in env.get(constants.ENV_TPU_WORKER_HOSTNAMES, "").split(",") if h
        )
        return cls(
            coordinator_address=env.get(constants.ENV_COORDINATOR_ADDRESS, ""),
            num_processes=_int(constants.ENV_NUM_PROCESSES, 1),
            process_id=_int(constants.ENV_PROCESS_ID, 0),
            worker_id=_int(constants.ENV_TPU_WORKER_ID, 0),
            worker_hostnames=hostnames,
            accelerator_type=env.get(constants.ENV_TPU_ACCELERATOR_TYPE, ""),
            topology=env.get(constants.ENV_TPU_TOPOLOGY, ""),
            chips_per_host=_int(constants.ENV_TPU_CHIPS_PER_HOST, 0),
            num_slices=_int(constants.ENV_NUM_SLICES, 1),
            slice_id=_int(constants.ENV_SLICE_ID, 0),
            job_name=env.get(constants.ENV_JOB_NAME, ""),
            job_namespace=env.get(constants.ENV_JOB_NAMESPACE, ""),
        )

    @property
    def is_distributed(self) -> bool:
        return self.num_processes > 1

    @property
    def is_coordinator(self) -> bool:
        return self.process_id == 0


_initialized = False


def initialize(
    config: Optional[RendezvousConfig] = None,
    *,
    initialization_timeout_seconds: int = 300,
    readiness_barrier: bool = True,
) -> RendezvousConfig:
    """Join the job's jax.distributed world (idempotent).

    Single-process jobs (num_processes == 1) skip distributed init
    entirely, so the same worker image runs unchanged on one host.

    ``readiness_barrier`` first assembles the gang on a side port
    (coordinator port + 1) so no rank dials jax.distributed before the
    coordinator process exists — the SSH-retry analog (launcher.barrier).
    """
    global _initialized
    cfg = config or RendezvousConfig.from_env()
    if not cfg.is_distributed:
        log.info("single-process TPUJob; skipping jax.distributed.initialize")
        return cfg
    if _initialized:
        return cfg

    if readiness_barrier and cfg.coordinator_address:
        from . import barrier

        host, _, port_str = cfg.coordinator_address.partition(":")
        barrier.gang_barrier(
            coordinator_host=host,
            port=int(port_str or constants.DEFAULT_COORDINATOR_PORT) + 1,
            rank=cfg.process_id,
            world_size=cfg.num_processes,
            timeout_s=initialization_timeout_seconds,
        )

    import jax

    log.info(
        "jax.distributed.initialize coordinator=%s process=%d/%d",
        cfg.coordinator_address,
        cfg.process_id,
        cfg.num_processes,
    )
    jax.distributed.initialize(
        coordinator_address=cfg.coordinator_address,
        num_processes=cfg.num_processes,
        process_id=cfg.process_id,
        initialization_timeout=initialization_timeout_seconds,
    )
    _initialized = True
    return cfg


def shutdown() -> None:
    global _initialized
    if _initialized:
        import jax

        jax.distributed.shutdown()
        _initialized = False
