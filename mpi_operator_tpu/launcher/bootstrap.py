"""Rendezvous bootstrap for TPUJob workers.

The controller injects the rendezvous env (builders._worker_env); this
module consumes it.  The equivalent moment in the reference is `mpirun`
reading the hostfile and ssh-ing into workers
(/root/reference/v2/pkg/controller/mpi_job_controller.go:177-191) — here
every worker calls ``initialize()`` itself and the JAX distributed runtime
forms the world.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Mapping, Optional

from ..api.v2beta1 import constants
from ..utils import trace
from ..utils.logging import get_logger

log = get_logger("launcher")


@dataclass
class RendezvousConfig:
    coordinator_address: str = ""
    num_processes: int = 1
    process_id: int = 0
    worker_id: int = 0
    worker_hostnames: tuple[str, ...] = ()
    accelerator_type: str = ""
    topology: str = ""
    chips_per_host: int = 0
    num_slices: int = 1
    slice_id: int = 0
    megascale_coordinator_address: str = ""
    megascale_num_slices: int = 0
    megascale_slice_id: int = -1
    megascale_port: int = 0
    job_name: str = ""
    job_namespace: str = ""

    @classmethod
    def from_env(cls, environ: Optional[Mapping[str, str]] = None) -> "RendezvousConfig":
        env = os.environ if environ is None else environ

        def _int(name: str, default: int) -> int:
            try:
                return int(env.get(name, default))
            except (TypeError, ValueError):
                return default

        hostnames = tuple(
            h for h in env.get(constants.ENV_TPU_WORKER_HOSTNAMES, "").split(",") if h
        )
        return cls(
            coordinator_address=env.get(constants.ENV_COORDINATOR_ADDRESS, ""),
            num_processes=_int(constants.ENV_NUM_PROCESSES, 1),
            process_id=_int(constants.ENV_PROCESS_ID, 0),
            worker_id=_int(constants.ENV_TPU_WORKER_ID, 0),
            worker_hostnames=hostnames,
            accelerator_type=env.get(constants.ENV_TPU_ACCELERATOR_TYPE, ""),
            topology=env.get(constants.ENV_TPU_TOPOLOGY, ""),
            chips_per_host=_int(constants.ENV_TPU_CHIPS_PER_HOST, 0),
            num_slices=_int(constants.ENV_NUM_SLICES, 1),
            slice_id=_int(constants.ENV_SLICE_ID, 0),
            megascale_coordinator_address=env.get(
                constants.ENV_MEGASCALE_COORDINATOR_ADDRESS, ""
            ),
            megascale_num_slices=_int(constants.ENV_MEGASCALE_NUM_SLICES, 0),
            megascale_slice_id=_int(constants.ENV_MEGASCALE_SLICE_ID, -1),
            megascale_port=_int(constants.ENV_MEGASCALE_PORT, 0),
            job_name=env.get(constants.ENV_JOB_NAME, ""),
            job_namespace=env.get(constants.ENV_JOB_NAMESPACE, ""),
        )

    @property
    def is_distributed(self) -> bool:
        return self.num_processes > 1

    @property
    def is_coordinator(self) -> bool:
        return self.process_id == 0

    @property
    def is_multislice(self) -> bool:
        return self.num_slices > 1

    @property
    def hosts_per_slice(self) -> int:
        return max(len(self.worker_hostnames), 1)

    def check_multislice(self) -> None:
        """Fail fast on inconsistent DCN wiring (a mis-wired megascale env
        otherwise surfaces as an opaque libtpu hang at first collective).

        The slice-local identity (TPU_WORKER_ID/HOSTNAMES) must agree with
        the global identity (process id, slice id): process_id = slice_id
        × hosts_per_slice + worker_id, and the whole world must divide
        evenly into slices.
        """
        if not self.is_multislice:
            return
        if not self.megascale_coordinator_address:
            raise RuntimeError(
                f"num_slices={self.num_slices} but "
                f"{constants.ENV_MEGASCALE_COORDINATOR_ADDRESS} is unset"
            )
        # The MEGASCALE_* values are what libtpu actually consumes — if a
        # wrapper script or pod template overrode them out of agreement
        # with the TPUJOB_* identity, two slices can claim the same id and
        # the world wedges. Cross-check every one that is set.
        if self.megascale_num_slices and self.megascale_num_slices != self.num_slices:
            raise RuntimeError(
                f"{constants.ENV_MEGASCALE_NUM_SLICES}="
                f"{self.megascale_num_slices} disagrees with "
                f"{constants.ENV_NUM_SLICES}={self.num_slices}"
            )
        if self.megascale_slice_id >= 0 and self.megascale_slice_id != self.slice_id:
            raise RuntimeError(
                f"{constants.ENV_MEGASCALE_SLICE_ID}={self.megascale_slice_id} "
                f"disagrees with {constants.ENV_SLICE_ID}={self.slice_id}"
            )
        if self.megascale_port:
            _, _, addr_port = self.megascale_coordinator_address.rpartition(":")
            if addr_port.isdigit() and int(addr_port) != self.megascale_port:
                raise RuntimeError(
                    f"{constants.ENV_MEGASCALE_PORT}={self.megascale_port} "
                    "disagrees with the port in "
                    f"{constants.ENV_MEGASCALE_COORDINATOR_ADDRESS}="
                    f"{self.megascale_coordinator_address}"
                )
        if self.num_processes % self.num_slices:
            raise RuntimeError(
                f"world of {self.num_processes} processes does not divide "
                f"into {self.num_slices} slices"
            )
        per_slice = self.num_processes // self.num_slices
        if self.worker_hostnames and per_slice != self.hosts_per_slice:
            raise RuntimeError(
                f"slice-local hostname list has {self.hosts_per_slice} "
                f"hosts but the world implies {per_slice} per slice"
            )
        expect = self.slice_id * per_slice + self.worker_id
        if self.process_id != expect:
            raise RuntimeError(
                f"process_id {self.process_id} inconsistent with slice "
                f"{self.slice_id} worker {self.worker_id} (expected {expect})"
            )


_initialized = False


def initialize(
    config: Optional[RendezvousConfig] = None,
    *,
    initialization_timeout_seconds: int = 300,
    readiness_barrier: bool = True,
) -> RendezvousConfig:
    """Join the job's jax.distributed world (idempotent).

    Single-process jobs (num_processes == 1) skip distributed init
    entirely, so the same worker image runs unchanged on one host.

    ``readiness_barrier`` first assembles the gang on a side port
    (coordinator port + 1) so no rank dials jax.distributed before the
    coordinator process exists — the SSH-retry analog (launcher.barrier).
    """
    global _initialized
    # Adopt the controller-stamped trace context before any span opens:
    # every span this process produces then shares the reconcile's trace
    # id (operator -> launcher -> worker in one /debug/trace timeline).
    trace.adopt_from_environ()
    cfg = config or RendezvousConfig.from_env()
    if not cfg.is_distributed:
        log.info("single-process TPUJob; skipping jax.distributed.initialize")
        return cfg
    if _initialized:
        return cfg
    with trace.span(
        "launcher.initialize",
        process_id=cfg.process_id,
        num_processes=cfg.num_processes,
        num_slices=cfg.num_slices,
    ):
        # Multislice: libtpu reads MEGASCALE_* from the environment on its
        # own; our job is to fail fast if the controller-rendered wiring is
        # inconsistent rather than hang in the first cross-slice collective.
        cfg.check_multislice()
        if cfg.is_multislice:
            log.info(
                "multislice world: slice %d/%d, DCN coordinator %s",
                cfg.slice_id, cfg.num_slices, cfg.megascale_coordinator_address,
            )

        if readiness_barrier and cfg.coordinator_address:
            from . import barrier

            host, _, port_str = cfg.coordinator_address.partition(":")
            barrier.gang_barrier(
                coordinator_host=host,
                port=int(port_str or constants.DEFAULT_COORDINATOR_PORT) + 1,
                rank=cfg.process_id,
                world_size=cfg.num_processes,
                timeout_s=initialization_timeout_seconds,
            )

        import jax

        log.info(
            "jax.distributed.initialize coordinator=%s process=%d/%d",
            cfg.coordinator_address,
            cfg.process_id,
            cfg.num_processes,
        )
        with trace.span("launcher.jax_distributed_initialize"):
            jax.distributed.initialize(
                coordinator_address=cfg.coordinator_address,
                num_processes=cfg.num_processes,
                process_id=cfg.process_id,
                initialization_timeout=initialization_timeout_seconds,
            )
        _initialized = True
    return cfg


def shutdown() -> None:
    global _initialized
    if _initialized:
        import jax

        jax.distributed.shutdown()
        _initialized = False
