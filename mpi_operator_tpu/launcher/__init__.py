"""Worker-side bootstrap: the TPU-native replacement for the reference's
sshd + hostfile + mpirun stack (reference analog:
/root/reference/v2/pkg/controller/mpi_job_controller.go:1272-1274 worker
sshd default, :1330-1422 launcher mpirun wiring).

Every worker pod runs the same SPMD program; this package turns the env
the controller injected (``TPUJOB_*`` / ``TPU_WORKER_*``) into a
``jax.distributed.initialize`` call, after which XLA collectives ride
ICI/DCN — no SSH, no remote shells, no rank spawning.
"""

from .bootstrap import RendezvousConfig, initialize  # noqa: F401
