"""Leader election over coordination Leases.

Reference analog: client-go leaderelection with an EndpointsLock as wired
in /root/reference/v2/cmd/mpi-operator/app/server.go:210-257 (timings
:60-71: 15s lease, 10s renew deadline, 5s retry).  Only the leader runs
the controller; a replica that loses its lease steps down so HA
deployments never double-reconcile.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional

from .apiserver import (
    AlreadyExistsError,
    ConflictError,
    InMemoryAPIServer,
    NotFoundError,
)

DEFAULT_LEASE_DURATION = 15.0
DEFAULT_RENEW_DEADLINE = 10.0
DEFAULT_RETRY_PERIOD = 5.0


@dataclass
class LeaderElectionConfig:
    lock_namespace: str = "default"
    lock_name: str = "tpu-operator"
    identity: str = ""
    lease_duration: float = DEFAULT_LEASE_DURATION
    renew_deadline: float = DEFAULT_RENEW_DEADLINE
    retry_period: float = DEFAULT_RETRY_PERIOD


class LeaderElector:
    def __init__(
        self,
        api: InMemoryAPIServer,
        config: LeaderElectionConfig,
        *,
        on_started_leading: Callable[[threading.Event], None],
        on_stopped_leading: Optional[Callable[[], None]] = None,
        clock: Callable[[], float] = time.time,
        sleep: Callable[[float], None] = time.sleep,
    ):
        self.api = api
        self.config = config
        self.on_started_leading = on_started_leading
        self.on_stopped_leading = on_stopped_leading
        self.clock = clock
        self.sleep = sleep
        self.is_leader = False

    # -- lease plumbing --------------------------------------------------

    def _lease(self) -> Optional[dict]:
        try:
            return self.api.get(
                "leases", self.config.lock_namespace, self.config.lock_name
            )
        except NotFoundError:
            return None

    def _try_acquire_or_renew(self) -> bool:
        now = self.clock()
        lease = self._lease()
        if lease is None:
            try:
                self.api.create(
                    "leases",
                    {
                        "metadata": {
                            "name": self.config.lock_name,
                            "namespace": self.config.lock_namespace,
                        },
                        "spec": {
                            "holderIdentity": self.config.identity,
                            "leaseDurationSeconds": self.config.lease_duration,
                            "acquireTime": now,
                            "renewTime": now,
                        },
                    },
                )
                return True
            except (AlreadyExistsError, ConflictError):
                return False  # lost the creation race; retry next period
        spec = lease.get("spec") or {}
        holder = spec.get("holderIdentity", "")
        renew = float(spec.get("renewTime", 0) or 0)
        duration = float(spec.get("leaseDurationSeconds", self.config.lease_duration))
        if holder != self.config.identity and now < renew + duration:
            return False  # someone else holds a live lease
        spec = dict(spec)
        spec["holderIdentity"] = self.config.identity
        spec["renewTime"] = now
        if holder != self.config.identity:
            spec["acquireTime"] = now
        lease["spec"] = spec
        try:
            self.api.update("leases", lease)
            return True
        except ConflictError:
            return False

    # -- run loop --------------------------------------------------------

    def run(self, stop: threading.Event) -> None:
        """Block until ``stop``; leads whenever the lease is held.

        on_started_leading(stop_leading) runs in a worker thread with an
        event that fires when leadership is lost or stop is set.
        """
        while not stop.is_set():
            if not self._try_acquire_or_renew():
                self.sleep(self.config.retry_period)
                continue
            # Acquired.
            self.is_leader = True
            lost = threading.Event()
            worker = threading.Thread(
                target=self.on_started_leading, args=(lost,), daemon=True
            )
            worker.start()
            deadline = self.clock() + self.config.renew_deadline
            while not stop.is_set():
                if self._try_acquire_or_renew():
                    deadline = self.clock() + self.config.renew_deadline
                elif self.clock() > deadline:
                    break  # failed to renew inside the deadline: step down
                self.sleep(self.config.retry_period)
            self.is_leader = False
            lost.set()
            # Let the previous term's worker finish before any re-acquire,
            # otherwise two terms could reconcile concurrently.
            worker.join(timeout=30)
            if self.on_stopped_leading:
                self.on_stopped_leading()
            if not stop.is_set():
                self.sleep(self.config.retry_period)

    def healthy(self) -> bool:
        """healthz adaptor (server.go:192-208 analog): healthy when not
        leading, or when leading with a fresh-enough lease."""
        if not self.is_leader:
            return True
        lease = self._lease()
        if lease is None:
            return False
        spec = lease.get("spec") or {}
        if spec.get("holderIdentity") != self.config.identity:
            return False
        renew = float(spec.get("renewTime", 0) or 0)
        return self.clock() - renew < self.config.lease_duration
