"""Runtime lock-order race detector (Go ``-race`` / kernel lockdep analog).

The control plane is genuinely concurrent: informer pumps, workqueue
workers, the gang scheduler's reservation pass, the queue manager, and
scrape-time metric hooks all take locks in ~20 threaded modules.  The
static checker (``mpi_operator_tpu/analysis/lockcheck.py``) proves
discipline at the AST level; this module proves it at *runtime*: every
control-plane lock is created through the factories below, and when
tracing is armed each acquisition records

- the set of locks the acquiring thread already holds (the lockdep
  held-set), building a global lock-*order* graph keyed by lock name;
- an **inversion** whenever the graph gains an edge A->B while the
  reverse edge B->A was already observed on any thread — the classic
  deadlock precondition, caught even when the timing never actually
  deadlocks (single-threaded drives like the chaos soak still surface
  ordering bugs this way);
- **long holds**: a lock held longer than ``long_hold_seconds`` of wall
  clock (a stalled scrape hook or an apiserver write made under a hot
  lock).

Zero cost when off: the factories return plain ``threading`` primitives
unless tracing was enabled *before* the lock was created, so production
paths pay only one module-attribute read at construction time and
nothing per acquisition.  Arm it with the ``TPU_LOCK_TRACE=1``
environment variable, the operator's ``--lock-trace`` flag, the bench
harness's ``--lock-trace``, or ``locktrace.enable()`` in tests.

Identity is the lock *name*, not the instance (lockdep's lock-class
idiom): every informer's cache lock shares the ``informer.<resource>``
class, so an ordering violation between two instances of the same
subsystem is still a violation.  Self-edges (A->A) are skipped — a
reentrant RLock re-acquisition is legal and must not read as an
inversion.
"""

from __future__ import annotations

import os
import threading
import time
import traceback
from typing import Callable, Optional

ENV_FLAG = "TPU_LOCK_TRACE"
DEFAULT_LONG_HOLD_SECONDS = 1.0

# How many stack frames to keep per edge/inversion sample (enough to see
# the call path, small enough to keep reports readable).
_STACK_DEPTH = 12


class LockOrderError(AssertionError):
    """Raised by ``LockTracer.assert_no_inversions`` with the full
    inversion report in the message."""


def _capture_stack() -> list[str]:
    # Drop the tracer's own frames; keep the caller's path.
    return [
        f"{frame.filename}:{frame.lineno}:{frame.name}"
        for frame in traceback.extract_stack()[-_STACK_DEPTH - 3:-3]
    ]


class LockTracer:
    """Per-thread held-lock sets and the global lock-order graph.

    One tracer serves every traced lock in the process.  Its own state
    is guarded by an *untraced* ``threading.Lock`` (the tracer cannot
    trace itself), and per-thread held stacks live in a
    ``threading.local`` so the hot path takes the internal lock only
    when the held-set is non-empty (nested acquisition) or on release
    of a long-held lock.
    """

    def __init__(
        self,
        clock: Callable[[], float] = time.monotonic,
        long_hold_seconds: float = DEFAULT_LONG_HOLD_SECONDS,
        capture_stacks: bool = True,
    ):
        self.clock = clock
        self.long_hold_seconds = long_hold_seconds
        self.capture_stacks = capture_stacks
        self._mu = threading.Lock()  # internal; never a traced lock
        self._local = threading.local()
        # name -> {name -> sample stack of the first A-held->B acquire}
        self._edges: dict[str, dict[str, list[str]]] = {}
        self._inversions: list[dict] = []
        self._seen_pairs: set[frozenset] = set()
        self._long_holds: list[dict] = []
        self._max_held: dict[str, float] = {}
        self._acquisitions = 0

    # -- per-thread held stack ------------------------------------------

    def _held(self) -> list:
        held = getattr(self._local, "held", None)
        if held is None:
            held = self._local.held = []
        return held

    def held_names(self) -> tuple[str, ...]:
        """Locks the calling thread currently holds, outermost first."""
        return tuple(name for name, _ in self._held())

    # -- acquisition hooks ----------------------------------------------

    def on_acquired(self, name: str) -> None:
        held = self._held()
        now = self.clock()
        if held:
            stack = _capture_stack() if self.capture_stacks else []
            with self._mu:
                self._acquisitions += 1
                for outer, _ in held:
                    if outer == name:
                        continue  # same lock class: reentrancy, not order
                    self._edges.setdefault(outer, {}).setdefault(name, stack)
                    reverse = self._edges.get(name, {}).get(outer)
                    if reverse is not None:
                        pair = frozenset((outer, name))
                        if pair not in self._seen_pairs:
                            self._seen_pairs.add(pair)
                            self._inversions.append({
                                "locks": sorted(pair),
                                "forward": f"{outer} -> {name}",
                                "forward_stack": stack,
                                "reverse": f"{name} -> {outer}",
                                "reverse_stack": reverse,
                            })
        else:
            with self._mu:
                self._acquisitions += 1
        held.append((name, now))

    def on_released(self, name: str) -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] == name:
                _, acquired_at = held.pop(i)
                duration = self.clock() - acquired_at
                with self._mu:
                    if duration > self._max_held.get(name, 0.0):
                        self._max_held[name] = duration
                    if duration >= self.long_hold_seconds:
                        self._long_holds.append({
                            "lock": name,
                            "held_seconds": round(duration, 6),
                            "stack": (
                                _capture_stack() if self.capture_stacks else []
                            ),
                        })
                return

    # -- reporting -------------------------------------------------------

    def report(self) -> dict:
        """JSON-friendly summary: inversions, long holds, the order
        graph, and per-lock max hold times."""
        with self._mu:
            return {
                "acquisitions": self._acquisitions,
                "locks": sorted(self._max_held),
                "inversions": [dict(inv) for inv in self._inversions],
                "long_holds": [dict(h) for h in self._long_holds],
                "edges": {
                    outer: sorted(inners)
                    for outer, inners in sorted(self._edges.items())
                },
                "max_held_seconds": {
                    name: round(secs, 6)
                    for name, secs in sorted(self._max_held.items())
                },
            }

    def assert_no_inversions(self) -> None:
        with self._mu:
            inversions = list(self._inversions)
        if inversions:
            lines = ["lock-order inversions detected:"]
            for inv in inversions:
                lines.append(f"  {inv['forward']}  vs  {inv['reverse']}")
                for label in ("forward_stack", "reverse_stack"):
                    for frame in inv[label][-4:]:
                        lines.append(f"    [{label}] {frame}")
            raise LockOrderError("\n".join(lines))


# ----------------------------------------------------------------------
# Traced primitives
# ----------------------------------------------------------------------


class TracedLock:
    """A non-reentrant ``threading.Lock`` that reports acquisition order
    to a :class:`LockTracer`.  Usable as a ``threading.Condition`` inner
    lock (acquire/release protocol only; no ``_release_save`` — the
    Condition falls back to plain release/acquire, which keeps the
    tracer's held-set honest across ``wait()``)."""

    def __init__(self, name: str, tracer: LockTracer):
        self._inner = threading.Lock()
        self.name = name
        self._tracer = tracer

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._tracer.on_acquired(self.name)
        return ok

    def release(self) -> None:
        self._tracer.on_released(self.name)
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()


class TracedRLock:
    """A reentrant lock wrapper.  Only the outermost acquisition (per
    thread) reports to the tracer — re-acquisition by the owning thread
    is legal and must not create order edges (the reentrant-RLock
    non-finding).  Implements the private Condition protocol
    (``_release_save``/``_acquire_restore``/``_is_owned``) so
    ``threading.Condition(TracedRLock(...))`` keeps exact RLock
    semantics while the tracer sees ``wait()`` drop and re-take the
    lock."""

    def __init__(self, name: str, tracer: LockTracer):
        self._inner = threading.RLock()
        self.name = name
        self._tracer = tracer
        self._local = threading.local()

    def _depth(self) -> int:
        return getattr(self._local, "depth", 0)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            depth = self._depth()
            if depth == 0:
                self._tracer.on_acquired(self.name)
            self._local.depth = depth + 1
        return ok

    def release(self) -> None:
        depth = self._depth()
        if depth == 1:
            self._tracer.on_released(self.name)
        self._local.depth = max(depth - 1, 0)
        self._inner.release()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    # -- Condition protocol ---------------------------------------------

    def _release_save(self):
        depth = self._depth()
        if depth:
            self._tracer.on_released(self.name)
        self._local.depth = 0
        return (self._inner._release_save(), depth)

    def _acquire_restore(self, state) -> None:
        inner_state, depth = state
        self._inner._acquire_restore(inner_state)
        if depth:
            self._tracer.on_acquired(self.name)
        self._local.depth = depth

    def _is_owned(self) -> bool:
        return self._inner._is_owned()


# ----------------------------------------------------------------------
# Process-global switch + factories
# ----------------------------------------------------------------------

_tracer: Optional[LockTracer] = None


def enabled() -> bool:
    return _tracer is not None


def tracer() -> Optional[LockTracer]:
    """The active tracer, or None when tracing is off."""
    return _tracer


def enable(active: Optional[LockTracer] = None) -> LockTracer:
    """Arm tracing for locks created from now on; returns the tracer.
    Call *before* constructing the stack under test — locks created
    while tracing was off stay plain forever."""
    global _tracer
    _tracer = active if active is not None else LockTracer()
    return _tracer


def disable() -> None:
    global _tracer
    _tracer = None


def _env_enabled() -> bool:
    return os.environ.get(ENV_FLAG, "").strip().lower() not in (
        "", "0", "false", "off", "no",
    )


if _env_enabled():  # pragma: no cover - exercised via subprocess tests
    enable()


def lock(name: str):
    """A mutex for control-plane state: plain ``threading.Lock`` when
    tracing is off, a :class:`TracedLock` when armed."""
    if _tracer is None:
        return threading.Lock()
    return TracedLock(name, _tracer)


def rlock(name: str):
    if _tracer is None:
        return threading.RLock()
    return TracedRLock(name, _tracer)


def condition(name: str):
    """A ``threading.Condition`` whose (reentrant) inner lock is traced
    when armed — the workqueue idiom."""
    if _tracer is None:
        return threading.Condition()
    return threading.Condition(TracedRLock(name, _tracer))
