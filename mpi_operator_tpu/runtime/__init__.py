"""Kubernetes-shaped runtime machinery: object model, in-memory API server,
typed clients, informers, and rate-limited workqueue."""
