"""Local pod runner: a kubelet + batch-Job-controller simulator.

Reference analog: the kind cluster in the reference's e2e tier
(/root/reference/v2/test/e2e/e2e_suite_test.go) — real containers running
real MPI traffic.  Here, worker pods become real *subprocesses* running
real ``jax.distributed`` traffic over localhost (JAX CPU backend standing
in for TPU chips), which exercises the identical rendezvous path the
operator wires up on a cluster:

- pods created on the API server are "scheduled" and executed:
  Pending → Running → Succeeded/Failed by exit code;
- the pod env is taken verbatim from the pod spec, with worker-FQDN
  coordinator addresses rewritten to 127.0.0.1 (the simulator's cluster
  DNS) and the JAX platform pinned to CPU for hermeticity;
- ``restartPolicy: OnFailure`` restarts the process (bounded);
- pod logs are tailed LIVE (a reader thread per process, not a read at
  reap), and ``step_heartbeat``/``device_memory`` JSONL lines the
  trainer emits are patched onto the pod as the step-heartbeat and
  device-memory annotations — the kubelet half of the step-skew and
  device-memory observatories (the pod informer watch carries the
  patches to utils/stepstats.py and utils/devstats.py with no new
  transport);
- batch/v1 Jobs get a pod created from their template and their status
  mirrored to Complete/Failed with backoffLimit retries — the part of the
  reference flow that the kube Job controller owns
  (mpi_job_controller.go:573 hands control to it);
- deleting a pod kills its process.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

from ..api.v2beta1 import constants
from ..utils import flightrecorder
from ..utils.logging import get_logger
from . import locktrace, retry
from .apiserver import (
    ADDED,
    DELETED,
    MODIFIED,
    ConflictError,
    InMemoryAPIServer,
    NotFoundError,
)

MAX_RESTARTS = 3

# The nodeName auto-bind mode stamps when no scheduler is running: the
# simulator's single implicit machine.
DEFAULT_NODE_NAME = "local-node"


@dataclass
class RunningPod:
    process: subprocess.Popen
    restarts: int = 0
    log: str = ""
    # Live stdout tail (one daemon thread per process); log appends are
    # serialized by log_lock so pod_log() reads a consistent prefix.
    reader: Optional[threading.Thread] = None
    log_lock: threading.Lock = field(default_factory=threading.Lock)


class LocalPodRunner:
    def __init__(
        self,
        api: InMemoryAPIServer,
        *,
        base_env: Optional[dict[str, str]] = None,
        workdir: Optional[str] = None,
        auto_bind: bool = True,
        node_name: str = DEFAULT_NODE_NAME,
        flight_recorder: Optional[flightrecorder.FlightRecorder] = None,
    ):
        self.api = api
        self.log = get_logger("podrunner")
        # Shared with the controller when the operator wires one through:
        # pod phase flips land on the owning job's timeline.
        self.flight_recorder = flight_recorder
        self.base_env = base_env or {}
        self.workdir = workdir or os.getcwd()
        # A kubelet only runs pods bound to its node.  With no scheduler in
        # the process (the default), the runner plays scheduler too and
        # auto-binds unbound pods to its own node; with ``auto_bind=False``
        # it strictly waits for ``spec.nodeName`` (gang-scheduler mode).
        self.auto_bind = auto_bind
        self.node_name = node_name
        self._pods: dict[tuple[str, str], RunningPod] = {}
        # Chaos SlowWorker registrations: pod key -> slowdown factor,
        # injected into the child env (ENV_STEP_SLOWDOWN) so the
        # trainer's step clock stretches; a factor registered against a
        # live process takes effect at its next (re)start — the runner
        # cannot retroactively slow a running subprocess.
        self._slow: dict[tuple[str, str], float] = {}
        # Chaos MemoryLeak registrations: pod key -> bytes per window,
        # injected into the child env (ENV_MEM_LEAK_BYTES) so the
        # worker's devstats sampler inflates its reported HBM; same
        # next-(re)start semantics as _slow.
        self._leak: dict[tuple[str, str], int] = {}
        # Chaos TornWrite registrations: pod key -> True, injected ONCE
        # into the child env (ENV_TORN_WRITE) so the checkpoint writer
        # tears its next commit (step data written, marker withheld);
        # one-shot — the entry is popped at injection, so a restarted pod
        # writes clean checkpoints again.
        self._torn: dict[tuple[str, str], bool] = {}
        self._job_pods: dict[tuple[str, str], int] = {}  # job -> failures so far
        self._lock = locktrace.rlock("podrunner")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._pod_watch = None
        self._job_watch = None

    # -- lifecycle -------------------------------------------------------

    def start(self) -> None:
        self._pod_watch = self.api.watch("pods")
        self._job_watch = self.api.watch("jobs")
        # Pick up anything that already exists.
        for pod in self.api.list("pods"):
            self._maybe_start_pod(pod)
        for job in self.api.list("jobs"):
            self._maybe_start_job_pod(job)
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=10)
        with self._lock:
            for running in self._pods.values():
                if running.process.poll() is None:
                    running.process.kill()
            self._pods.clear()
        if self._pod_watch:
            self._pod_watch.stop()
        if self._job_watch:
            self._job_watch.stop()

    def _loop(self) -> None:
        while not self._stop.is_set():
            progressed = False
            for event in self._pod_watch.drain():
                progressed = True
                key = self._event_key(event.object)
                if event.type in (ADDED, MODIFIED):
                    # MODIFIED matters in scheduler mode: the bind that
                    # stamps spec.nodeName arrives as an update, not a
                    # create. _maybe_start_pod is idempotent per pod.
                    self._maybe_start_pod(event.object)
                elif event.type == DELETED:
                    self._kill(key)
            for event in self._job_watch.drain():
                progressed = True
                if event.type == ADDED:
                    self._maybe_start_job_pod(event.object)
            if self._reap():
                progressed = True
            if not progressed:
                time.sleep(0.02)

    @staticmethod
    def _event_key(obj: dict) -> tuple[str, str]:
        meta = obj["metadata"]
        return meta.get("namespace", ""), meta["name"]

    # -- pod execution ---------------------------------------------------

    def _child_env(self, pod: dict) -> dict[str, str]:
        env = dict(os.environ)
        # Hermetic: children run the JAX CPU backend, never the real TPU.
        env["JAX_PLATFORMS"] = "cpu"
        env["PALLAS_AXON_POOL_IPS"] = ""
        # Don't inherit the test harness's virtual 8-device mesh: a worker
        # pod models ONE host (its own chips), and 4+ workers × 8 virtual
        # devices × XLA's thread pools thrash a CI machine enough to blow
        # the 200 s e2e bound.
        if "XLA_FLAGS" in env:
            flags = [
                f for f in env["XLA_FLAGS"].split()
                if not f.startswith("--xla_force_host_platform_device_count")
            ]
            env["XLA_FLAGS"] = " ".join(flags)
        # The "image" of our simulated containers is the repo itself.
        env["PYTHONPATH"] = self.workdir + (
            os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else ""
        )
        env.update(self.base_env)
        factor = self._slow.get(self._event_key(pod))
        if factor is not None and factor > 1.0:
            env[constants.ENV_STEP_SLOWDOWN] = str(factor)
        leak = self._leak.get(self._event_key(pod))
        if leak is not None and leak > 0:
            env[constants.ENV_MEM_LEAK_BYTES] = str(leak)
        if self._torn.pop(self._event_key(pod), None):
            env[constants.ENV_TORN_WRITE] = "1"
        container = (pod["spec"].get("containers") or [{}])[0]
        for item in container.get("env") or []:
            value = str(item.get("value", ""))
            if item.get("name") == "TPUJOB_COORDINATOR_ADDRESS" and ":" in value:
                # Cluster DNS of the simulator: every "node" is localhost.
                value = "127.0.0.1:" + value.rsplit(":", 1)[1]
            env[item["name"]] = value
        return env

    def _command(self, pod: dict) -> list[str]:
        container = (pod["spec"].get("containers") or [{}])[0]
        cmd = list(container.get("command") or [])
        cmd += [str(a) for a in container.get("args") or []]
        if cmd and cmd[0] == "python":
            cmd[0] = sys.executable
        return cmd

    def _ensure_bound(self, pod: dict) -> Optional[dict]:
        """Return a pod bound to a node, auto-binding if this runner plays
        scheduler; None if the pod must keep waiting for a bind."""
        if pod["spec"].get("nodeName"):
            return pod
        if not self.auto_bind:
            return None
        key = self._event_key(pod)

        def bind():
            fresh = self.api.get("pods", key[0], key[1])
            if fresh["spec"].get("nodeName"):
                return fresh
            fresh["spec"]["nodeName"] = self.node_name
            return self.api.update("pods", fresh)

        try:
            return retry.retry_on_conflict(bind, retry.Backoff(steps=2, duration=0.005))
        except NotFoundError:
            return None
        except ConflictError:
            return None  # give up until the next watch event

    def _maybe_start_pod(self, pod: dict) -> None:
        key = self._event_key(pod)
        with self._lock:
            if key in self._pods:
                return
        # A pod we are not tracking but whose phase already progressed is
        # one we (or a previous runner) finished or are mid-reaping —
        # MODIFIED events from our own status writes must not relaunch it.
        if (pod.get("status") or {}).get("phase") in ("Running", "Succeeded", "Failed"):
            return
        bound = self._ensure_bound(pod)
        if bound is None:
            return
        pod = bound
        with self._lock:
            if key in self._pods:
                return
            cmd = self._command(pod)
            if not cmd:
                self._set_phase(key, "Failed", reason="NoCommand")
                return
            running = self._launch(key, pod)
            self._pods[key] = running
        self.log.info("started pod %s/%s", key[0], key[1],
                      pid=running.process.pid)
        self._set_phase(key, "Running")

    def _launch(
        self, key: tuple[str, str], pod: dict, restarts: int = 0, log: str = ""
    ) -> RunningPod:
        """Spawn the pod's process plus its log-tail thread.  The tail is
        the kubelet-sim's live log stream: it accumulates the pod log as
        lines arrive (pod_log() sees a running pod's output, not just a
        dead one's) and bridges ``step_heartbeat`` JSONL lines onto the
        pod as annotation patches."""
        process = subprocess.Popen(
            self._command(pod),
            env=self._child_env(pod),
            cwd=self.workdir,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        running = RunningPod(process=process, restarts=restarts, log=log)
        running.reader = threading.Thread(
            target=self._tail, args=(key, running), daemon=True,
            name=f"podrunner-tail-{key[1]}",
        )
        running.reader.start()
        return running

    def _tail(self, key: tuple[str, str], running: RunningPod) -> None:
        stdout = running.process.stdout
        if stdout is None:  # pragma: no cover - Popen always pipes here
            return
        for line in stdout:
            with running.log_lock:
                running.log += line
            stripped = line.strip()
            if not stripped.startswith('{"'):
                continue
            try:
                record = json.loads(stripped)
            except ValueError:
                continue
            if not isinstance(record, dict):
                continue
            event = record.get("event")
            if event == "step_heartbeat":
                self._publish_annotation(
                    key, constants.STEP_HEARTBEAT_ANNOTATION, record
                )
            elif event == "device_memory":
                self._publish_annotation(
                    key, constants.DEVICE_MEMORY_ANNOTATION, record
                )

    def _publish_annotation(
        self, key: tuple[str, str], annotation: str, record: dict
    ) -> None:
        """Patch a telemetry record onto one of the pod's observatory
        annotations — step heartbeats and device-memory samples share
        this bridge (get+mutate+update with conflict retry — the memory
        apiserver has no patch verb).  The resulting MODIFIED watch event
        is how the controller-side matrices learn about the window."""

        def apply():
            pod = self.api.get("pods", key[0], key[1])
            meta = pod.setdefault("metadata", {})
            annotations = dict(meta.get("annotations") or {})
            annotations[annotation] = json.dumps(record, sort_keys=True)
            meta["annotations"] = annotations
            return self.api.update("pods", pod)

        try:
            retry.retry_on_conflict(
                apply, retry.Backoff(steps=3, duration=0.005)
            )
        except NotFoundError:
            pass  # pod deleted mid-run; nothing to annotate
        except ConflictError:
            pass  # next window's record will carry fresher numbers
        except Exception:
            self.log.debug(
                "annotation patch %s failed for %s/%s",
                annotation, key[0], key[1],
            )

    def _kill(self, key: tuple[str, str]) -> None:
        with self._lock:
            running = self._pods.pop(key, None)
        if running and running.process.poll() is None:
            running.process.kill()

    def _reap(self) -> bool:
        """Collect exited processes, apply restart policy, flip phases."""
        progressed = False
        with self._lock:
            items = list(self._pods.items())
        for key, running in items:
            rc = running.process.poll()
            if rc is None:
                continue
            progressed = True
            # The tail thread owns stdout: wait for it to drain the last
            # buffered lines so the failure message below sees them.
            if running.reader is not None:
                running.reader.join(timeout=5)
            try:
                pod = self.api.get("pods", key[0], key[1])
            except NotFoundError:
                with self._lock:
                    self._pods.pop(key, None)
                continue
            restart_policy = pod["spec"].get("restartPolicy", "Never")
            # Container exit code convention: a signal death reports
            # 128+signal (SIGKILL → 137), the code TPU preemptions show.
            exit_code = rc if rc >= 0 else 128 - rc
            if rc == 0:
                self._set_phase(key, "Succeeded", exit_code=0)
                with self._lock:
                    self._pods.pop(key, None)
            elif restart_policy == "OnFailure" and running.restarts < MAX_RESTARTS:
                running.restarts += 1
                self.log.warning(
                    "pod %s/%s exited rc=%d; restarting (%d/%d)",
                    key[0], key[1], rc, running.restarts, MAX_RESTARTS,
                )
                with running.log_lock:
                    carried_log = running.log
                with self._lock:
                    self._pods[key] = self._launch(
                        key, pod, restarts=running.restarts, log=carried_log
                    )
            else:
                with running.log_lock:
                    tail = running.log[-1024:]
                self._set_phase(
                    key, "Failed", reason="Error", message=tail,
                    exit_code=exit_code,
                )
                with self._lock:
                    self._pods.pop(key, None)
                self._mirror_job_failure(pod)
        return progressed

    # -- chaos hooks -----------------------------------------------------

    def kill_pod(self, namespace: str, name: str) -> bool:
        """Chaos hook: SIGKILL the pod's process.  The reaper classifies
        the exit through the normal failure path (rc -9 → exitCode 137,
        the code a TPU preemption reports)."""
        with self._lock:
            running = self._pods.get((namespace, name))
        if running is None or running.process.poll() is not None:
            return False
        running.process.kill()
        return True

    def slow_worker(
        self, namespace: str, name: str, factor: float
    ) -> bool:
        """Chaos hook: mark the pod's host slow by ``factor``.  The
        factor reaches the trainer's step clock via ENV_STEP_SLOWDOWN at
        the pod's next (re)start — a live subprocess cannot be slowed
        retroactively, matching a real straggler that appears after a
        reschedule onto a degraded host.  Returns False for pods this
        runner does not know."""
        if factor < 1.0:
            return False
        return self._register_chaos(self._slow, namespace, name, factor)

    def leak_worker(
        self, namespace: str, name: str, bytes_per_window: int
    ) -> bool:
        """Chaos hook: mark the pod's worker as leaking HBM by
        ``bytes_per_window``.  The increment reaches the devstats
        sampler via ENV_MEM_LEAK_BYTES at the pod's next (re)start —
        same semantics as slow_worker.  Returns False for pods this
        runner does not know."""
        if bytes_per_window <= 0:
            return False
        return self._register_chaos(
            self._leak, namespace, name, int(bytes_per_window)
        )

    def tear_write(self, namespace: str, name: str) -> bool:
        """Chaos hook: arm a one-shot torn checkpoint commit for the
        pod's *next* (re)start — the writer persists the step data but
        withholds the commit marker (ENV_TORN_WRITE), modelling a death
        between the fsync of the data and the rename of the marker.
        Same next-(re)start semantics as slow_worker; the registration
        is consumed at injection (one torn commit per arm)."""
        return self._register_chaos(self._torn, namespace, name, True)

    def _register_chaos(
        self, table: dict, namespace: str, name: str, value
    ) -> bool:
        """Shared registration for next-(re)start chaos env injection:
        the pod must be running here or at least exist in the apiserver."""
        key = (namespace, name)
        with self._lock:
            if key not in self._pods:
                try:
                    self.api.get("pods", namespace, name)
                except NotFoundError:
                    return False
            table[key] = value
        return True

    def fail_node(self, namespace: str, name: str) -> bool:
        """Chaos hook: the pod's node dies — the process is killed and the
        pod flips straight to Failed/NodeLost with no exit code (a dead
        kubelet never reports one), exercising condition/reason matching
        in podFailurePolicy rather than exit-code matching."""
        key = (namespace, name)
        with self._lock:
            running = self._pods.pop(key, None)
        if running is None:
            return False
        if running.process.poll() is None:
            running.process.kill()
        self._set_phase(key, "Failed", reason="NodeLost", message="node died")
        return True

    def _set_phase(
        self,
        key: tuple[str, str],
        phase: str,
        reason: str = "",
        message: str = "",
        exit_code: Optional[int] = None,
    ) -> None:
        try:
            pod = self.api.get("pods", key[0], key[1])
        except NotFoundError:
            return
        # Merge, don't replace: the scheduler's PodScheduled condition must
        # survive the phase flip.
        status = dict(pod.get("status") or {})
        status["phase"] = phase
        if reason:
            status["reason"] = reason
        if message:
            status["message"] = message
        if exit_code is not None:
            # Surface the container exit code the way a kubelet would, so
            # podFailurePolicy onExitCodes rules have something to match.
            container = (pod["spec"].get("containers") or [{}])[0]
            status["containerStatuses"] = [
                {
                    "name": container.get("name", "main"),
                    "state": {"terminated": {"exitCode": exit_code}},
                }
            ]
        pod["status"] = status
        try:
            self.api.update_status("pods", pod)
        except Exception:
            pass
        if reason:
            self.log.debug("pod %s/%s -> %s", key[0], key[1], phase,
                           reason=reason)
        else:
            self.log.debug("pod %s/%s -> %s", key[0], key[1], phase)
        self._record_pod_flip(pod, phase, reason, message, exit_code)
        if phase == "Succeeded":
            self._mirror_job_success(pod)

    def _record_pod_flip(
        self, pod: dict, phase: str, reason: str, message: str,
        exit_code: Optional[int] = None,
    ) -> None:
        """Put the phase flip on the owning TPUJob's flight-recorder
        timeline.  Worker pods carry the job-name label directly; launcher
        pods are owned by a batch Job whose template carries it too.
        The exit code rides along when the kubelet reported one, so the
        goodput ledger can tell a preemption (137) from a crash without
        re-reading the pod."""
        if self.flight_recorder is None:
            return
        labels = pod["metadata"].get("labels") or {}
        job_name = labels.get(constants.JOB_NAME_LABEL)
        if not job_name:
            return
        attrs = {}
        if exit_code is not None:
            attrs["exit_code"] = exit_code
        self.flight_recorder.record(
            pod["metadata"].get("namespace", ""),
            job_name,
            flightrecorder.POD,
            reason=reason or phase,
            message=message[-256:] if message else "",
            pod=pod["metadata"]["name"],
            phase=phase,
            **attrs,
        )

    def pod_log(self, namespace: str, name: str) -> str:
        with self._lock:
            running = self._pods.get((namespace, name))
        if running is None:
            return ""
        with running.log_lock:
            return running.log

    # -- batch Job mirroring --------------------------------------------

    def _maybe_start_job_pod(self, job: dict) -> None:
        ns, name = self._event_key(job)
        template = (job.get("spec") or {}).get("template") or {}
        pod = {
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": {
                "name": f"{name}-0",
                "namespace": ns,
                "labels": dict((template.get("metadata") or {}).get("labels") or {}),
                "ownerReferences": [
                    {
                        "apiVersion": "batch/v1",
                        "kind": "Job",
                        "name": name,
                        "uid": job["metadata"].get("uid", ""),
                        "controller": True,
                    }
                ],
            },
            "spec": dict(template.get("spec") or {}),
        }
        pod["metadata"]["labels"].setdefault("job-name", name)
        try:
            self.api.create("pods", pod)
        except Exception:
            pass  # already exists

    def _owning_job(self, pod: dict) -> Optional[tuple[str, str]]:
        for ref in pod["metadata"].get("ownerReferences") or []:
            if ref.get("kind") == "Job" and ref.get("controller"):
                return pod["metadata"].get("namespace", ""), ref["name"]
        return None

    def _mirror_job_success(self, pod: dict) -> None:
        owner = self._owning_job(pod)
        if owner is None:
            return
        try:
            job = self.api.get("jobs", owner[0], owner[1])
        except NotFoundError:
            return
        job["status"] = {
            "succeeded": 1,
            "completionTime": time.time(),
            "conditions": [{"type": "Complete", "status": "True"}],
        }
        try:
            self.api.update_status("jobs", job)
        except Exception:
            pass

    def _mirror_job_failure(self, pod: dict) -> None:
        owner = self._owning_job(pod)
        if owner is None:
            return
        try:
            job = self.api.get("jobs", owner[0], owner[1])
        except NotFoundError:
            return
        failures = self._job_pods.get(owner, 0) + 1
        self._job_pods[owner] = failures
        backoff = (job.get("spec") or {}).get("backoffLimit", 0)
        status = dict(job.get("status") or {})
        status["failed"] = failures
        if failures > backoff:
            status["conditions"] = [
                {
                    "type": "Failed",
                    "status": "True",
                    "reason": "BackoffLimitExceeded",
                    "message": "Job has reached the specified backoff limit",
                }
            ]
        job["status"] = status
        try:
            self.api.update_status("jobs", job)
        except Exception:
            pass
        if failures <= backoff:
            # Retry: new pod (the kube Job controller would do this).
            try:
                self.api.delete("pods", pod["metadata"]["namespace"], pod["metadata"]["name"])
            except NotFoundError:
                pass
            self._maybe_start_job_pod(job)
