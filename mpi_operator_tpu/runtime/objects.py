"""Kubernetes-shaped object model.

The operator reconciles TPUJobs into ordinary Kubernetes objects (Pods,
Services, ConfigMaps, batch Jobs, PodGroups).  This module provides the
minimal-but-faithful object model those objects share: ``ObjectMeta``,
``OwnerReference``, and a generic ``KubeObject`` wrapper whose payload
(spec/status/data) stays in plain dict form, exactly as an apiserver would
store JSON.

Reference analog: k8s.io/apimachinery/pkg/apis/meta/v1 as consumed by
/root/reference/v2/pkg/apis/kubeflow/v2beta1/types.go:25-38 and the object
builders in /root/reference/v2/pkg/controller/mpi_job_controller.go:1103-1546.
"""

from __future__ import annotations

import copy
import re
from dataclasses import dataclass, field
from typing import Any, Optional

DNS1123_LABEL_RE = re.compile(r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?$")
DNS1123_LABEL_MAX = 63


def is_dns1123_label(value: str) -> list[str]:
    """Validate an RFC 1123 DNS label; returns a list of error strings.

    Reference analog: k8s.io/apimachinery/pkg/util/validation.IsDNS1123Label
    as used in /root/reference/v2/pkg/apis/kubeflow/validation/validation.go:62.
    """
    errs = []
    if len(value) > DNS1123_LABEL_MAX:
        errs.append(f"must be no more than {DNS1123_LABEL_MAX} characters")
    if not DNS1123_LABEL_RE.match(value):
        errs.append(
            "a lowercase RFC 1123 label must consist of lower case "
            "alphanumeric characters or '-', and must start and end with an "
            "alphanumeric character"
        )
    return errs


@dataclass
class OwnerReference:
    api_version: str = ""
    kind: str = ""
    name: str = ""
    uid: str = ""
    controller: bool = False
    block_owner_deletion: bool = False

    def to_dict(self) -> dict:
        return {
            "apiVersion": self.api_version,
            "kind": self.kind,
            "name": self.name,
            "uid": self.uid,
            "controller": self.controller,
            "blockOwnerDeletion": self.block_owner_deletion,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "OwnerReference":
        return cls(
            api_version=d.get("apiVersion", ""),
            kind=d.get("kind", ""),
            name=d.get("name", ""),
            uid=d.get("uid", ""),
            controller=bool(d.get("controller", False)),
            block_owner_deletion=bool(d.get("blockOwnerDeletion", False)),
        )


@dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = ""
    labels: dict[str, str] = field(default_factory=dict)
    annotations: dict[str, str] = field(default_factory=dict)
    uid: str = ""
    resource_version: str = ""
    generation: int = 0
    creation_timestamp: Optional[float] = None
    deletion_timestamp: Optional[float] = None
    owner_references: list[OwnerReference] = field(default_factory=list)
    finalizers: list[str] = field(default_factory=list)

    def to_dict(self) -> dict:
        d: dict[str, Any] = {}
        if self.name:
            d["name"] = self.name
        if self.namespace:
            d["namespace"] = self.namespace
        if self.labels:
            d["labels"] = dict(self.labels)
        if self.annotations:
            d["annotations"] = dict(self.annotations)
        if self.uid:
            d["uid"] = self.uid
        if self.resource_version:
            d["resourceVersion"] = self.resource_version
        if self.generation:
            d["generation"] = self.generation
        if self.creation_timestamp is not None:
            d["creationTimestamp"] = self.creation_timestamp
        if self.deletion_timestamp is not None:
            d["deletionTimestamp"] = self.deletion_timestamp
        if self.owner_references:
            d["ownerReferences"] = [r.to_dict() for r in self.owner_references]
        if self.finalizers:
            d["finalizers"] = list(self.finalizers)
        return d

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "ObjectMeta":
        d = d or {}
        return cls(
            name=d.get("name", ""),
            namespace=d.get("namespace", ""),
            labels=dict(d.get("labels") or {}),
            annotations=dict(d.get("annotations") or {}),
            uid=d.get("uid", ""),
            resource_version=d.get("resourceVersion", ""),
            generation=int(d.get("generation", 0) or 0),
            creation_timestamp=d.get("creationTimestamp"),
            deletion_timestamp=d.get("deletionTimestamp"),
            owner_references=[
                OwnerReference.from_dict(r) for r in d.get("ownerReferences") or []
            ],
            finalizers=list(d.get("finalizers") or []),
        )


class KubeObject:
    """A generic Kubernetes object: typed metadata + dict payload.

    The payload keys (``spec``, ``status``, ``data`` ...) mirror the JSON an
    apiserver stores, so golden-object tests compare plain dicts, and the
    in-memory API server round-trips without information loss.
    """

    def __init__(
        self,
        api_version: str = "",
        kind: str = "",
        metadata: Optional[ObjectMeta] = None,
        **payload: Any,
    ):
        self.api_version = api_version
        self.kind = kind
        self.metadata = metadata or ObjectMeta()
        self.payload: dict[str, Any] = dict(payload)

    # Convenience accessors for the common payload members.
    @property
    def spec(self) -> dict:
        return self.payload.setdefault("spec", {})

    @spec.setter
    def spec(self, value: dict) -> None:
        self.payload["spec"] = value

    @property
    def status(self) -> dict:
        return self.payload.setdefault("status", {})

    @status.setter
    def status(self, value: dict) -> None:
        self.payload["status"] = value

    @property
    def data(self) -> dict:
        return self.payload.setdefault("data", {})

    @data.setter
    def data(self, value: dict) -> None:
        self.payload["data"] = value

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace

    def to_dict(self) -> dict:
        d = {
            "apiVersion": self.api_version,
            "kind": self.kind,
            "metadata": self.metadata.to_dict(),
        }
        for k, v in self.payload.items():
            # Empty payload members are omitted, the way an apiserver omits
            # empty optional fields — so merely reading `.spec` (whose getter
            # installs an empty dict for ergonomic mutation) never changes
            # the serialized form or equality.
            if v is None or v == {}:
                continue
            d[k] = copy.deepcopy(v)
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "KubeObject":
        payload = {
            k: copy.deepcopy(v)
            for k, v in d.items()
            if k not in ("apiVersion", "kind", "metadata")
        }
        return cls(
            api_version=d.get("apiVersion", ""),
            kind=d.get("kind", ""),
            metadata=ObjectMeta.from_dict(d.get("metadata")),
            **payload,
        )

    def deep_copy(self) -> "KubeObject":
        return KubeObject.from_dict(self.to_dict())

    def __repr__(self) -> str:  # pragma: no cover
        return f"<KubeObject {self.kind} {self.metadata.namespace}/{self.metadata.name}>"

    def __eq__(self, other: object) -> bool:
        if not isinstance(other, KubeObject):
            return NotImplemented
        return self.to_dict() == other.to_dict()


def new_controller_ref(owner: Any, api_version: str, kind: str) -> OwnerReference:
    """Build the controller OwnerReference for objects created for ``owner``.

    Reference analog: metav1.NewControllerRef as called in
    /root/reference/v2/pkg/controller/mpi_job_controller.go:1124 etc.
    """
    meta = owner.metadata if hasattr(owner, "metadata") else owner
    return OwnerReference(
        api_version=api_version,
        kind=kind,
        name=meta.name,
        uid=meta.uid,
        controller=True,
        block_owner_deletion=True,
    )


def get_controller_of(obj: KubeObject) -> Optional[OwnerReference]:
    """Return the controlling OwnerReference, if any.

    Reference analog: metav1.GetControllerOf in
    /root/reference/v2/pkg/controller/mpi_job_controller.go:1044.
    """
    for ref in obj.metadata.owner_references:
        if ref.controller:
            return ref
    return None
