"""Client-go-style retry helpers with injectable time.

Analog of k8s.io/client-go/util/retry (RetryOnConflict / OnError over a
wait.Backoff).  Every control-plane writer in the operator — controller
status updates, scheduler binds, queue suspend/status patches, the pod
runner's node binding — goes through these helpers instead of hand-rolled
``for attempt in (1, 2)`` loops, so conflict storms (real or injected by
the chaos engine) degrade into bounded, jittered backoff instead of
immediate give-up.

All sleeping funnels through the module-level :func:`sleep`, which tests
and the chaos harness may monkeypatch (or callers may inject per call);
the jitter draws from an injectable ``random.Random`` so chaos runs stay
replayable from their seed.
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass
from typing import Callable, Iterator, Optional

from .apiserver import ConflictError

# Module-level injectable sleep: the single chokepoint for every pause in
# controller/scheduler/queue code (tests/test_lint.py bans bare
# ``time.sleep`` there).  Reassign or monkeypatch to accelerate tests.
sleep: Callable[[float], None] = time.sleep


@dataclass(frozen=True)
class Backoff:
    """wait.Backoff analog: capped, jittered exponential backoff.

    ``steps`` is the number of *attempts* (not retries); ``duration`` the
    base delay before the second attempt; each subsequent delay multiplies
    by ``factor`` up to ``cap``; ``jitter`` adds up to that fraction of
    the delay, drawn from ``rng``.
    """

    steps: int = 4
    duration: float = 0.01
    factor: float = 5.0
    jitter: float = 0.1
    cap: float = 1.0

    def delays(self, rng: Optional[random.Random] = None) -> Iterator[float]:
        """Yield the delay to sleep before each retry (steps - 1 values)."""
        duration = self.duration
        for _ in range(max(0, self.steps - 1)):
            delay = min(duration, self.cap)
            if self.jitter > 0:
                r = rng.random() if rng is not None else random.random()
                delay += delay * self.jitter * r
            yield delay
            duration = min(duration * self.factor, self.cap)


# client-go's retry.DefaultRetry / retry.DefaultBackoff values.
DEFAULT_RETRY = Backoff(steps=5, duration=0.01, factor=1.0, jitter=0.1)
DEFAULT_BACKOFF = Backoff(steps=4, duration=0.01, factor=5.0, jitter=0.1)


def on_error(
    backoff: Backoff,
    retriable: Callable[[BaseException], bool],
    fn: Callable[[], object],
    *,
    sleep: Optional[Callable[[float], None]] = None,
    rng: Optional[random.Random] = None,
):
    """Run ``fn`` up to ``backoff.steps`` times, sleeping between attempts.

    Exceptions for which ``retriable`` returns False propagate
    immediately; the last retriable exception propagates once attempts
    are exhausted.  Returns ``fn``'s result on success.
    """
    do_sleep = globals()["sleep"] if sleep is None else sleep
    delays = backoff.delays(rng)
    while True:
        try:
            return fn()
        except Exception as exc:
            if not retriable(exc):
                raise
            delay = next(delays, None)
            if delay is None:
                raise
            do_sleep(delay)


def retry_on_conflict(
    fn: Callable[[], object],
    backoff: Backoff = DEFAULT_RETRY,
    *,
    sleep: Optional[Callable[[float], None]] = None,
    rng: Optional[random.Random] = None,
):
    """RetryOnConflict analog: re-run ``fn`` while it raises ConflictError.

    ``fn`` must re-read the object each attempt — retrying a write of a
    stale resourceVersion just conflicts again.
    """
    return on_error(
        backoff, lambda exc: isinstance(exc, ConflictError), fn, sleep=sleep, rng=rng
    )
