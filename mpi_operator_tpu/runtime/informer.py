"""Shared informers and listers.

Reference analog: the generated informer/lister machinery in
/root/reference/v2/pkg/client/informers + k8s.io/client-go informers, as
wired in mpi_job_controller.go:249-347 (event handlers) and :355-377
(WaitForCacheSync before workers start).

Each informer keeps a local cache (the lister's view) fed by an apiserver
watch stream.  Event delivery is *pumped*: ``pump()`` applies buffered
watch events to the cache and fires handlers.  Tests pump synchronously
for determinism; the operator process runs a pump loop in a thread.  This
mirrors the real informer property that the cache can lag the apiserver,
which is exactly what the reference's deep-copy-before-mutate discipline
(mpi_job_controller.go:475-478) is guarding against.
"""

from __future__ import annotations

import copy
import time
from dataclasses import dataclass
from typing import Callable, Optional

from ..utils import profiling
from . import locktrace
from .apiserver import (
    ADDED,
    DELETED,
    ApiError,
    GoneError,
    InMemoryAPIServer,
    match_labels,
)


# -- built-in indexers --------------------------------------------------
# client-go cache.Indexers analog: secondary key maps maintained
# incrementally on every cache mutation, so grouped reads (pods by
# phase, objects by namespace) cost O(groups) instead of a full scan
# with a deep copy per object.

def _namespace_of(obj: dict) -> str:
    return (obj.get("metadata") or {}).get("namespace", "")


def _phase_of(obj: dict) -> str:
    # Mirrors statemetrics pod-phase semantics: no phase yet == Pending.
    return (obj.get("status") or {}).get("phase") or "Pending"


DEFAULT_INDEXERS: dict[str, Callable[[dict], str]] = {
    "namespace": _namespace_of,
    "phase": _phase_of,
}


def split_key(key: str) -> tuple[str, str]:
    """"namespace/name" -> (namespace, name) (cache.SplitMetaNamespaceKey)."""
    if "/" in key:
        ns, name = key.split("/", 1)
        return ns, name
    return "", key


def meta_namespace_key(obj: dict) -> str:
    meta = obj.get("metadata") or {}
    ns = meta.get("namespace", "")
    name = meta.get("name", "")
    return f"{ns}/{name}" if ns else name


@dataclass
class EventHandler:
    on_add: Optional[Callable[[dict], None]] = None
    on_update: Optional[Callable[[dict, dict], None]] = None
    on_delete: Optional[Callable[[dict], None]] = None


class Lister:
    """Read-only view over an informer cache (namespace/name keyed dicts)."""

    def __init__(self, informer: "Informer"):
        self._informer = informer

    def get(self, namespace: str, name: str) -> Optional[dict]:
        return self._informer.cache_get(namespace, name)

    def list(
        self,
        namespace: Optional[str] = None,
        label_selector: Optional[dict[str, str]] = None,
    ) -> list[dict]:
        return self._informer.cache_list(namespace, label_selector)

    def by_index(self, index: str, value: str) -> list[dict]:
        """Objects whose indexer maps to ``value`` (cache.Indexer.ByIndex
        analog) — no full-cache scan."""
        return self._informer.cache_by_index(index, value)

    def index_counts(self, index: str) -> dict[str, int]:
        """``{index value: object count}`` without copying any object —
        the cheap path for by-phase/by-namespace gauges."""
        return self._informer.cache_index_counts(index)


class Informer:
    def __init__(
        self,
        api: InMemoryAPIServer,
        resource: str,
        namespace: str = "",
        resync_interval: Optional[float] = None,
        clock: Callable[[], float] = time.monotonic,
        profiler: Optional[profiling.PhaseProfiler] = None,
        indexers: Optional[dict[str, Callable[[dict], str]]] = None,
    ):
        self._api = api
        self.resource = resource
        self.namespace = namespace  # "" = cluster-wide (server.go:139-147 analog)
        # Reflector resyncPeriod analog: when set, pump() periodically
        # relists so events lost in flight (a lossy watch under fault
        # injection) cannot leave the cache stale forever.
        self.resync_interval = resync_interval
        self._clock = clock
        self.profiler = profiler
        self._lock = locktrace.rlock(f"informer.{resource}")
        self._cache: dict[str, dict] = {}
        self._indexers = dict(DEFAULT_INDEXERS if indexers is None else indexers)
        # index name -> index value -> cache keys
        self._indexes: dict[str, dict[str, set[str]]] = {
            name: {} for name in self._indexers
        }
        self._handlers: list[EventHandler] = []
        self._watch = None
        self._synced = False
        self._stopped = False
        self._need_resync = False
        self._last_sync = clock()
        self.lister = Lister(self)

    # -- cache reads -----------------------------------------------------

    def cache_get(self, namespace: str, name: str) -> Optional[dict]:
        with self._lock:
            obj = self._cache.get(f"{namespace}/{name}" if namespace else name)
            return None if obj is None else _deep_copy(obj)

    def cache_list(
        self,
        namespace: Optional[str] = None,
        label_selector: Optional[dict[str, str]] = None,
    ) -> list[dict]:
        with self._lock:
            if self.profiler is not None:
                self.profiler.record_scan(self.resource, len(self._cache))
            out = []
            for obj in self._cache.values():
                meta = obj.get("metadata") or {}
                if namespace is not None and meta.get("namespace", "") != namespace:
                    continue
                if not match_labels(label_selector, meta.get("labels") or {}):
                    continue
                out.append(_deep_copy(obj))
            out.sort(
                key=lambda o: (
                    o["metadata"].get("namespace", ""),
                    o["metadata"]["name"],
                )
            )
            return out

    def cache_by_index(self, index: str, value: str) -> list[dict]:
        with self._lock:
            keys = self._indexes[index].get(value, ())
            return sorted(
                (_deep_copy(self._cache[k]) for k in keys if k in self._cache),
                key=lambda o: (
                    o["metadata"].get("namespace", ""),
                    o["metadata"]["name"],
                ),
            )

    def cache_index_counts(self, index: str) -> dict[str, int]:
        with self._lock:
            return {
                value: len(keys)
                for value, keys in self._indexes[index].items()
                if keys
            }

    # -- index maintenance (call with self._lock held) -------------------

    def _index_insert(self, key: str, obj: dict) -> None:
        for name, indexer in self._indexers.items():
            self._indexes[name].setdefault(indexer(obj), set()).add(key)

    def _index_discard(self, key: str, obj: Optional[dict]) -> None:
        if obj is None:
            return
        for name, indexer in self._indexers.items():
            value = indexer(obj)
            keys = self._indexes[name].get(value)
            if keys is not None:
                keys.discard(key)
                if not keys:
                    del self._indexes[name][value]

    def _rebuild_indexes(self) -> None:
        self._indexes = {name: {} for name in self._indexers}
        for key, obj in self._cache.items():
            self._index_insert(key, obj)

    # -- lifecycle -------------------------------------------------------

    def add_event_handler(self, handler: EventHandler) -> None:
        # The pump loop runs on its own thread: registration must not
        # race an in-flight handler iteration (list.append is atomic in
        # CPython, but the guarded/unguarded split is exactly what the
        # TPU401 checker bans — one discipline everywhere).
        with self._lock:
            self._handlers.append(handler)

    def _handlers_snapshot(self) -> list[EventHandler]:
        """Handlers as of now; iterate the snapshot so delivery never
        holds the cache lock and never races add_event_handler."""
        with self._lock:
            return list(self._handlers)

    def set_resync_interval(self, seconds: Optional[float]) -> None:
        """Arm/change the reflector resync period (pump reads it under
        the lock; a cross-thread bare-attribute write would race)."""
        with self._lock:
            self.resync_interval = seconds

    def _in_scope(self, obj: dict) -> bool:
        return not self.namespace or (obj.get("metadata") or {}).get(
            "namespace", ""
        ) == self.namespace

    def start(self) -> None:
        """Open the watch, then load the initial listing into the cache.

        Opening the watch first guarantees no lost updates: anything that
        changes between list and first pump arrives as a watch event.
        Namespace-scoped informers open namespace-scoped watches/lists so
        RBAC-scoped deployments never need cluster-wide permissions.
        Re-entrant (leadership regained after a step-down): the fresh list
        *replaces* the previous term's cache, and objects that disappeared
        while we were not watching fire on_delete instead of lingering as
        ghosts.
        """
        with self._lock:
            if self._watch is not None:
                return
            self._stopped = False
        self.resync()

    def resync(self) -> None:
        """(Re)open the watch and replace the cache from a fresh list.

        Reflector ListAndWatch relist analog: called at start, after the
        watch reports 410 Gone (compaction), and on the periodic resync
        interval.  Objects that vanished fire on_delete; everything else
        re-fires on_add (no-op adds collapse in the workqueue, as in
        client-go's resync).  Raises ApiError if the relist itself fails;
        pump() treats that as "still stale, retry next round".
        """
        with self._lock:
            if self._stopped:
                return
            old_watch, self._watch = self._watch, None
        if old_watch is not None:
            old_watch.stop()
        ns = self.namespace or None
        watch = self._api.watch(self.resource, namespace=ns)
        # REST watches already paid for a baseline LIST (their 410
        # resume mirror); reuse it instead of issuing a second full
        # LIST per resource against the apiserver.
        try:
            if hasattr(watch, "baseline"):
                listing = watch.baseline()
            else:
                listing = self._api.list(self.resource, ns)
        except ApiError:
            watch.stop()
            raise
        with self._lock:
            if self._stopped:
                watch.stop()
                return
            self._watch = watch
            fresh = {
                meta_namespace_key(obj): obj
                for obj in listing
                if self._in_scope(obj)
            }
            removed = [
                obj for key, obj in self._cache.items() if key not in fresh
            ]
            self._cache = fresh
            self._rebuild_indexes()
            self._synced = True
            self._need_resync = False
            self._last_sync = self._clock()
        # Handlers fire outside the lock (a snapshot: registration may
        # race the relist).
        handlers = self._handlers_snapshot()
        for obj in removed:
            for h in handlers:
                if h.on_delete:
                    h.on_delete(_deep_copy(obj))
        for obj in self.cache_list():
            for h in handlers:
                if h.on_add:
                    h.on_add(obj)

    @property
    def has_synced(self) -> bool:
        return self._synced

    def pump(self) -> int:
        """Apply buffered watch events to the cache; fire handlers.

        Returns the number of events processed.  Events already reflected in
        the initial list (same resourceVersion) collapse into no-op updates,
        which handlers still see — the workqueue dedups, as in client-go.
        """
        # Snapshot under the lock: stop() may null the watch concurrently
        # (the pump loop is not joined before stop_all at step-down), and
        # _last_sync/_synced/resync_interval are written by resync() and
        # set_resync_interval() on other threads.
        with self._lock:
            watch = self._watch
            stale = self._need_resync
            synced = self._synced
            if not stale and self.resync_interval is not None:
                stale = (
                    self._clock() - self._last_sync >= self.resync_interval
                )
        if watch is None:
            if not synced:
                raise RuntimeError(
                    f"informer for {self.resource} not started; call start() first"
                )
            return 0  # started, then stopped: clean shutdown
        if stale:
            with self._lock:
                self._need_resync = True  # sticky until a relist succeeds
            try:
                self.resync()
            except ApiError:
                return 0  # apiserver unavailable; retry next pump
            with self._lock:
                watch = self._watch
            if watch is None:
                return 0
        handlers = self._handlers_snapshot()
        try:
            events = watch.drain()
        except GoneError:
            # Compacted away mid-stream: the buffer is suspect; relist on
            # the next pump round (keeps this round cheap and non-raising).
            with self._lock:
                self._need_resync = True
            return 0
        for event in events:
            if not self._in_scope(event.object):
                continue
            key = meta_namespace_key(event.object)
            with self._lock:
                old = self._cache.get(key)
                self._index_discard(key, old)
                if event.type == DELETED:
                    self._cache.pop(key, None)
                else:
                    self._cache[key] = event.object
                    self._index_insert(key, event.object)
            if self.profiler is not None:
                self.profiler.observe_delivery(event.emitted_at)
            # Handlers run with the event's emission stamp visible so an
            # enqueue they trigger can attribute the key to this event
            # (even across object->owner key mapping).
            profiling.set_current_event_stamp(event.emitted_at)
            try:
                if event.type == ADDED and old is None:
                    for h in handlers:
                        if h.on_add:
                            h.on_add(_deep_copy(event.object))
                elif event.type == DELETED:
                    for h in handlers:
                        if h.on_delete:
                            h.on_delete(
                                _deep_copy(old if old is not None else event.object)
                            )
                else:  # MODIFIED, or ADDED already seen via initial list
                    base = old if old is not None else event.object
                    for h in handlers:
                        if h.on_update:
                            h.on_update(_deep_copy(base), _deep_copy(event.object))
            finally:
                profiling.clear_current_event_stamp()
        return len(events)

    def stop(self) -> None:
        with self._lock:
            self._stopped = True
            if self._watch is not None:
                self._watch.stop()
                self._watch = None


class InformerFactory:
    """Shared informer factory (one informer per resource).

    Reference analog: kubeinformers.NewSharedInformerFactory +
    informers.NewSharedInformerFactory in app/server.go:139-147.
    """

    def __init__(
        self,
        api: InMemoryAPIServer,
        namespace: str = "",
        resync_interval: Optional[float] = None,
        profiler: Optional[profiling.PhaseProfiler] = None,
    ):
        self._api = api
        self.namespace = namespace
        self.resync_interval = resync_interval
        self.profiler = profiler
        self._informers: dict[str, Informer] = {}

    def informer(self, resource: str) -> Informer:
        if resource not in self._informers:
            self._informers[resource] = Informer(
                self._api,
                resource,
                namespace=self.namespace,
                resync_interval=self.resync_interval,
                profiler=self.profiler,
            )
        return self._informers[resource]

    def set_resync_interval(self, seconds: Optional[float]) -> None:
        """Apply a resync period to existing and future informers (lets a
        chaos harness arm resync on a controller-owned factory)."""
        self.resync_interval = seconds
        for informer in self._informers.values():
            informer.set_resync_interval(seconds)

    def start_all(self) -> None:
        for informer in self._informers.values():
            informer.start()

    def pump_all(self) -> int:
        """One pump round across all informers; returns events processed."""
        return sum(informer.pump() for informer in self._informers.values())

    def pump_until_quiet(self, max_rounds: int = 100) -> None:
        """Pump until no informer has buffered events (test convenience)."""
        for _ in range(max_rounds):
            if self.pump_all() == 0:
                return
        raise RuntimeError("informers did not quiesce")

    def stop_all(self) -> None:
        for informer in self._informers.values():
            informer.stop()


def _deep_copy(obj: dict) -> dict:
    return copy.deepcopy(obj)
