"""In-memory Kubernetes API server.

This is the storage + watch hub everything else plugs into.  It serves three
duties the reference splits across external machinery:

1. the *fake clientset* used by unit tests (reference analog:
   k8s.io/client-go/kubernetes/fake as wired in
   /root/reference/v2/pkg/controller/mpi_job_controller_test.go:149-150);
2. the *envtest* backend for integration tests — a real-enough apiserver
   with no kubelet, where tests flip pod phases by hand (reference analog:
   /root/reference/v2/test/integration/main_test.go:42-59);
3. the default backend the operator process runs against in local mode
   (a real-cluster REST backend can implement the same surface).

Semantics kept faithful to Kubernetes: monotonic ``resourceVersion`` with
optimistic-concurrency conflicts, uid assignment, AlreadyExists/NotFound
errors, label-selector list filtering, a ``status`` subresource that
ignores non-status changes, watch streams with ADDED/MODIFIED/DELETED
events, and cascading deletion along ownerReferences (the garbage
collector the reference leans on when an MPIJob is deleted).
"""

from __future__ import annotations

import copy
import itertools
import time
import uuid
from dataclasses import dataclass
from typing import Callable, Optional

from ..utils import profiling
from . import locktrace
from ..utils.logging import DEBUG, get_logger


class ApiError(Exception):
    code = 0
    reason = ""

    def __init__(self, resource: str, name: str, detail: str = ""):
        self.resource = resource
        self.name = name
        msg = f"{self.reason}: {resource} {name!r}"
        if detail:
            msg += f": {detail}"
        super().__init__(msg)


class NotFoundError(ApiError):
    code = 404
    reason = "NotFound"


class AlreadyExistsError(ApiError):
    code = 409
    reason = "AlreadyExists"


class ConflictError(ApiError):
    code = 409
    reason = "Conflict"


class InvalidError(ApiError):
    code = 422
    reason = "Invalid"


class ServerError(ApiError):
    """Transient 500 (apiserver hiccup); retriable via runtime/retry."""

    code = 500
    reason = "InternalError"


class ServerTimeoutError(ApiError):
    """504: the request may or may not have been applied (chaos treats it
    as not applied, the strictest interpretation for callers)."""

    code = 504
    reason = "Timeout"


class GoneError(ApiError):
    """410 Gone: the watch fell behind a compaction and must relist
    (client-go reflector's ``ResourceExpired`` relist trigger)."""

    code = 410
    reason = "Gone"


# Watch event types (k8s watch.EventType analog).
ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"


@dataclass(frozen=True)
class WatchEvent:
    type: str  # ADDED | MODIFIED | DELETED
    resource: str  # plural, e.g. "pods"
    object: dict  # full object at event time (deep copy)
    # profiling.clock() stamp at emission; the anchor for watch-to-
    # reconcile propagation latency.  Chaos-delayed watches re-deliver
    # the same event object, so an injected delay shows up honestly.
    emitted_at: Optional[float] = None


@dataclass(frozen=True)
class ResourceType:
    plural: str
    api_version: str
    kind: str


# The resource universe the operator touches (reference analog: the four
# clientsets created in v2/cmd/mpi-operator/app/server.go:262-285).
RESOURCES: dict[str, ResourceType] = {
    r.plural: r
    for r in [
        ResourceType("nodes", "v1", "Node"),
        ResourceType("pods", "v1", "Pod"),
        ResourceType("services", "v1", "Service"),
        ResourceType("configmaps", "v1", "ConfigMap"),
        ResourceType("secrets", "v1", "Secret"),
        ResourceType("events", "v1", "Event"),
        ResourceType("jobs", "batch/v1", "Job"),
        ResourceType("leases", "coordination.k8s.io/v1", "Lease"),
        ResourceType("podgroups", "scheduling.x-k8s.io/v1alpha1", "PodGroup"),
        ResourceType("tpujobs", "kubeflow.org/v2beta1", "TPUJob"),
        ResourceType("clusterqueues", "kubeflow.org/v2beta1", "ClusterQueue"),
        ResourceType("localqueues", "kubeflow.org/v2beta1", "LocalQueue"),
    ]
}


class Watch:
    """One watch stream: a buffered queue of events plus a stop handle."""

    def __init__(self, server: "InMemoryAPIServer", resource: str,
                 namespace: Optional[str] = None):
        self._server = server
        self.resource = resource
        self.namespace = namespace  # None = cluster-wide
        self._events: list[WatchEvent] = []
        self._cond = locktrace.condition("apiserver.watch")
        self._stopped = False

    def _deliver(self, event: WatchEvent) -> None:
        if self.namespace and (
            (event.object.get("metadata") or {}).get("namespace", "")
            != self.namespace
        ):
            return
        with self._cond:
            if self._stopped:
                return
            self._events.append(event)
            self._cond.notify_all()

    def drain(self) -> list[WatchEvent]:
        """Return and clear all buffered events (non-blocking)."""
        with self._cond:
            events, self._events = self._events, []
            return events

    def next(self, timeout: Optional[float] = None) -> Optional[WatchEvent]:
        """Block until an event arrives (or timeout / stop); None on neither."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while not self._events and not self._stopped:
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return None
                self._cond.wait(remaining)
            if self._events:
                return self._events.pop(0)
            return None

    def stop(self) -> None:
        with self._cond:
            self._stopped = True
            self._cond.notify_all()
        self._server._remove_watch(self)


def match_labels(selector: Optional[dict[str, str]], labels: dict[str, str]) -> bool:
    if not selector:
        return True
    return all(labels.get(k) == v for k, v in selector.items())


class InMemoryAPIServer:
    """Thread-safe in-memory object store with Kubernetes semantics."""

    def __init__(self, clock: Callable[[], float] = time.time):
        self._lock = locktrace.rlock("apiserver.store")
        self._clock = clock
        self._log = get_logger("apiserver")
        self._rv = itertools.count(1)
        # resource plural -> {(namespace, name) -> object dict}
        self._store: dict[str, dict[tuple[str, str], dict]] = {
            plural: {} for plural in RESOURCES
        }
        self._watches: list[Watch] = []
        # Recorded write actions, for reference-style "expected actions"
        # unit assertions (fixture pattern, mpi_job_controller_test.go:58-88).
        self.actions: list[tuple[str, str, str]] = []  # (verb, resource, ns/name)

    # -- helpers ---------------------------------------------------------

    def _meta(self, obj: dict) -> dict:
        return obj.setdefault("metadata", {})

    def _key(self, obj: dict) -> tuple[str, str]:
        meta = self._meta(obj)
        return meta.get("namespace", ""), meta.get("name", "")

    def _check_resource(self, resource: str) -> None:
        if resource not in self._store:
            raise NotFoundError("resources", resource, "unknown resource type")

    def _notify(self, type_: str, resource: str, obj: dict) -> None:
        event = WatchEvent(
            type_, resource, copy.deepcopy(obj), emitted_at=profiling.clock()
        )
        for watch in list(self._watches):
            if watch.resource == resource:
                watch._deliver(event)

    def _record(self, verb: str, resource: str, obj: dict) -> None:
        ns, name = self._key(obj)
        self.actions.append((verb, resource, f"{ns}/{name}"))
        # Request log (kube-apiserver audit-log analog): every write verb
        # at debug, so `--log-level debug` shows the full mutation stream.
        if self._log.enabled_for(DEBUG):
            self._log.debug(
                "%s %s %s/%s", verb, resource, ns, name,
                rv=(obj.get("metadata") or {}).get("resourceVersion", ""),
            )

    def clear_actions(self) -> None:
        # Writers append via _record() under self._lock; clearing must
        # take the same lock or it races an in-flight write (TPU401).
        with self._lock:
            self.actions.clear()

    # -- CRUD ------------------------------------------------------------

    def _admit(self, resource: str, obj: dict) -> dict:
        """CRD structural-schema admission (real-apiserver analog): writes
        to CRD-backed resources (TPUJob, ClusterQueue, LocalQueue) are
        validated against the generated openAPIV3Schema — a malformed pod
        template or quota entry fails here, at create/update time, not
        later at pod-creation time — and unknown fields are pruned the
        way a real apiserver prunes them (typos never reach storage)."""
        from ..api.schema import admission_schema_for, prune, validate_schema

        admission = admission_schema_for(resource)
        if admission is None:
            return obj
        schema, path = admission
        errors = validate_schema(obj, schema, path=path)
        if errors:
            name = self._key(obj)[1]
            shown = "; ".join(errors[:5])
            if len(errors) > 5:
                shown += f" (+{len(errors) - 5} more)"
            raise InvalidError(resource, name, shown)
        return prune(obj, schema)

    def create(self, resource: str, obj: dict) -> dict:
        self._check_resource(resource)
        obj = copy.deepcopy(obj)
        obj = self._admit(resource, obj)
        with self._lock:
            key = self._key(obj)
            if not key[1]:
                raise InvalidError(resource, "", "metadata.name is required")
            if key in self._store[resource]:
                raise AlreadyExistsError(resource, key[1])
            meta = self._meta(obj)
            meta.setdefault("uid", str(uuid.uuid4()))
            meta["resourceVersion"] = str(next(self._rv))
            meta.setdefault("creationTimestamp", self._clock())
            rt = RESOURCES[resource]
            obj.setdefault("apiVersion", rt.api_version)
            obj.setdefault("kind", rt.kind)
            self._store[resource][key] = obj
            self._record("create", resource, obj)
            self._notify(ADDED, resource, obj)
            return copy.deepcopy(obj)

    def get(self, resource: str, namespace: str, name: str) -> dict:
        self._check_resource(resource)
        with self._lock:
            obj = self._store[resource].get((namespace, name))
            if obj is None:
                raise NotFoundError(resource, f"{namespace}/{name}")
            return copy.deepcopy(obj)

    def list(
        self,
        resource: str,
        namespace: Optional[str] = None,
        label_selector: Optional[dict[str, str]] = None,
    ) -> list[dict]:
        self._check_resource(resource)
        with self._lock:
            out = []
            for (ns, _), obj in self._store[resource].items():
                if namespace is not None and ns != namespace:
                    continue
                if not match_labels(label_selector, self._meta(obj).get("labels") or {}):
                    continue
                out.append(copy.deepcopy(obj))
            out.sort(key=lambda o: (o["metadata"].get("namespace", ""), o["metadata"]["name"]))
            return out

    def _update(self, resource: str, obj: dict, *, status_only: bool) -> dict:
        self._check_resource(resource)
        obj = copy.deepcopy(obj)
        if not status_only:
            obj = self._admit(resource, obj)
        with self._lock:
            key = self._key(obj)
            current = self._store[resource].get(key)
            if current is None:
                raise NotFoundError(resource, key[1])
            rv = self._meta(obj).get("resourceVersion")
            current_rv = current["metadata"]["resourceVersion"]
            if rv and rv != current_rv:
                raise ConflictError(
                    resource, key[1], f"resourceVersion {rv} != {current_rv}"
                )
            if status_only:
                # Status subresource: only .status changes; spec/meta kept.
                merged = copy.deepcopy(current)
                if "status" in obj:
                    merged["status"] = obj["status"]
                else:
                    merged.pop("status", None)
                new = merged
            else:
                # Spec update: status is carried over from storage (writes to
                # the main resource never change status, like k8s).
                new = obj
                if "status" in current:
                    new["status"] = copy.deepcopy(current["status"])
                else:
                    new.pop("status", None)
                # Immutable fields survive from storage.
                for immutable in ("uid", "creationTimestamp"):
                    if immutable in current["metadata"]:
                        new["metadata"][immutable] = current["metadata"][immutable]
            new["metadata"]["resourceVersion"] = str(next(self._rv))
            self._store[resource][key] = new
            self._record("update_status" if status_only else "update", resource, new)
            self._notify(MODIFIED, resource, new)
            return copy.deepcopy(new)

    def update(self, resource: str, obj: dict) -> dict:
        return self._update(resource, obj, status_only=False)

    def update_status(self, resource: str, obj: dict) -> dict:
        return self._update(resource, obj, status_only=True)

    def delete(self, resource: str, namespace: str, name: str) -> None:
        self._check_resource(resource)
        with self._lock:
            obj = self._store[resource].pop((namespace, name), None)
            if obj is None:
                raise NotFoundError(resource, f"{namespace}/{name}")
            # Deletion is a write: the DELETED event carries a fresh
            # resourceVersion (kube semantics — watch streams stay
            # rv-monotonic, which the HTTP frontend's watch cache needs).
            obj["metadata"]["resourceVersion"] = str(next(self._rv))
            self._record("delete", resource, obj)
            self._notify(DELETED, resource, obj)
            self._garbage_collect(obj["metadata"].get("uid"), namespace)

    def _garbage_collect(self, owner_uid: Optional[str], namespace: str) -> None:
        """Cascading deletion along ownerReferences (kube GC analog)."""
        if not owner_uid:
            return
        for resource, store in self._store.items():
            doomed = [
                (ns, name)
                for (ns, name), obj in store.items()
                if ns == namespace
                and any(
                    ref.get("uid") == owner_uid
                    for ref in obj["metadata"].get("ownerReferences") or []
                )
            ]
            for ns, name in doomed:
                # Recursive: children of children go too.
                try:
                    self.delete(resource, ns, name)
                except NotFoundError:
                    pass

    # -- watch -----------------------------------------------------------

    def watch(self, resource: str, namespace: Optional[str] = None) -> Watch:
        """Open a watch; ``namespace`` scopes delivery (None =
        cluster-wide), mirroring the kube backend's namespaced watch
        paths so RBAC-scoped deployments work identically."""
        self._check_resource(resource)
        watch = Watch(self, resource, namespace)
        with self._lock:
            self._watches.append(watch)
        return watch

    def _remove_watch(self, watch: Watch) -> None:
        with self._lock:
            if watch in self._watches:
                self._watches.remove(watch)
