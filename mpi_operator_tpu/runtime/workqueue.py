"""Rate-limited workqueue.

Reference analog: k8s.io/client-go/util/workqueue as used by the controller
(/root/reference/v2/pkg/controller/mpi_job_controller.go:237, :294,
:389-446): deduplicating delay-capable queue + per-item exponential backoff
rate limiter, so a failing TPUJob retries with backoff while a hot TPUJob
only ever occupies one queue slot.

Semantics kept from client-go:
- an item added while queued is deduplicated;
- an item added while *being processed* is remembered (dirty set) and
  re-queued when ``done()`` is called;
- ``add_rate_limited`` delays re-adds exponentially per item until
  ``forget()`` resets the failure count;
- ``shutdown()`` unblocks all getters.

Passing a ``registry`` arms the client-go workqueue metric set
(``workqueue_depth``, ``adds_total``, ``queue_duration_seconds``,
``work_duration_seconds``, ``retries_total``, ``unfinished_work_seconds``,
``longest_running_processor_seconds`` analogs), every series labeled by
queue ``name``.
"""

from __future__ import annotations

import heapq
import time
from typing import Any, Hashable, Optional

from ..utils import metrics
from . import locktrace

# Queue/work latencies span informer-event microseconds up to multi-second
# syncs against a real apiserver: wider-than-default buckets at both ends
# (client-go uses 1e-8..~10s exponential buckets for the same reason).
_LATENCY_BUCKETS = (
    1e-6, 1e-5, 1e-4, 1e-3, 0.01, 0.1, 0.5, 1.0, 5.0, 10.0, 30.0, 60.0,
)


class WorkqueueMetrics:
    """The client-go workqueue metric set, bound to one registry.

    One instance can serve many queues (series split by the ``name``
    label), matching client-go's MetricsProvider shape. All clock reads
    come from the owning queue so tests can drive time.
    """

    def __init__(self, registry: metrics.Registry):
        self.depth = metrics.new_gauge(
            "tpu_operator_workqueue_depth",
            "Current depth of the workqueue",
            ("name",),
            registry,
        )
        self.adds = metrics.new_counter(
            "tpu_operator_workqueue_adds_total",
            "Total number of adds handled by the workqueue",
            ("name",),
            registry,
        )
        self.queue_duration = metrics.new_histogram(
            "tpu_operator_workqueue_queue_duration_seconds",
            "How long an item stays in the workqueue before being requested",
            ("name",),
            registry,
            buckets=_LATENCY_BUCKETS,
        )
        self.work_duration = metrics.new_histogram(
            "tpu_operator_workqueue_work_duration_seconds",
            "How long processing an item from the workqueue takes",
            ("name",),
            registry,
            buckets=_LATENCY_BUCKETS,
        )
        self.retries = metrics.new_counter(
            "tpu_operator_workqueue_retries_total",
            "Total number of rate-limited re-adds (retries)",
            ("name",),
            registry,
        )
        self.unfinished_work = metrics.new_gauge(
            "tpu_operator_workqueue_unfinished_work_seconds",
            "Seconds of work in progress that has not been observed by "
            "work_duration yet (large values indicate stuck threads)",
            ("name",),
            registry,
        )
        self.longest_running = metrics.new_gauge(
            "tpu_operator_workqueue_longest_running_processor_seconds",
            "Seconds the single longest-running processor has held its "
            "item (unfinished_work aggregates; this isolates one stuck "
            "worker from many busy ones)",
            ("name",),
            registry,
        )


class ItemExponentialFailureRateLimiter:
    """Per-item exponential backoff (client-go default: 5ms base, 1000s cap)."""

    def __init__(self, base_delay: float = 0.005, max_delay: float = 1000.0):
        self.base_delay = base_delay
        self.max_delay = max_delay
        self._failures: dict[Hashable, int] = {}
        self._lock = locktrace.lock("workqueue.ratelimiter")

    def when(self, item: Hashable) -> float:
        with self._lock:
            failures = self._failures.get(item, 0)
            self._failures[item] = failures + 1
            delay = self.base_delay * (2**failures)
            return min(delay, self.max_delay)

    def forget(self, item: Hashable) -> None:
        with self._lock:
            self._failures.pop(item, None)

    def num_requeues(self, item: Hashable) -> int:
        with self._lock:
            return self._failures.get(item, 0)


class RateLimitingQueue:
    def __init__(
        self,
        rate_limiter: Optional[ItemExponentialFailureRateLimiter] = None,
        name: str = "",
        clock=time.monotonic,
        registry: Optional[metrics.Registry] = None,
        queue_metrics: Optional[WorkqueueMetrics] = None,
    ):
        self.name = name
        self._rate_limiter = rate_limiter or ItemExponentialFailureRateLimiter()
        self._clock = clock
        self._cond = locktrace.condition(f"workqueue.{name or 'default'}")
        self._queue: list[Any] = []  # FIFO of ready items
        self._queued: set[Hashable] = set()  # dedup: in _queue or delayed
        self._processing: set[Hashable] = set()
        self._dirty: set[Hashable] = set()  # re-add requested while processing
        self._delayed: list[tuple[float, int, Any]] = []  # heap (ready_at, seq, item)
        self._seq = 0
        self._shutdown = False
        # Instrumentation (client-go workqueue metrics analog). A shared
        # WorkqueueMetrics wins over a bare registry; both absent = no-op.
        self._metrics = queue_metrics
        if self._metrics is None and registry is not None:
            self._metrics = WorkqueueMetrics(registry)
        self._add_times: dict[Hashable, float] = {}  # queued-at, per item
        self._start_times: dict[Hashable, float] = {}  # processing-start
        if self._metrics is not None and registry is not None:
            # unfinished_work is a pull-model value: freshest at scrape.
            registry.on_scrape(self._update_unfinished_work)

    @property
    def metrics(self) -> Optional[WorkqueueMetrics]:
        """The bound WorkqueueMetrics, or None when unmetered."""
        return self._metrics

    # -- instrumentation hooks (no-ops when unmetered) -------------------

    def _on_enqueued(self, item: Hashable) -> None:
        """Item landed in the ready FIFO (fresh add, delayed promotion, or
        dirty re-queue). Caller holds self._cond."""
        if self._metrics is None:
            return
        self._metrics.adds.inc(1, self.name)
        self._add_times.setdefault(item, self._clock())
        self._metrics.depth.set(len(self._queue), self.name)

    def _on_get(self, item: Hashable) -> None:
        if self._metrics is None:
            return
        now = self._clock()
        added_at = self._add_times.pop(item, None)
        if added_at is not None:
            self._metrics.queue_duration.observe(now - added_at, self.name)
        self._start_times[item] = now
        self._metrics.depth.set(len(self._queue), self.name)

    def _on_done(self, item: Hashable) -> None:
        if self._metrics is None:
            return
        started_at = self._start_times.pop(item, None)
        if started_at is not None:
            self._metrics.work_duration.observe(
                self._clock() - started_at, self.name
            )

    def _update_unfinished_work(self) -> None:
        with self._cond:
            now = self._clock()
            running = [now - t for t in self._start_times.values()]
            self._metrics.unfinished_work.set(round(sum(running), 9), self.name)
            self._metrics.longest_running.set(
                round(max(running, default=0.0), 9), self.name
            )

    # -- core queue ------------------------------------------------------

    def add(self, item: Hashable) -> None:
        with self._cond:
            if self._shutdown:
                return
            if item in self._processing:
                self._dirty.add(item)
                return
            if item in self._queued:
                return
            self._queued.add(item)
            self._queue.append(item)
            self._on_enqueued(item)
            self._cond.notify()

    def add_after(self, item: Hashable, delay: float) -> None:
        if delay <= 0:
            self.add(item)
            return
        with self._cond:
            if self._shutdown:
                return
            self._seq += 1
            heapq.heappush(self._delayed, (self._clock() + delay, self._seq, item))
            self._cond.notify()

    def add_rate_limited(self, item: Hashable) -> None:
        if self._metrics is not None:
            self._metrics.retries.inc(1, self.name)
        self.add_after(item, self._rate_limiter.when(item))

    def forget(self, item: Hashable) -> None:
        self._rate_limiter.forget(item)

    def num_requeues(self, item: Hashable) -> int:
        return self._rate_limiter.num_requeues(item)

    def _promote_ready(self) -> Optional[float]:
        """Move due delayed items into the FIFO; return next wake-up delay."""
        now = self._clock()
        while self._delayed and self._delayed[0][0] <= now:
            _, _, item = heapq.heappop(self._delayed)
            if item in self._processing:
                self._dirty.add(item)
            elif item not in self._queued:
                self._queued.add(item)
                self._queue.append(item)
                self._on_enqueued(item)
        if self._delayed:
            return self._delayed[0][0] - now
        return None

    def get(self, timeout: Optional[float] = None) -> tuple[Any, bool]:
        """Return (item, shutdown). Blocks until an item is ready."""
        deadline = None if timeout is None else self._clock() + timeout
        with self._cond:
            while True:
                next_delay = self._promote_ready()
                if self._queue:
                    item = self._queue.pop(0)
                    self._queued.discard(item)
                    self._processing.add(item)
                    self._on_get(item)
                    return item, False
                if self._shutdown:
                    return None, True
                wait = next_delay
                if deadline is not None:
                    remaining = deadline - self._clock()
                    if remaining <= 0:
                        return None, False
                    wait = remaining if wait is None else min(wait, remaining)
                self._cond.wait(wait)

    def done(self, item: Hashable) -> None:
        with self._cond:
            self._processing.discard(item)
            self._on_done(item)
            if item in self._dirty:
                self._dirty.discard(item)
                if item not in self._queued:
                    self._queued.add(item)
                    self._queue.append(item)
                    self._on_enqueued(item)
                    self._cond.notify()

    def shutdown(self) -> None:
        with self._cond:
            self._shutdown = True
            self._cond.notify_all()

    def reset(self) -> None:
        """Re-arm a shut-down queue (leadership regained after step-down)."""
        with self._cond:
            self._shutdown = False

    @property
    def is_shutdown(self) -> bool:
        with self._cond:
            return self._shutdown

    def __len__(self) -> int:
        with self._cond:
            return len(self._queue)

    def pending_delayed(self) -> int:
        with self._cond:
            return len(self._delayed)

    def stats(self) -> dict:
        """Point-in-time queue health snapshot (the /debug/profile
        payload): depth, in-flight work, and how long the slowest
        processor has been holding its item.  Live values, not gauge
        reads, so it works on unmetered queues too (durations need
        metering — start times are only tracked then).

        All mutable state is copied in ONE critical section — a single
        consistent cut of the queue — and the derived math plus the
        metric-counter reads (which take the metrics' own locks) happen
        after release, keeping the condition's hold time flat no matter
        how many processors are in flight.
        """
        with self._cond:
            now = self._clock()
            depth = len(self._queue)
            delayed = len(self._delayed)
            processing = len(self._processing)
            start_times = list(self._start_times.values())
        running = [now - t for t in start_times]
        out = {
            "depth": depth,
            "delayed": delayed,
            "processing": processing,
            "unfinished_work_seconds": round(sum(running), 9),
            "longest_running_processor_seconds": round(
                max(running, default=0.0), 9
            ),
        }
        if self._metrics is not None:
            out["adds_total"] = self._metrics.adds.value(self.name)
            out["retries_total"] = self._metrics.retries.value(self.name)
        return out
