"""Typed clients over an API server.

Reference analog: the four clientsets the operator wires up in
/root/reference/v2/cmd/mpi-operator/app/server.go:262-285 (kubeClient,
mpiJobClient, volcanoClient, leaderElectionClient) — here a ``KubeClient``
(core+batch), a ``TPUJobClient`` (our CRD, generated-clientset analog of
v2/pkg/client/clientset/versioned), and a ``SchedulingClient`` (PodGroups).

All clients speak dicts to the backend and typed objects to callers.
"""

from __future__ import annotations

from typing import Optional

from ..api.v2beta1.types import TPUJob
from .apiserver import InMemoryAPIServer
from .objects import KubeObject


class ResourceClient:
    """Namespaced CRUD for one resource, KubeObject-typed."""

    def __init__(self, api: InMemoryAPIServer, resource: str, namespace: str):
        self._api = api
        self.resource = resource
        self.namespace = namespace

    def _localize(self, obj: KubeObject) -> dict:
        d = obj.to_dict()
        d["metadata"].setdefault("namespace", self.namespace)
        return d

    def create(self, obj: KubeObject) -> KubeObject:
        return KubeObject.from_dict(self._api.create(self.resource, self._localize(obj)))

    def get(self, name: str) -> KubeObject:
        return KubeObject.from_dict(self._api.get(self.resource, self.namespace, name))

    def list(self, label_selector: Optional[dict[str, str]] = None) -> list[KubeObject]:
        return [
            KubeObject.from_dict(d)
            for d in self._api.list(self.resource, self.namespace, label_selector)
        ]

    def update(self, obj: KubeObject) -> KubeObject:
        return KubeObject.from_dict(self._api.update(self.resource, self._localize(obj)))

    def update_status(self, obj: KubeObject) -> KubeObject:
        return KubeObject.from_dict(
            self._api.update_status(self.resource, self._localize(obj))
        )

    def delete(self, name: str) -> None:
        self._api.delete(self.resource, self.namespace, name)


class KubeClient:
    """Core/v1 + batch/v1 + coordination surface used by the operator."""

    def __init__(self, api: InMemoryAPIServer):
        self.api = api

    def pods(self, namespace: str) -> ResourceClient:
        return ResourceClient(self.api, "pods", namespace)

    def nodes(self) -> ResourceClient:
        # Nodes are cluster-scoped: the empty namespace is their home.
        return ResourceClient(self.api, "nodes", "")

    def services(self, namespace: str) -> ResourceClient:
        return ResourceClient(self.api, "services", namespace)

    def configmaps(self, namespace: str) -> ResourceClient:
        return ResourceClient(self.api, "configmaps", namespace)

    def secrets(self, namespace: str) -> ResourceClient:
        return ResourceClient(self.api, "secrets", namespace)

    def jobs(self, namespace: str) -> ResourceClient:
        return ResourceClient(self.api, "jobs", namespace)

    def events(self, namespace: str) -> ResourceClient:
        return ResourceClient(self.api, "events", namespace)

    def leases(self, namespace: str) -> ResourceClient:
        return ResourceClient(self.api, "leases", namespace)


class SchedulingClient:
    """Gang-scheduling PodGroups (volcano clientset analog)."""

    def __init__(self, api: InMemoryAPIServer):
        self.api = api

    def podgroups(self, namespace: str) -> ResourceClient:
        return ResourceClient(self.api, "podgroups", namespace)


class TPUJobResourceClient:
    """Namespaced CRUD for TPUJobs, TPUJob-typed."""

    def __init__(self, api: InMemoryAPIServer, namespace: str):
        self._api = api
        self.namespace = namespace

    def _localize(self, job: TPUJob) -> dict:
        d = job.to_dict()
        d["metadata"].setdefault("namespace", self.namespace)
        return d

    def create(self, job: TPUJob) -> TPUJob:
        return TPUJob.from_dict(self._api.create("tpujobs", self._localize(job)))

    def get(self, name: str) -> TPUJob:
        return TPUJob.from_dict(self._api.get("tpujobs", self.namespace, name))

    def list(self, label_selector: Optional[dict[str, str]] = None) -> list[TPUJob]:
        return [
            TPUJob.from_dict(d)
            for d in self._api.list("tpujobs", self.namespace, label_selector)
        ]

    def update(self, job: TPUJob) -> TPUJob:
        return TPUJob.from_dict(self._api.update("tpujobs", self._localize(job)))

    def update_status(self, job: TPUJob) -> TPUJob:
        return TPUJob.from_dict(self._api.update_status("tpujobs", self._localize(job)))

    def delete(self, name: str) -> None:
        self._api.delete("tpujobs", self.namespace, name)


class TPUJobClient:
    def __init__(self, api: InMemoryAPIServer):
        self.api = api

    def tpujobs(self, namespace: str) -> TPUJobResourceClient:
        return TPUJobResourceClient(self.api, namespace)
