"""Real-cluster Kubernetes REST backend.

The peer of :class:`~mpi_operator_tpu.runtime.apiserver.InMemoryAPIServer`
that speaks HTTP to an actual kube-apiserver. Same duck-typed surface
(``create/get/list/update/update_status/delete/watch``), same error
types, so the controller, informers, leader elector, and clients run
unchanged against a live cluster.

Reference analogs:
- config loading (kubeconfig / in-cluster):
  /root/reference/v2/cmd/mpi-operator/app/server.go:103-109
- the four clientsets this replaces:
  /root/reference/v2/cmd/mpi-operator/app/server.go:262-285
- informer watches against the cluster:
  /root/reference/v2/pkg/controller/mpi_job_controller.go:249-347

Implementation notes (stdlib only — no kubernetes pip package):

- One short-lived ``http.client`` connection per CRUD call; a long-lived
  streaming connection per watch.
- Watches keep a private mirror of the collection. The stream starts at
  the mirror's list resourceVersion, so ``watch()`` + a later ``list()``
  can never lose an update (the informer's watch-then-list discipline,
  informer.py:117-149). On ``410 Gone`` (resourceVersion compacted) the
  watch re-lists and emits synthetic ADDED/MODIFIED/DELETED events from
  the diff against its mirror — transparent resume, the client-go
  Reflector's relist behavior.
- Errors map from the apiserver's ``Status`` body by reason first, HTTP
  code second, onto the same exception types the in-memory server
  raises.
"""

from __future__ import annotations

import base64
import json
import os
import random
import ssl
import tempfile
import threading
import time
import urllib.parse
from dataclasses import dataclass, field
from typing import Optional

from .apiserver import (
    ADDED,
    DELETED,
    MODIFIED,
    RESOURCES,
    AlreadyExistsError,
    ApiError,
    ConflictError,
    InvalidError,
    NotFoundError,
    WatchEvent,
)
from ..utils.logging import get_logger

log = get_logger("kube")

BOOKMARK = "BOOKMARK"
ERROR = "ERROR"

SERVICE_ACCOUNT_ROOT = "/var/run/secrets/kubernetes.io/serviceaccount"


class ForbiddenError(ApiError):
    code = 403
    reason = "Forbidden"


class UnauthorizedError(ApiError):
    code = 401
    reason = "Unauthorized"


class ServerError(ApiError):
    """5xx / transport-level failure talking to the apiserver."""

    code = 500
    reason = "InternalError"


class TooManyRequestsError(ApiError):
    """429 after the client's retry budget is exhausted."""

    code = 429
    reason = "TooManyRequests"


_ERRORS_BY_REASON = {
    "NotFound": NotFoundError,
    "AlreadyExists": AlreadyExistsError,
    "Conflict": ConflictError,
    "Invalid": InvalidError,
    "Forbidden": ForbiddenError,
    "Unauthorized": UnauthorizedError,
}
_ERRORS_BY_CODE = {
    404: NotFoundError,
    409: ConflictError,
    422: InvalidError,
    403: ForbiddenError,
    401: UnauthorizedError,
    429: TooManyRequestsError,
}


class _TokenBucket:
    """Client-side flow control (client-go's TokenBucketRateLimiter analog,
    rest.Config QPS/Burst — reference options.go:69-70). ``qps <= 0``
    disables throttling. Callers over the rate queue fairly: the bucket
    balance goes negative and each further caller's wait grows by 1/qps."""

    def __init__(self, qps: float, burst: int):
        self.qps = float(qps)
        self.burst = max(1, int(burst))
        self._balance = float(self.burst)
        self._last = time.monotonic()
        self._lock = threading.Lock()

    def acquire(self) -> float:
        """Take one token, sleeping until it is due; returns the wait."""
        if self.qps <= 0:
            return 0.0
        with self._lock:
            now = time.monotonic()
            self._balance = min(
                float(self.burst),
                self._balance + (now - self._last) * self.qps,
            )
            self._last = now
            self._balance -= 1.0
            wait = 0.0 if self._balance >= 0 else -self._balance / self.qps
        if wait > 0:
            time.sleep(wait)
        return wait


# ---------------------------------------------------------------------------
# Config loading (kubeconfig + in-cluster)
# ---------------------------------------------------------------------------


@dataclass
class RestConfig:
    """Connection config for one apiserver (client-go rest.Config analog)."""

    host: str  # e.g. https://10.0.0.1:6443 or http://127.0.0.1:8001
    token: Optional[str] = None
    ca_file: Optional[str] = None
    client_cert_file: Optional[str] = None
    client_key_file: Optional[str] = None
    verify_tls: bool = True
    namespace: str = "default"  # default namespace from context / SA
    # Rotating credentials (exec plugins, projected SA tokens): called to
    # re-acquire the bearer token when ``token_expiry`` (epoch seconds)
    # passes or a request gets 401 — client-go's refresh behavior.
    token_refresher: Optional[object] = field(default=None, repr=False)
    token_expiry: Optional[float] = None
    # Files this config wrote itself (inline *-data fields); kept so the
    # tempfiles outlive the config object, and removed at process exit
    # (they can hold private keys).
    _owned_files: list = field(default_factory=list, repr=False)

    def refresh_token(self) -> bool:
        """Re-acquire the bearer token; returns True if it changed."""
        if self.token_refresher is None:
            return False
        old = self.token
        self.token, self.token_expiry = self.token_refresher()
        return self.token != old

    def current_token(self) -> Optional[str]:
        if (self.token_refresher is not None
                and self.token_expiry is not None
                and time.time() > self.token_expiry - 60):
            self.refresh_token()
        return self.token

    def ssl_context(self) -> Optional[ssl.SSLContext]:
        if not self.host.startswith("https"):
            return None
        if self.verify_tls:
            ctx = ssl.create_default_context(cafile=self.ca_file)
        else:
            ctx = ssl._create_unverified_context()  # noqa: S323
        if self.client_cert_file:
            ctx.load_cert_chain(self.client_cert_file, self.client_key_file)
        return ctx


def _materialize(data_b64: Optional[str], path: Optional[str],
                 owned: list) -> Optional[str]:
    """kubeconfig fields come as either a file path or inline base64 data;
    ssl wants paths, so inline data lands in a 0600 tempfile that is
    removed at process exit (it can hold a private key)."""
    if path:
        return path
    if not data_b64:
        return None
    # NamedTemporaryFile creates 0600 by default.
    f = tempfile.NamedTemporaryFile(mode="wb", suffix=".pem", delete=False)
    f.write(base64.b64decode(data_b64))
    f.close()
    owned.append(f.name)
    _cleanup_at_exit(f.name)
    return f.name


def _cleanup_at_exit(path: str) -> None:
    import atexit

    def rm():
        try:
            os.unlink(path)
        except OSError:
            pass

    atexit.register(rm)


def load_kubeconfig(path: Optional[str] = None,
                    context: Optional[str] = None) -> RestConfig:
    """Parse a kubeconfig file (server.go:103-109 BuildConfigFromFlags
    analog). ``path`` defaults to ``$KUBECONFIG`` then ``~/.kube/config``."""
    import yaml

    path = path or os.environ.get("KUBECONFIG") or os.path.expanduser(
        "~/.kube/config"
    )
    with open(path) as f:
        cfg = yaml.safe_load(f) or {}

    contexts = {e["name"]: e["context"] for e in cfg.get("contexts") or []}
    clusters = {e["name"]: e["cluster"] for e in cfg.get("clusters") or []}
    users = {e["name"]: e["user"] for e in cfg.get("users") or []}

    ctx_name = context or cfg.get("current-context")
    if not ctx_name or ctx_name not in contexts:
        raise ValueError(
            f"kubeconfig {path}: no usable context {ctx_name!r} "
            f"(have {sorted(contexts)})"
        )
    ctx = contexts[ctx_name]
    cluster = clusters.get(ctx.get("cluster", ""))
    if cluster is None or "server" not in cluster:
        raise ValueError(f"kubeconfig {path}: context {ctx_name!r} names "
                         f"unknown cluster {ctx.get('cluster')!r}")
    user = users.get(ctx.get("user", ""), {})

    owned: list = []
    refresher = None
    expiry = None
    token = user.get("token")
    if not token and user.get("tokenFile"):
        token_file = user["tokenFile"]
        with open(token_file) as f:
            token = f.read().strip()

        def _reread(tf=token_file):
            with open(tf) as f:
                # Re-check in 5 min (projected SA tokens rotate on disk).
                return f.read().strip(), time.time() + 300

        refresher, expiry = _reread, time.time() + 300
    exec_cert = exec_key = None
    if not token and "exec" in user:
        token, exec_cert, exec_key, expiry = _run_exec_credential(
            user["exec"], owned
        )

        def _reexec(spec=user["exec"], o=owned):
            t, _c, _k, exp = _run_exec_credential(spec, o)
            return t, exp

        if token:
            refresher = _reexec
    if (not token and "auth-provider" in user
            and not user.get("client-certificate")
            and not user.get("client-certificate-data")):
        raise ValueError(
            f"kubeconfig {path}: user {ctx.get('user')!r} uses the legacy "
            "auth-provider mechanism, which is not supported — use a "
            "token, client certificate, or exec credential plugin"
        )
    rc = RestConfig(
        host=cluster["server"].rstrip("/"),
        token=token,
        ca_file=_materialize(
            cluster.get("certificate-authority-data"),
            cluster.get("certificate-authority"), owned,
        ),
        client_cert_file=exec_cert or _materialize(
            user.get("client-certificate-data"),
            user.get("client-certificate"), owned,
        ),
        client_key_file=exec_key or _materialize(
            user.get("client-key-data"), user.get("client-key"), owned,
        ),
        verify_tls=not cluster.get("insecure-skip-tls-verify", False),
        namespace=ctx.get("namespace", "default"),
        token_refresher=refresher,
        token_expiry=expiry,
    )
    rc._owned_files = owned
    return rc


def _run_exec_credential(spec: dict, owned: list):
    """client.authentication.k8s.io exec plugin (the mechanism GKE's
    gke-gcloud-auth-plugin and EKS's aws-iam-authenticator use): run the
    command, parse the ExecCredential JSON it prints, return
    (token, cert_file, key_file, expiry_epoch)."""
    import subprocess

    argv = [spec["command"], *(spec.get("args") or [])]
    env = dict(os.environ)
    for e in spec.get("env") or []:
        env[e["name"]] = e["value"]
    env["KUBERNETES_EXEC_INFO"] = json.dumps({
        "apiVersion": spec.get("apiVersion",
                               "client.authentication.k8s.io/v1"),
        "kind": "ExecCredential",
        "spec": {"interactive": False},
    })
    try:
        out = subprocess.run(
            argv, env=env, capture_output=True, text=True, timeout=60,
            check=True,
        ).stdout
        cred = json.loads(out)
    except (OSError, subprocess.SubprocessError, ValueError) as e:
        raise ValueError(
            f"exec credential plugin {argv[0]!r} failed: {e}"
        ) from e
    status = cred.get("status") or {}
    token = status.get("token")
    cert = key = None
    cert_data = status.get("clientCertificateData")
    key_data = status.get("clientKeyData")
    if cert_data and not key_data:
        raise ValueError(
            f"exec credential plugin {argv[0]!r} returned "
            "clientCertificateData without clientKeyData"
        )
    if cert_data:
        cert = _materialize(
            base64.b64encode(cert_data.encode()).decode(), None, owned
        )
        key = _materialize(
            base64.b64encode(key_data.encode()).decode(), None, owned
        )
    if not token and not cert:
        raise ValueError(
            f"exec credential plugin {argv[0]!r} returned neither a token "
            "nor a client certificate"
        )
    expiry = None
    ts = status.get("expirationTimestamp")
    if ts:
        try:
            from datetime import datetime

            expiry = datetime.fromisoformat(
                ts.replace("Z", "+00:00")
            ).timestamp()
        except ValueError:
            log.warning("exec plugin %s: bad expirationTimestamp %r",
                        argv[0], ts)
    return token, cert, key, expiry


def load_incluster_config(root: str = SERVICE_ACCOUNT_ROOT) -> RestConfig:
    """In-cluster config: serviceaccount token + CA + env-provided host
    (client-go rest.InClusterConfig analog)."""
    host = os.environ.get("KUBERNETES_SERVICE_HOST")
    port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
    token_file = os.path.join(root, "token")
    if not host or not os.path.exists(token_file):
        raise RuntimeError(
            "not running in-cluster: KUBERNETES_SERVICE_HOST unset or "
            f"{token_file} missing"
        )
    with open(token_file) as f:
        token = f.read().strip()
    ns = "default"
    ns_file = os.path.join(root, "namespace")
    if os.path.exists(ns_file):
        with open(ns_file) as f:
            ns = f.read().strip() or "default"
    ca = os.path.join(root, "ca.crt")

    def _reread(tf=token_file):
        # Projected SA tokens rotate on disk; kubelet refreshes the file.
        with open(tf) as f:
            return f.read().strip(), time.time() + 300

    return RestConfig(
        host=f"https://{host}:{port}",
        token=token,
        ca_file=ca if os.path.exists(ca) else None,
        namespace=ns,
        token_refresher=_reread,
        token_expiry=time.time() + 300,
    )


def load_config(kubeconfig: Optional[str] = None,
                context: Optional[str] = None) -> RestConfig:
    """kubeconfig if present, else in-cluster — the standard resolution
    order (server.go:103-109)."""
    explicit = kubeconfig or os.environ.get("KUBECONFIG")
    default_path = os.path.expanduser("~/.kube/config")
    if explicit or os.path.exists(default_path):
        return load_kubeconfig(explicit, context)
    return load_incluster_config()


# ---------------------------------------------------------------------------
# REST path mapping
# ---------------------------------------------------------------------------


def resource_path(resource: str, namespace: Optional[str] = None,
                  name: Optional[str] = None,
                  subresource: Optional[str] = None) -> str:
    """Map a resource plural to its apiserver path.

    core/v1 lives under ``/api/v1``; every group under
    ``/apis/{group}/{version}`` — the same split client-go's RESTMapper
    performs.
    """
    rt = RESOURCES.get(resource)
    if rt is None:
        raise NotFoundError("resources", resource, "unknown resource type")
    if rt.api_version == "v1":
        prefix = "/api/v1"
    else:
        prefix = f"/apis/{rt.api_version}"
    parts = [prefix]
    if namespace:
        parts += ["namespaces", namespace]
    parts.append(resource)
    if name:
        parts.append(name)
    if subresource:
        parts.append(subresource)
    return "/".join(parts)


def _selector_query(label_selector: Optional[dict]) -> Optional[str]:
    if not label_selector:
        return None
    return ",".join(f"{k}={v}" for k, v in sorted(label_selector.items()))


# ---------------------------------------------------------------------------
# The REST client
# ---------------------------------------------------------------------------


class KubeAPIServer:
    """``InMemoryAPIServer``-surface client for a real kube-apiserver."""

    def __init__(self, config: RestConfig, *, user_agent: str = "tpu-operator",
                 request_timeout: float = 30.0, qps: float = 0.0,
                 burst: int = 10, page_limit: int = 500,
                 max_retries: int = 5):
        self.config = config
        self.user_agent = user_agent
        self.request_timeout = request_timeout
        # Client-side throttle (off by default; the operator CLI wires
        # --kube-api-qps/--kube-api-burst, reference defaults 5/10).
        self._limiter = _TokenBucket(qps, burst)
        # Lists arrive in pages of this many items (0 = unpaginated) —
        # the Reflector's WatchListPageSize discipline.
        self.page_limit = page_limit
        self.max_retries = max_retries
        # Total time one logical request may spend across retry sleeps —
        # keeps a Retry-After storm from silently stretching a single
        # call past lease-renewal deadlines (leader election calls sit
        # on this same client).
        self.max_retry_duration = 30.0
        # Observability for tests: requests that were retried/throttled.
        self.retry_count = 0
        self.throttle_wait = 0.0
        parsed = urllib.parse.urlsplit(config.host)
        if parsed.scheme not in ("http", "https"):
            raise ValueError(f"unsupported apiserver scheme {parsed.scheme!r}")
        self._scheme = parsed.scheme
        self._netloc = parsed.netloc
        self._base_path = parsed.path.rstrip("/")
        self._ssl = config.ssl_context()
        self._watches: list[KubeWatch] = []
        self._lock = threading.Lock()

    # -- plumbing --------------------------------------------------------

    def _connect(self, timeout: Optional[float] = None):
        import http.client

        if self._scheme == "https":
            return http.client.HTTPSConnection(
                self._netloc, context=self._ssl,
                timeout=timeout or self.request_timeout,
            )
        return http.client.HTTPConnection(
            self._netloc, timeout=timeout or self.request_timeout
        )

    def _headers(self) -> dict:
        h = {
            "Accept": "application/json",
            "User-Agent": self.user_agent,
        }
        token = self.config.current_token()
        if token:
            h["Authorization"] = f"Bearer {token}"
        return h

    def _error_from_response(self, resource: str, name: str, code: int,
                             body: bytes) -> ApiError:
        reason, detail = "", ""
        try:
            status = json.loads(body)
            reason = status.get("reason", "")
            detail = status.get("message", "")
        except (ValueError, AttributeError):
            detail = body.decode(errors="replace")[:500]
        cls = _ERRORS_BY_REASON.get(reason) or _ERRORS_BY_CODE.get(code)
        if cls is None:
            cls = ServerError
        err = cls(resource, name, detail)
        err.code = code
        return err

    def _retry_delay(self, attempt: int,
                     retry_after: Optional[str]) -> float:
        """Server-directed Retry-After wins; else jittered exponential
        backoff (0.25s·2^n, capped, 50-100% jitter so a fleet of clients
        does not re-stampede in lockstep)."""
        if retry_after:
            try:
                return max(0.0, min(float(retry_after), 30.0))
            except ValueError:
                pass
        base = min(0.25 * (2 ** attempt), 8.0)
        return base * (0.5 + random.random() / 2)

    def _request(self, method: str, path: str, *, resource: str = "",
                 name: str = "", query: Optional[dict] = None,
                 body: Optional[dict] = None,
                 _retry_auth: bool = True) -> dict:
        url = self._base_path + path
        if query:
            url += "?" + urllib.parse.urlencode(
                {k: v for k, v in query.items() if v is not None}
            )
        payload = None
        if body is not None:
            payload = json.dumps(body).encode()
        attempt = 0
        retry_deadline = time.monotonic() + self.max_retry_duration
        while True:
            self.throttle_wait += self._limiter.acquire()
            headers = self._headers()
            if payload is not None:
                headers["Content-Type"] = "application/json"
            conn = self._connect()
            try:
                conn.request(method, url, body=payload, headers=headers)
                resp = conn.getresponse()
                data = resp.read()
                if resp.status == 401 and _retry_auth:
                    # Expired rotating credential: re-acquire, retry once
                    # (does not consume the transient-failure budget).
                    if self.config.refresh_token():
                        _retry_auth = False
                        continue
                if resp.status < 300:
                    return json.loads(data) if data else {}
                # 429 means the server never processed the request, so
                # every verb retries; transient gateway 5xx retry only
                # for GET (the idempotent verb — a replayed PUT/POST
                # could double-apply behind a flaky LB).
                transient = (
                    resp.status == 429
                    or (method == "GET"
                        and resp.status in (500, 502, 503, 504))
                )
                if transient and attempt < self.max_retries:
                    delay = self._retry_delay(
                        attempt, resp.getheader("Retry-After")
                    )
                    if time.monotonic() + delay <= retry_deadline:
                        attempt += 1
                        self.retry_count += 1
                        time.sleep(delay)
                        continue
                raise self._error_from_response(
                    resource, name, resp.status, data
                )
            except ApiError:
                raise
            except (OSError, ValueError) as e:
                if method == "GET" and attempt < self.max_retries:
                    delay = self._retry_delay(attempt, None)
                    if time.monotonic() + delay <= retry_deadline:
                        attempt += 1
                        self.retry_count += 1
                        time.sleep(delay)
                        continue
                raise ServerError(
                    resource, name, f"{method} {url}: {e}"
                ) from e
            finally:
                conn.close()

    @staticmethod
    def _ns_name(obj: dict) -> tuple[str, str]:
        meta = obj.get("metadata") or {}
        return meta.get("namespace") or "default", meta.get("name", "")

    def _stamp(self, resource: str, obj: dict) -> dict:
        """List items arrive without apiVersion/kind; callers (and the
        informer cache) expect them present, matching the in-memory
        server's behavior."""
        rt = RESOURCES[resource]
        obj.setdefault("apiVersion", rt.api_version)
        obj.setdefault("kind", rt.kind)
        return obj

    # -- surface ---------------------------------------------------------

    def create(self, resource: str, obj: dict) -> dict:
        ns, name = self._ns_name(obj)
        rt = RESOURCES[resource]
        obj = dict(obj)
        obj.setdefault("apiVersion", rt.api_version)
        obj.setdefault("kind", rt.kind)
        return self._request(
            "POST", resource_path(resource, ns),
            resource=resource, name=name, body=obj,
        )

    def get(self, resource: str, namespace: str, name: str) -> dict:
        return self._request(
            "GET", resource_path(resource, namespace or "default", name),
            resource=resource, name=f"{namespace}/{name}",
        )

    def list(self, resource: str, namespace: Optional[str] = None,
             label_selector: Optional[dict] = None) -> list[dict]:
        return self.list_with_rv(resource, namespace, label_selector)[0]

    def list_with_rv(
        self, resource: str, namespace: Optional[str] = None,
        label_selector: Optional[dict] = None,
    ) -> tuple[list[dict], str]:
        """List plus the collection resourceVersion (watch baseline).

        Pages through the collection ``page_limit`` items at a time
        (``limit``/``continue``, the Reflector's chunked-list
        discipline) so a large cluster never forces one giant response.
        An expired continue token (410 mid-pagination) restarts the
        whole list — pages from different snapshots must not be mixed —
        and the restart is UNPAGINATED (client-go's ListPager fallback:
        a plain list has no continuation to expire, so one retry always
        suffices even against a server compacting every snapshot;
        pinned by tests/test_properties_operator.py's pagination
        property).
        """
        sel = _selector_query(label_selector)
        path = resource_path(resource, namespace)
        use_limit = bool(self.page_limit)
        for _restart in range(4):
            items: list[dict] = []
            rv = ""
            cont: Optional[str] = None
            while True:
                query = {
                    "labelSelector": sel,
                    "limit": str(self.page_limit) if use_limit else None,
                    "continue": cont,
                }
                try:
                    result = self._request(
                        "GET", path, resource=resource, query=query,
                    )
                except ApiError as e:
                    if cont is not None and getattr(e, "code", 0) == 410:
                        use_limit = False  # token expired: restart
                        break              # from page one, unpaginated
                    raise
                items += [
                    self._stamp(resource, o)
                    for o in result.get("items") or []
                ]
                meta = result.get("metadata") or {}
                # Every page is served from the same snapshot; the first
                # page's rv is the collection rv.
                rv = rv or meta.get("resourceVersion", "")
                cont = meta.get("continue") or None
                if cont is None:
                    items.sort(
                        key=lambda o: (o["metadata"].get("namespace", ""),
                                       o["metadata"]["name"])
                    )
                    return items, rv
        raise ServerError(
            resource, "", "list pagination restarted 4x on expired "
            "continue tokens without completing",
        )

    def update(self, resource: str, obj: dict) -> dict:
        ns, name = self._ns_name(obj)
        rt = RESOURCES[resource]
        obj = dict(obj)
        obj.setdefault("apiVersion", rt.api_version)
        obj.setdefault("kind", rt.kind)
        return self._request(
            "PUT", resource_path(resource, ns, name),
            resource=resource, name=name, body=obj,
        )

    def update_status(self, resource: str, obj: dict) -> dict:
        ns, name = self._ns_name(obj)
        rt = RESOURCES[resource]
        obj = dict(obj)
        obj.setdefault("apiVersion", rt.api_version)
        obj.setdefault("kind", rt.kind)
        return self._request(
            "PUT", resource_path(resource, ns, name, subresource="status"),
            resource=resource, name=name, body=obj,
        )

    def delete(self, resource: str, namespace: str, name: str) -> None:
        # Background propagation: the cluster's GC cascades along
        # ownerReferences (the in-memory server's _garbage_collect analog).
        self._request(
            "DELETE", resource_path(resource, namespace or "default", name),
            resource=resource, name=f"{namespace}/{name}",
            body={"apiVersion": "v1", "kind": "DeleteOptions",
                  "propagationPolicy": "Background"},
        )

    def watch(self, resource: str,
              namespace: Optional[str] = None) -> "KubeWatch":
        w = KubeWatch(self, resource, namespace)
        w._open()  # synchronous: stream established before watch() returns
        with self._lock:
            self._watches.append(w)
        return w

    def _remove_watch(self, watch: "KubeWatch") -> None:
        with self._lock:
            if watch in self._watches:
                self._watches.remove(watch)

    def close(self) -> None:
        with self._lock:
            watches = list(self._watches)
        for w in watches:
            w.stop()


class KubeWatch:
    """One streaming watch with transparent reconnect and 410 resume.

    Exposes the same queue interface as the in-memory ``Watch``
    (``drain`` / ``next`` / ``stop``).  Maintains a mirror of the
    watched collection — **resourceVersions only, not objects**, so a
    watch costs O(collection) keys rather than a full copy of every
    object at cluster scale. A compaction (410 Gone) resumes by
    re-listing and emitting the *diff* as synthetic events; ADDED and
    MODIFIED carry the fresh objects from that list, while DELETED
    carries a metadata-only tombstone (namespace/name/resourceVersion)
    — the informer on top delivers the full last-known object from its
    own cache, client-go's DeletedFinalStateUnknown discipline.
    """

    def __init__(self, server: KubeAPIServer, resource: str,
                 namespace: Optional[str]):
        self._server = server
        self.resource = resource
        self.namespace = namespace
        self._events: list[WatchEvent] = []
        self._cond = threading.Condition()
        self._stopped = False
        self._rv = ""
        # key -> object resourceVersion (see class docstring).
        self._mirror: dict[tuple[str, str], str] = {}
        self._conn = None
        self._thread: Optional[threading.Thread] = None
        # Surfaced for tests/debugging: how many relists (410s) happened.
        self.relist_count = 0

    def baseline(self) -> list[dict]:
        """The objects from the opening LIST (informers reuse this as
        their initial cache instead of listing again). Snapshotted before
        the reader thread starts, so it is safe to read afterwards."""
        return self._baseline_snapshot

    # -- queue interface (apiserver.Watch parity) ------------------------

    def _deliver(self, event: WatchEvent) -> None:
        with self._cond:
            if self._stopped:
                return
            self._events.append(event)
            self._cond.notify_all()

    def drain(self) -> list[WatchEvent]:
        with self._cond:
            events, self._events = self._events, []
            return events

    def next(self, timeout: Optional[float] = None) -> Optional[WatchEvent]:
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while not self._events and not self._stopped:
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    return None
                self._cond.wait(remaining)
            if self._events:
                return self._events.pop(0)
            return None

    def stop(self) -> None:
        with self._cond:
            self._stopped = True
            self._cond.notify_all()
        conn = self._conn
        if conn is not None:
            try:
                conn.close()  # unblocks the reader thread
            except OSError:
                pass
        self._server._remove_watch(self)

    # -- streaming -------------------------------------------------------

    @staticmethod
    def _key(obj: dict) -> tuple[str, str]:
        meta = obj.get("metadata") or {}
        return meta.get("namespace", ""), meta.get("name", "")

    def _tombstone(self, key: tuple[str, str], old_rv: str) -> dict:
        ns, name = key
        rt = RESOURCES[self.resource]
        return {
            "apiVersion": rt.api_version,
            "kind": rt.kind,
            "metadata": {
                "namespace": ns, "name": name, "resourceVersion": old_rv,
            },
        }

    def _baseline(self, emit_diff: bool) -> list[dict]:
        """Full (paginated) list into the rv mirror; on resume
        (``emit_diff``) the diff against the previous mirror becomes
        synthetic events. Returns the listed objects (the caller's
        baseline snapshot) — they are not retained here."""
        items, rv = self._server.list_with_rv(self.resource, self.namespace)
        fresh = {
            self._key(o): o["metadata"].get("resourceVersion", "")
            for o in items
        }
        if emit_diff:
            for obj in items:
                old_rv = self._mirror.get(self._key(obj))
                if old_rv is None:
                    self._deliver(WatchEvent(ADDED, self.resource, obj))
                elif old_rv != obj["metadata"].get("resourceVersion"):
                    self._deliver(WatchEvent(MODIFIED, self.resource, obj))
            for key, old_rv in self._mirror.items():
                if key not in fresh:
                    self._deliver(WatchEvent(
                        DELETED, self.resource,
                        self._tombstone(key, old_rv),
                    ))
        self._mirror = fresh
        self._rv = rv
        return items

    def _open_stream(self):
        """Open the chunked watch request; returns (conn, resp)."""
        query = {
            "watch": "true",
            "resourceVersion": self._rv,
            "allowWatchBookmarks": "true",
            "timeoutSeconds": "300",
        }
        url = (self._server._base_path
               + resource_path(self.resource, self.namespace)
               + "?" + urllib.parse.urlencode(query))
        # Watch opens ride the same client-side throttle as CRUD calls
        # (a relist storm must not bypass --kube-api-qps).
        self._server.throttle_wait += self._server._limiter.acquire()
        conn = self._server._connect(timeout=330.0)
        conn.request("GET", url, headers=self._server._headers())
        resp = conn.getresponse()
        if resp.status == 401 and self._server.config.refresh_token():
            resp.read()
            conn.close()
            conn = self._server._connect(timeout=330.0)
            conn.request("GET", url, headers=self._server._headers())
            resp = conn.getresponse()
        if resp.status == 410:
            resp.read()
            conn.close()
            raise _Gone()
        if resp.status >= 300:
            body = resp.read()
            conn.close()
            raise self._server._error_from_response(
                self.resource, "", resp.status, body
            )
        return conn, resp

    def _open(self) -> None:
        """Baseline list + first stream, synchronously, then the reader
        thread takes over. Guarantees the stream covers everything after
        the caller's next ``list()``."""
        items = self._baseline(emit_diff=False)
        try:
            self._conn, resp = self._open_stream()
        except _Gone:
            # Pathological but possible: compaction between list and watch.
            self.relist_count += 1
            items = self._baseline(emit_diff=True)
            self._conn, resp = self._open_stream()
        # After this point only the reader thread touches the mirror.
        self._baseline_snapshot = items
        self._thread = threading.Thread(
            target=self._run, args=(resp,),
            name=f"kubewatch-{self.resource}", daemon=True,
        )
        self._thread.start()

    def _run(self, resp) -> None:
        while not self._stopped:
            if resp is not None:
                try:
                    self._consume(resp)
                except _Gone:
                    self.relist_count += 1
                    self._rv = ""
                except (OSError, ValueError, AttributeError) as e:
                    # AttributeError: http.client raises it when the
                    # response is closed under a blocked readline
                    # (stop() racing us).
                    if self._stopped:
                        return
                    log.debug("watch %s stream error: %s", self.resource, e)
                    time.sleep(0.2)
                resp = None
                conn, self._conn = self._conn, None
                if conn is not None:
                    try:
                        conn.close()
                    except OSError:
                        pass
            if self._stopped:
                return
            # Reconnect (timeout rollover, network blip, or 410 resume).
            try:
                if not self._rv:
                    self._baseline(emit_diff=True)
                self._conn, resp = self._open_stream()
            except _Gone:
                self.relist_count += 1
                self._rv = ""  # next iteration relists, resp stays None
            except (ApiError, OSError, ValueError) as e:
                if self._stopped:
                    return
                log.warning("watch %s reopen failed: %s", self.resource, e)
                time.sleep(1.0)

    def _consume(self, resp) -> None:
        """Read newline-delimited watch events until the stream ends."""
        if resp is None:
            raise OSError("no stream")
        for raw in iter(resp.readline, b""):
            if self._stopped:
                return
            raw = raw.strip()
            if not raw:
                continue
            event = json.loads(raw)
            etype = event.get("type", "")
            obj = event.get("object") or {}
            if etype == ERROR:
                if obj.get("code") == 410:
                    raise _Gone()
                log.warning("watch %s server error: %s", self.resource,
                            obj.get("message", obj))
                raise _Gone()  # safest recovery path is a relist
            rv = (obj.get("metadata") or {}).get("resourceVersion")
            if rv:
                self._rv = rv
            if etype == BOOKMARK:
                continue
            self._server._stamp(self.resource, obj)
            key = self._key(obj)
            if etype == DELETED:
                self._mirror.pop(key, None)
            else:
                self._mirror[key] = (obj.get("metadata") or {}).get(
                    "resourceVersion", ""
                )
            self._deliver(WatchEvent(etype, self.resource, obj))
        # Clean EOF: server closed (timeoutSeconds rollover); reconnect
        # from the last seen rv.


class _Gone(Exception):
    """410: the requested resourceVersion is compacted away."""
