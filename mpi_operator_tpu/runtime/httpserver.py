"""HTTP frontend over the in-memory apiserver — the envtest analog.

Serves the Kubernetes REST API (core/v1, batch/v1, coordination.k8s.io,
scheduling.x-k8s.io, and the kubeflow.org TPUJob CRD) over real HTTP on
localhost, backed by :class:`InMemoryAPIServer`. This is what lets the
*real-cluster* REST backend (:mod:`.kube`) — request signing, path
mapping, chunked watch streaming, 410 resume — be exercised end to end
with no cluster, the same discipline as the reference's envtest tier
(/root/reference/v2/test/integration/main_test.go:42-59: a real
apiserver, no kubelet).

Faithful bits:
- list responses carry the collection ``metadata.resourceVersion``;
- watches honor ``resourceVersion=`` by replaying from a bounded event
  history (the apiserver's watch cache), stream newline-delimited JSON
  in chunked encoding, honor ``timeoutSeconds``, and send BOOKMARK
  events;
- a watch from a compacted resourceVersion gets ``410 Gone`` — set
  ``history_limit`` low (or call ``compact()``) to test client resume;
- errors come back as ``Status`` objects with the apiserver's
  code/reason vocabulary;
- optional bearer-token auth (401 without it), so client auth headers
  are actually exercised.
"""

from __future__ import annotations

import base64
import json
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlsplit

from .apiserver import (
    RESOURCES,
    ApiError,
    InMemoryAPIServer,
    WatchEvent,
)

# /api/v1/... (core) and /apis/{group}/{version}/... (everything else),
# optionally namespaced, optionally named, optional status subresource.
_CORE = re.compile(
    r"^/api/v1(?:/namespaces/(?P<ns>[^/]+))?/(?P<plural>[a-z]+)"
    r"(?:/(?P<name>[^/]+))?(?:/(?P<sub>status))?$"
)
_GROUP = re.compile(
    r"^/apis/(?P<gv>[^/]+/[^/]+)(?:/namespaces/(?P<ns>[^/]+))?"
    r"/(?P<plural>[a-z]+)(?:/(?P<name>[^/]+))?(?:/(?P<sub>status))?$"
)


class APIServerFrontend:
    """Runs the HTTP server; owns the watch-cache history."""

    def __init__(self, api: InMemoryAPIServer, *, host: str = "127.0.0.1",
                 port: int = 0, token: Optional[str] = None,
                 history_limit: int = 4096):
        self.api = api
        self.token = token
        self.history_limit = history_limit
        # Fault/behavior knobs for client-hardening tests:
        # throttle_429 > 0: the next N non-watch requests get 429 with a
        # Retry-After header (apiserver priority-and-fairness shedding).
        self.throttle_429 = 0
        self.throttle_hits = 0
        # expire_continue: every list continuation token 410s (etcd
        # compacted the snapshot) — clients must restart the list.
        self.expire_continue = False
        self._knob_lock = threading.Lock()
        # Watch cache: rv-ordered (rv, WatchEvent) history per resource,
        # fed by one persistent watch per resource. ``_compacted`` is
        # the continuity watermark: the rv of the newest event ever
        # DROPPED from the history (by the ring limit or compact()).
        # A watch rv below it must 410 even when the history is empty —
        # an empty cache means "cannot prove continuity", not "nothing
        # happened". (Conflating the two left a reconnecting idle watch
        # silently stale forever; found by
        # tests/test_properties_operator.py:TestWatchContractProperties.)
        self._history: dict[str, list[tuple[int, WatchEvent]]] = {
            plural: [] for plural in RESOURCES
        }
        self._compacted: dict[str, int] = {plural: 0 for plural in RESOURCES}
        self._hist_lock = threading.Condition()
        self._recorders = [api.watch(plural) for plural in RESOURCES]
        self._recorder_thread = threading.Thread(
            target=self._record_loop, daemon=True, name="watchcache"
        )
        self._stopped = False

        handler = type("Handler", (_Handler,), {"frontend": self})
        self.server = ThreadingHTTPServer((host, port), handler)
        self.server.daemon_threads = True
        self._serve_thread = threading.Thread(
            target=self.server.serve_forever, daemon=True, name="apiserver-http"
        )

    # -- lifecycle -------------------------------------------------------

    def start(self) -> "APIServerFrontend":
        self._recorder_thread.start()
        self._serve_thread.start()
        return self

    @property
    def url(self) -> str:
        host, port = self.server.server_address[:2]
        return f"http://{host}:{port}"

    def stop(self) -> None:
        self._stopped = True
        for w in self._recorders:
            w.stop()
        with self._hist_lock:
            self._hist_lock.notify_all()
        self.server.shutdown()
        self.server.server_close()

    # -- watch cache -----------------------------------------------------

    def _record_loop(self) -> None:
        while not self._stopped:
            got = False
            for w in self._recorders:
                for event in w.drain():
                    got = True
                    rv = int(event.object["metadata"]["resourceVersion"])
                    with self._hist_lock:
                        hist = self._history[event.resource]
                        hist.append((rv, event))
                        if len(hist) > self.history_limit:
                            drop = len(hist) - self.history_limit
                            self._compacted[event.resource] = max(
                                self._compacted[event.resource],
                                hist[drop - 1][0],
                            )
                            del hist[:drop]
                        self._hist_lock.notify_all()
            if not got:
                time.sleep(0.005)

    def compact(self) -> None:
        """Drop all history — every watch resume from an old rv now 410s
        (simulates etcd compaction for resume tests)."""
        with self._hist_lock:
            for plural, hist in self._history.items():
                if hist:
                    self._compacted[plural] = max(
                        self._compacted[plural], hist[-1][0]
                    )
                hist.clear()

    def oldest_rv(self, resource: str) -> Optional[int]:
        with self._hist_lock:
            hist = self._history[resource]
            return hist[0][0] if hist else None

    def events_since(self, resource: str, rv: int,
                     timeout: float) -> Optional[list[tuple[int, WatchEvent]]]:
        """History entries with event-rv > rv; blocks up to ``timeout``
        for the first one. None signals 410 (rv is before the retained
        window)."""
        deadline = time.monotonic() + timeout
        with self._hist_lock:
            while True:
                hist = self._history[resource]
                # Re-checked every wakeup: an event arriving *while we
                # block* can evict the window our rv needs. The
                # watermark is exact — the newest rv ever dropped from
                # this resource's history — and covers the
                # empty-history case (compaction with an idle stream
                # must still 410, or the client waits forever on a
                # provably stale rv). No adjacency heuristic: rvs come
                # from one global counter, so per-resource gaps are
                # normal, not evidence of loss.
                if rv < self._compacted[resource]:
                    return None
                out = [(erv, e) for erv, e in hist if erv > rv]
                if out or self._stopped:
                    return out
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return []
                self._hist_lock.wait(min(remaining, 0.25))


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    frontend: APIServerFrontend = None

    # -- plumbing --------------------------------------------------------

    def log_message(self, *args):  # quiet
        pass

    def _send_json(self, code: int, obj: dict) -> None:
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_status_error(self, code: int, reason: str, message: str) -> None:
        self._send_json(code, {
            "apiVersion": "v1", "kind": "Status", "status": "Failure",
            "code": code, "reason": reason, "message": message,
        })

    def _send_api_error(self, err: ApiError) -> None:
        self._send_status_error(err.code, err.reason, str(err))

    def _throttled(self) -> bool:
        """429 shedding knob: consume one slot if armed (watches exempt —
        the real server's APF treats long-running requests separately)."""
        fe = self.frontend
        with fe._knob_lock:
            if fe.throttle_429 <= 0:
                return False
            fe.throttle_429 -= 1
            fe.throttle_hits += 1
        self.send_response(429)
        body = json.dumps({
            "apiVersion": "v1", "kind": "Status", "status": "Failure",
            "code": 429, "reason": "TooManyRequests",
            "message": "the server is currently unable to handle the "
                       "request — try again later",
        }).encode()
        self.send_header("Content-Type", "application/json")
        self.send_header("Retry-After", "0")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)
        return True

    def _authorized(self) -> bool:
        token = self.frontend.token
        if token is None:
            return True
        got = self.headers.get("Authorization", "")
        if got == f"Bearer {token}":
            return True
        self._send_status_error(401, "Unauthorized", "bad or missing token")
        return False

    def _route(self):
        """Parse path -> (resource, ns, name, sub, query) or None (404)."""
        parts = urlsplit(self.path)
        m = _CORE.match(parts.path) or _GROUP.match(parts.path)
        if not m:
            return None
        plural = m.group("plural")
        rt = RESOURCES.get(plural)
        if rt is None:
            return None
        gv = m.groupdict().get("gv")
        expect = "v1" if gv is None else gv
        if rt.api_version != expect:
            return None
        query = {k: v[0] for k, v in parse_qs(parts.query).items()}
        return plural, m.group("ns"), m.group("name"), m.group("sub"), query

    def _read_body(self) -> dict:
        length = int(self.headers.get("Content-Length") or 0)
        raw = self.rfile.read(length) if length else b""
        return json.loads(raw) if raw else {}

    @staticmethod
    def _parse_selector(q: dict) -> Optional[dict]:
        sel = q.get("labelSelector")
        if not sel:
            return None
        out = {}
        for term in sel.split(","):
            k, _, v = term.partition("=")
            out[k] = v
        return out

    # -- verbs -----------------------------------------------------------

    def do_GET(self):  # noqa: N802
        if not self._authorized():
            return
        route = self._route()
        if route is None:
            self._send_status_error(404, "NotFound", f"path {self.path}")
            return
        plural, ns, name, _sub, query = route
        api = self.frontend.api
        try:
            if query.get("watch") == "true" and not name:
                self._watch(plural, ns, query)
                return
            if self._throttled():
                return
            if name:
                self._send_json(200, api.get(plural, ns or "default", name))
            else:
                self._list(plural, ns, query)
        except ApiError as e:
            self._send_api_error(e)

    def _list(self, plural: str, ns: Optional[str], query: dict) -> None:
        """List with ``limit``/``continue`` chunking (apiserver
        pagination). The continue token encodes the last-returned key;
        ``expire_continue`` makes every continuation 410 to exercise the
        client's restart path."""
        items = self.frontend.api.list(plural, ns, self._parse_selector(query))
        items.sort(key=lambda o: (o["metadata"].get("namespace", ""),
                                  o["metadata"]["name"]))
        cont = query.get("continue")
        if cont:
            if self.frontend.expire_continue:
                self._send_status_error(
                    410, "Expired",
                    "the provided continue parameter is too old",
                )
                return
            try:
                after = tuple(json.loads(base64.b64decode(cont)))
            except (ValueError, TypeError):
                after = None
            if (after is None or len(after) != 2
                    or not all(isinstance(p, str) for p in after)):
                self._send_status_error(
                    400, "BadRequest", "malformed continue token"
                )
                return
            items = [
                o for o in items
                if (o["metadata"].get("namespace", ""),
                    o["metadata"]["name"]) > after
            ]
        rt = RESOURCES[plural]
        # Collection rv: the newest rv across the store (next()-1
        # would race writers; max over items is the same contract
        # the real watch cache provides — "at least this fresh").
        rv = max(
            (int(o["metadata"]["resourceVersion"]) for o in items),
            default=self._newest_known_rv(),
        )
        meta: dict = {"resourceVersion": str(rv)}
        try:
            limit = int(query.get("limit") or 0)
        except ValueError:
            limit = 0
        if limit and len(items) > limit:
            last = items[limit - 1]
            meta["remainingItemCount"] = len(items) - limit
            items = items[:limit]
            meta["continue"] = base64.b64encode(json.dumps([
                last["metadata"].get("namespace", ""),
                last["metadata"]["name"],
            ]).encode()).decode()
        self._send_json(200, {
            "apiVersion": rt.api_version,
            "kind": rt.kind + "List",
            "metadata": meta,
            "items": items,
        })

    def _newest_known_rv(self) -> int:
        # The compaction watermark counts as "known": a list served
        # right after a compaction must not hand out a collection rv
        # below it, or the client's follow-up watch 410s, relists to the
        # same stale rv, and livelocks (410 -> relist -> 410 ...).
        newest = 0
        with self.frontend._hist_lock:
            for hist in self.frontend._history.values():
                if hist:
                    newest = max(newest, hist[-1][0])
            newest = max(newest, *self.frontend._compacted.values())
        return newest

    def do_POST(self):  # noqa: N802
        if not self._authorized() or self._throttled():
            return
        route = self._route()
        if route is None:
            self._send_status_error(404, "NotFound", f"path {self.path}")
            return
        plural, ns, name, _sub, _query = route
        if name:
            self._send_status_error(405, "MethodNotAllowed", "POST to object")
            return
        try:
            obj = self._read_body()
            if ns:
                obj.setdefault("metadata", {}).setdefault("namespace", ns)
            self._send_json(201, self.frontend.api.create(plural, obj))
        except ApiError as e:
            self._send_api_error(e)
        except ValueError as e:
            self._send_status_error(400, "BadRequest", str(e))

    def do_PUT(self):  # noqa: N802
        if not self._authorized() or self._throttled():
            return
        route = self._route()
        if route is None or not route[2]:
            self._send_status_error(404, "NotFound", f"path {self.path}")
            return
        plural, ns, name, sub, _query = route
        try:
            obj = self._read_body()
            meta = obj.setdefault("metadata", {})
            if ns:
                meta.setdefault("namespace", ns)
            meta.setdefault("name", name)
            api = self.frontend.api
            if sub == "status":
                self._send_json(200, api.update_status(plural, obj))
            else:
                self._send_json(200, api.update(plural, obj))
        except ApiError as e:
            self._send_api_error(e)
        except ValueError as e:
            self._send_status_error(400, "BadRequest", str(e))

    def do_DELETE(self):  # noqa: N802
        if not self._authorized() or self._throttled():
            return
        route = self._route()
        if route is None or not route[2]:
            self._send_status_error(404, "NotFound", f"path {self.path}")
            return
        plural, ns, name, _sub, _query = route
        try:
            self._read_body()  # DeleteOptions, accepted and ignored
            self.frontend.api.delete(plural, ns or "default", name)
            self._send_json(200, {
                "apiVersion": "v1", "kind": "Status", "status": "Success",
            })
        except ApiError as e:
            self._send_api_error(e)

    # -- watch streaming -------------------------------------------------

    def _watch(self, plural: str, ns: Optional[str], query: dict) -> None:
        try:
            rv = int(query.get("resourceVersion") or 0)
        except ValueError:
            self._send_status_error(400, "BadRequest", "bad resourceVersion")
            return
        timeout = min(float(query.get("timeoutSeconds") or 300), 3600.0)
        bookmarks = query.get("allowWatchBookmarks") == "true"

        first = self.frontend.events_since(plural, rv, timeout=0)
        if first is None:
            self._send_status_error(
                410, "Expired",
                f"resourceVersion {rv} is too old (compacted)",
            )
            return

        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()

        deadline = time.monotonic() + timeout
        last_bookmark = time.monotonic()
        try:
            while True:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                batch = self.frontend.events_since(
                    plural, rv, timeout=min(remaining, 1.0)
                )
                if batch is None:
                    self._write_chunk(json.dumps({
                        "type": "ERROR",
                        "object": {
                            "apiVersion": "v1", "kind": "Status",
                            "status": "Failure", "code": 410,
                            "reason": "Expired",
                            "message": f"resourceVersion {rv} compacted",
                        },
                    }))
                    break
                for erv, event in batch:
                    obj = event.object
                    if ns and obj["metadata"].get("namespace", "") != ns:
                        rv = erv
                        continue
                    self._write_chunk(json.dumps(
                        {"type": event.type, "object": obj}
                    ))
                    rv = erv
                if bookmarks and time.monotonic() - last_bookmark > 5.0:
                    rt = RESOURCES[plural]
                    self._write_chunk(json.dumps({
                        "type": "BOOKMARK",
                        "object": {
                            "apiVersion": rt.api_version, "kind": rt.kind,
                            "metadata": {"resourceVersion": str(rv)},
                        },
                    }))
                    last_bookmark = time.monotonic()
            self.wfile.write(b"0\r\n\r\n")  # end chunked stream
        except (BrokenPipeError, ConnectionResetError):
            pass  # client went away

    def _write_chunk(self, line: str) -> None:
        data = (line + "\n").encode()
        self.wfile.write(f"{len(data):x}\r\n".encode() + data + b"\r\n")
        self.wfile.flush()
