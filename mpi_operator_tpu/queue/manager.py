"""The QueueManager: suspend-based admission of TPUJobs against
ClusterQueue chip quotas.

In-process Kueue analog.  The reference operator's production story gates
MPIJobs behind sigs.k8s.io/kueue: jobs are created suspended, Kueue
reserves quota in a ClusterQueue and unsuspends them, and evicts (re-
suspends) borrowers when a lender reclaims.  This controller runs the
same handshake against the in-memory apiserver:

- A TPUJob opts in by naming a LocalQueue in
  ``spec.runPolicy.schedulingPolicy.queue``.  The LocalQueue (in the
  job's namespace) binds to a ClusterQueue, whose per-generation chip
  quota the job's footprint (api/topology.py shape x numSlices) is
  charged against.
- While enabled, the QueueManager is the **single writer** of
  ``runPolicy.suspend`` (lint-enforced): queue-targeted jobs are forced
  suspended until admitted, admitted by flipping ``suspend=false`` plus
  a ``QuotaReserved=True`` condition, and evicted by re-suspending.
- Admission order is priority-then-FIFO per ClusterQueue, strict: the
  first workload that does not fit blocks the ones behind it (no
  out-of-order admission), and is requeued with backoff carrying the
  kube-style "insufficient quota in ClusterQueue x: ..." message.
- Cohort borrowing: a queue may exceed its nominal quota using cohort
  peers' unused chips (capped by ``borrowingLimit``).  When a lender's
  pending workload fits within its *nominal* quota but not in current
  free chips, and the lender declares
  ``preemption.reclaimWithinCohort: Any``, the youngest borrowing
  workloads are evicted until it fits.

Every sync runs a **global admission pass** rebuilt from apiserver truth
(not informer caches — the manager's own synchronous writes make the
API the only non-stale source); the informers merely trigger the
workqueue, mirroring the gang scheduler's fresh-list discipline
(scheduler/core.py).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from ..api import topology
from ..api.v2beta1.queue_types import (
    RECLAIM_ANY,
    ClusterQueue,
    LocalQueue,
)
from ..api.v2beta1.types import (
    JOB_QUEUE_NOT_FOUND,
    JOB_QUOTA_RESERVED,
    TPUJob,
)
from ..controller import status as st
from ..runtime import locktrace, retry
from ..runtime.apiserver import (
    AlreadyExistsError,
    ConflictError,
    InMemoryAPIServer,
    NotFoundError,
)
from ..runtime.client import TPUJobClient
from ..runtime.informer import EventHandler, InformerFactory, meta_namespace_key
from ..runtime.workqueue import RateLimitingQueue
from ..scheduler.core import DEFAULT_PRIORITIES
from ..utils import flightrecorder, metrics, profiling
from ..utils import logging as logutil
from ..utils.events import EVENT_TYPE_NORMAL, EVENT_TYPE_WARNING, EventRecorder
from .quota import Charge, JobKey, QueueQuota, QuotaLedger, insufficient_quota_message

# Condition reasons (Kueue Workload-condition vocabulary).
ADMITTED_REASON = "Admitted"
PENDING_REASON = "Pending"
EVICTED_REASON = "Evicted"
QUOTA_RELEASED_REASON = "QuotaReleased"
QUEUE_NOT_FOUND_REASON = "QueueNotFound"
QUEUE_FOUND_REASON = "QueueFound"
SUSPENDED_BY_QUEUE_REASON = "SuspendedByQueue"

# Workqueue sentinel for "run a pass, no specific job" (queue events,
# the periodic resync ticker).
PERIODIC_KEY = "@queue-resync"


def job_queue_name(job: TPUJob) -> str:
    sp = job.spec.run_policy.scheduling_policy
    return sp.queue if sp is not None else ""


def is_admitted(job: TPUJob) -> bool:
    """QuotaReserved=True — the job holds chips until finished/evicted."""
    return st.has_condition(job.status, JOB_QUOTA_RESERVED)


class QueueManager:
    """Admits queue-targeted TPUJobs by flipping ``suspend`` (Kueue's
    scheduler + workload controller collapsed into one sync loop)."""

    def __init__(
        self,
        api: InMemoryAPIServer,
        *,
        recorder: Optional[EventRecorder] = None,
        registry: Optional[metrics.Registry] = None,
        flight_recorder: Optional[flightrecorder.FlightRecorder] = None,
        priorities: Optional[Dict[str, int]] = None,
        clock: Callable[[], float] = time.time,
        resync_interval: float = 1.0,
    ):
        self.api = api
        self.tpujobs = TPUJobClient(api)
        self.clock = clock
        self.log = logutil.get_logger("queue-manager")
        self._lock = locktrace.rlock("queue.manager")
        self._resync_interval = resync_interval
        self._priorities = dict(DEFAULT_PRIORITIES if priorities is None else priorities)

        registry = registry or metrics.Registry()
        self.registry = registry
        # "is None", not "or": an empty FlightRecorder is falsy (__len__).
        self.flight_recorder = (
            flightrecorder.FlightRecorder(clock=clock)
            if flight_recorder is None
            else flight_recorder
        )
        if recorder is None:
            recorder = EventRecorder(api, source="tpu-queue-manager", clock=clock)
            # A shared recorder is usually already feeding the flight
            # recorder (controller wiring); only a private one needs it.
            recorder.subscribe(self.flight_recorder.observe_event)
        self.recorder = recorder

        self.pending_workloads = metrics.new_gauge(
            "tpu_operator_queue_pending_workloads",
            "Queue-targeted TPUJobs waiting for quota, per ClusterQueue",
            ("cluster_queue",),
            registry,
        )
        self.admitted_workloads = metrics.new_gauge(
            "tpu_operator_queue_admitted_workloads",
            "TPUJobs currently holding quota, per ClusterQueue",
            ("cluster_queue",),
            registry,
        )
        self.admission_duration = metrics.new_histogram(
            "tpu_operator_queue_admission_duration_seconds",
            "Time from TPUJob creation to quota reservation",
            ("cluster_queue",),
            registry,
        )
        self.evictions = metrics.new_counter(
            "tpu_operator_queue_evictions_total",
            "Workloads evicted so a lender could reclaim cohort quota",
            ("cluster_queue",),
            registry,
        )
        registry.on_scrape(self._refresh_gauges)

        # Shared per-registry profiler (profiler_for dedups with the
        # controller when both run against one registry): the admission
        # pass is one timed phase, and its three full-store lists are
        # scan-accounted under the "queue_admit" scope.
        self.profiler = profiling.profiler_for(registry)

        self.ledger = QuotaLedger()
        # Last-pass snapshots behind _lock: gauge values per queue and the
        # set of still-pending job keys (drives backoff requeues).
        self._pending_counts: Dict[str, int] = {}
        self._admitted_counts: Dict[str, int] = {}
        self._pending_keys: set[str] = set()
        # Failure-message dedup (scheduler _last_failure_msg pattern): an
        # unchanged "insufficient quota" verdict on resync is not news.
        self._last_failure_msg: Dict[str, str] = {}

        # Informers are *triggers* only — the pass lists from the API.
        self.factory = InformerFactory(api, namespace="", profiler=self.profiler)
        self.tpujob_informer = self.factory.informer("tpujobs")
        self.clusterqueue_informer = self.factory.informer("clusterqueues")
        self.localqueue_informer = self.factory.informer("localqueues")

        self.queue = RateLimitingQueue(name="QueueManager", registry=registry)

        self.tpujob_informer.add_event_handler(
            EventHandler(
                on_add=self._enqueue_job,
                on_update=lambda old, new: self._enqueue_job(new),
                on_delete=self._enqueue_job,
            )
        )
        queues_changed = EventHandler(
            on_add=lambda obj: self.queue.add(PERIODIC_KEY),
            on_update=lambda old, new: self.queue.add(PERIODIC_KEY),
            on_delete=lambda obj: self.queue.add(PERIODIC_KEY),
        )
        self.clusterqueue_informer.add_event_handler(queues_changed)
        self.localqueue_informer.add_event_handler(queues_changed)

    # ------------------------------------------------------------------
    # Queue plumbing
    # ------------------------------------------------------------------

    def _enqueue_job(self, obj: dict) -> None:
        sp = (((obj.get("spec") or {}).get("runPolicy") or {})
              .get("schedulingPolicy") or {})
        if not sp.get("queue"):
            return  # not queue-managed; the plain controller owns it
        self.queue.add(meta_namespace_key(obj))

    def start(self) -> None:
        self.factory.start_all()

    def run(self, threadiness: int = 1, stop: Optional[threading.Event] = None) -> None:
        """Blocking run loop (controller.run analog) plus a resync ticker
        so reclaim opportunities surface even without watch events."""
        stop = stop or threading.Event()
        if self.queue.is_shutdown:
            self.queue.reset()
        self.start()

        def pump_loop():
            while not stop.is_set():
                if self.factory.pump_all() == 0:
                    retry.sleep(0.005)

        def tick_loop():
            while not stop.is_set():
                self.queue.add(PERIODIC_KEY)
                stop.wait(self._resync_interval)

        threads = [
            threading.Thread(target=pump_loop, daemon=True),
            threading.Thread(target=tick_loop, daemon=True),
        ]
        for _ in range(threadiness):
            threads.append(
                threading.Thread(target=self._worker_loop, args=(stop,), daemon=True)
            )
        for t in threads:
            t.start()
        stop.wait()
        self.queue.shutdown()
        for t in threads[2:]:
            t.join(timeout=5)
        self.factory.stop_all()

    def _worker_loop(self, stop: threading.Event) -> None:
        while not stop.is_set() and self.process_next_work_item():
            pass

    def process_next_work_item(self) -> bool:
        key, shutdown = self.queue.get()
        if shutdown:
            return False
        try:
            still_pending = self.sync_handler(key)
        except Exception as e:
            self.queue.add_rate_limited(key)
            self.log.warning(
                "error in admission pass for %r: %s", key, e,
                error=type(e).__name__,
            )
        else:
            if still_pending:
                # Inadmissible: back off, but keep retrying — quota frees
                # up without necessarily producing an event for *this* key.
                self.queue.add_rate_limited(key)
            else:
                self.queue.forget(key)
        finally:
            self.queue.done(key)
        return True

    def sync_pending(self, max_rounds: int = 50) -> None:
        """Test/synchronous convenience: pump informers and drain the
        *immediate* queue.  Unlike the controller's version this does NOT
        wait out delayed (backed-off) items — a permanently inadmissible
        job would never quiesce; transitions re-enqueue via watch events."""
        for _ in range(max_rounds):
            self.factory.pump_until_quiet()
            key, _ = self.queue.get(timeout=0.05)
            if key is None:
                return
            try:
                if self.sync_handler(key):
                    self.queue.add_rate_limited(key)
                else:
                    self.queue.forget(key)
            finally:
                self.queue.done(key)
        raise RuntimeError("queue manager did not quiesce")

    def sync_handler(self, key: str) -> bool:
        """Run the global admission pass; returns whether ``key`` names a
        workload still waiting for quota (requeue-with-backoff signal)."""
        self._admit_pass()
        if key == PERIODIC_KEY:
            return False
        with self._lock:
            return key in self._pending_keys

    # ------------------------------------------------------------------
    # The admission pass
    # ------------------------------------------------------------------

    def _admit_pass(self) -> None:
        with self.profiler.phase(profiling.PHASE_QUEUE_ADMISSION):
            self._admit_pass_locked()

    def _admit_pass_locked(self) -> None:
        with self._lock:
            now = self.clock()
            cq_objs = self.api.list("clusterqueues")
            lq_objs = self.api.list("localqueues")
            cluster_queues = {
                cq.name: cq
                for cq in (ClusterQueue.from_dict(o) for o in cq_objs)
                if cq.name
            }
            local_queues = {
                (lq.namespace, lq.name): lq
                for lq in (LocalQueue.from_dict(o) for o in lq_objs)
            }
            for name, cq in cluster_queues.items():
                self.ledger.set_queue(
                    name,
                    cohort=cq.spec.cohort,
                    quotas={
                        q.generation: QueueQuota(q.nominal_quota, q.borrowing_limit)
                        for q in cq.spec.quotas
                    },
                )
            for stale in set(self.ledger.queues()) - set(cluster_queues):
                self.ledger.remove_queue(stale)

            job_objs = self.api.list("tpujobs")
            # Every pass re-reads all three stores from apiserver truth —
            # that is the point (fresh-list discipline) and the cost the
            # scan counter makes visible.
            self.profiler.record_scan(
                "queue_admit", len(cq_objs) + len(lq_objs) + len(job_objs)
            )
            jobs = [TPUJob.from_dict(o) for o in job_objs]
            queued = [j for j in jobs if job_queue_name(j)]

            # Rebuild the ledger from admitted truth (cache.reconcile
            # analog): one charge per unfinished QuotaReserved=True job.
            charges: List[Tuple[JobKey, Charge]] = []
            admitted: List[TPUJob] = []
            waiting: List[TPUJob] = []
            for job in queued:
                if st.is_finished(job.status):
                    continue
                if is_admitted(job):
                    admitted.append(job)
                else:
                    waiting.append(job)
            for job in admitted:
                placement = self._resolve(job, cluster_queues, local_queues)
                footprint = self._footprint(job)
                if placement is None or footprint is None:
                    continue  # queue vanished; charge drops with it
                generation, chips = footprint
                cond = st.get_condition(job.status, JOB_QUOTA_RESERVED)
                charges.append((
                    (job.namespace, job.name),
                    Charge(placement, generation, chips,
                           cond.last_transition_time if cond else 0.0),
                ))
            self.ledger.reconcile(charges)

            # Pending workloads, bucketed per ClusterQueue.
            pending_by_cq: Dict[str, List[Tuple[TPUJob, str, int]]] = {}
            self._pending_keys = set()
            for job in waiting:
                key = f"{job.namespace}/{job.name}"
                # Single-writer gate: a queue-targeted job runs only after
                # admission; anything unadmitted is forced suspended first —
                # even one naming a queue that does not (yet) exist.
                if not job.spec.run_policy.suspend:
                    self._gate(job, now)
                placement = self._resolve(job, cluster_queues, local_queues)
                if placement is None:
                    self._pending_keys.add(key)
                    self._mark_queue_not_found(job, local_queues, now)
                    continue
                if st.has_condition(job.status, JOB_QUEUE_NOT_FOUND):
                    self._set_job_condition(
                        job, JOB_QUEUE_NOT_FOUND, QUEUE_FOUND_REASON,
                        f"queue {job_queue_name(job)} resolved to "
                        f"ClusterQueue {placement}",
                        status=st.CONDITION_FALSE, now=now, write=True,
                    )
                footprint = self._footprint(job)
                if footprint is None:
                    self._pending_keys.add(key)
                    self._mark_pending(
                        job,
                        "cannot compute chip footprint: invalid "
                        f"tpu.acceleratorType "
                        f"{job.spec.tpu.accelerator_type!r}",
                        now,
                    )
                    continue
                generation, chips = footprint
                pending_by_cq.setdefault(placement, []).append(
                    (job, generation, chips)
                )

            for cq_name in sorted(pending_by_cq):
                self._admit_queue(
                    cluster_queues[cq_name], pending_by_cq[cq_name], now
                )

            # Gauges + ClusterQueue status mirror, from this pass's truth.
            self._pending_counts = {name: 0 for name in cluster_queues}
            self._admitted_counts = {name: 0 for name in cluster_queues}
            for key, charge in self.ledger.charges().items():
                self._admitted_counts[charge.queue] = (
                    self._admitted_counts.get(charge.queue, 0) + 1
                )
            for cq_name, entries in pending_by_cq.items():
                still = [
                    1 for job, _, _ in entries
                    if f"{job.namespace}/{job.name}" in self._pending_keys
                ]
                self._pending_counts[cq_name] = len(still)
            self._refresh_gauges()
            self._mirror_queue_status(cluster_queues)

    def _admit_queue(
        self,
        cq: ClusterQueue,
        entries: List[Tuple[TPUJob, str, int]],
        now: float,
    ) -> None:
        """Priority-then-FIFO admission for one ClusterQueue, strict: the
        first workload that cannot fit (even after reclaim) blocks the
        rest, so high-priority large jobs are not starved by small ones
        slipping past them."""
        entries.sort(
            key=lambda e: (
                -self._job_priority(e[0]),
                e[0].metadata.creation_timestamp or 0.0,
                f"{e[0].namespace}/{e[0].name}",
            )
        )
        ahead = 0
        for job, generation, chips in entries:
            key = f"{job.namespace}/{job.name}"
            if ahead:
                self._pending_keys.add(key)
                self._mark_pending(
                    job,
                    f"waiting for {ahead} workload(s) ahead in "
                    f"ClusterQueue {cq.name}",
                    now,
                )
                ahead += 1
                continue
            ok, free = self.ledger.fits(cq.name, generation, chips)
            if not ok and cq.spec.preemption.reclaim_within_cohort == RECLAIM_ANY:
                victims = self.ledger.reclaim_candidates(
                    cq.name, generation, chips
                )
                if victims:
                    for victim_key in victims:
                        self._evict(victim_key, cq.name, job, now)
                    ok, free = self.ledger.fits(cq.name, generation, chips)
            if not ok:
                self._pending_keys.add(key)
                self._mark_pending(
                    job,
                    insufficient_quota_message(cq.name, generation, chips, free),
                    now,
                )
                ahead = 1
                continue
            self._admit(job, cq.name, generation, chips, now)

    # ------------------------------------------------------------------
    # Decisions
    # ------------------------------------------------------------------

    def _admit(self, job: TPUJob, cq_name: str, generation: str, chips: int,
               now: float) -> None:
        self.ledger.reserve(
            (job.namespace, job.name), cq_name, generation, chips,
            admitted_at=now,
        )
        live = self._patch_suspend(job, False)
        if live is None:  # deleted underneath us: give the chips back
            self.ledger.release((job.namespace, job.name))
            return
        msg = (
            f"Admitted by ClusterQueue {cq_name}: reserved {chips} "
            f"google.com/tpu ({generation})"
        )
        self._set_job_condition(
            live, JOB_QUOTA_RESERVED, ADMITTED_REASON, msg,
            status=st.CONDITION_TRUE, now=now, write=True,
            queue=cq_name, chips=chips,
        )
        self.recorder.event(live, EVENT_TYPE_NORMAL, ADMITTED_REASON, msg)
        self._last_failure_msg.pop(f"{job.namespace}/{job.name}", None)
        created = live.metadata.creation_timestamp
        if created is not None:
            self.admission_duration.observe(max(0.0, now - created), cq_name)
        self.log.info(
            "admitted %s/%s: %d chips (%s) in ClusterQueue %s",
            job.namespace, job.name, chips, generation, cq_name,
            cluster_queue=cq_name,
        )

    def _evict(self, victim_key: JobKey, lender: str, claimant: TPUJob,
               now: float) -> None:
        """Re-suspend a borrowing workload and return its chips (Kueue
        reclaimWithinCohort eviction).  The controller observes the
        suspend flip and tears the workers down."""
        charge = self.ledger.charge_of(victim_key)
        if charge is None:
            return
        self.ledger.release(victim_key)
        namespace, name = victim_key
        try:
            victim = self.tpujobs.tpujobs(namespace).get(name)
        except NotFoundError:
            return
        self._patch_suspend(victim, True)
        msg = (
            f"Evicted from ClusterQueue {charge.queue}: ClusterQueue "
            f"{lender} reclaimed {charge.chips} borrowed google.com/tpu "
            f"({charge.generation}) for {claimant.namespace}/{claimant.name}"
        )
        self._set_job_condition(
            victim, JOB_QUOTA_RESERVED, EVICTED_REASON, msg,
            status=st.CONDITION_FALSE, now=now, write=True,
            queue=charge.queue, chips=charge.chips,
        )
        self.recorder.event(victim, EVENT_TYPE_WARNING, EVICTED_REASON, msg)
        self.evictions.inc(1, charge.queue)
        self.log.info(
            "evicted %s/%s from ClusterQueue %s (reclaim by %s)",
            namespace, name, charge.queue, lender, cluster_queue=charge.queue,
        )

    def _gate(self, job: TPUJob, now: float) -> None:
        """Force an unadmitted queue-targeted job suspended (the webhook
        role Kueue plays at creation time)."""
        live = self._patch_suspend(job, True)
        if live is None:
            return
        msg = (
            f"Suspended until admitted by LocalQueue "
            f"{job.namespace}/{job_queue_name(job)}"
        )
        self.recorder.event(live, EVENT_TYPE_NORMAL, SUSPENDED_BY_QUEUE_REASON, msg)
        self.log.info(
            "gated %s/%s: queue-targeted jobs start suspended",
            job.namespace, job.name,
        )

    def _mark_pending(self, job: TPUJob, message: str, now: float) -> None:
        key = f"{job.namespace}/{job.name}"
        first_report = self._last_failure_msg.get(key) != message
        self._last_failure_msg[key] = message
        changed = self._set_job_condition(
            job, JOB_QUOTA_RESERVED, PENDING_REASON, message,
            status=st.CONDITION_FALSE, now=now, write=True,
        )
        if first_report or changed:
            self.recorder.event(job, EVENT_TYPE_WARNING, PENDING_REASON, message)

    def _mark_queue_not_found(self, job: TPUJob, local_queues, now: float) -> None:
        queue = job_queue_name(job)
        lq = local_queues.get((job.namespace, queue))
        if lq is None:
            msg = f"LocalQueue {job.namespace}/{queue} not found"
        else:
            msg = (
                f"ClusterQueue {lq.spec.cluster_queue} referenced by "
                f"LocalQueue {job.namespace}/{queue} not found"
            )
        first_report = self._last_failure_msg.get(f"{job.namespace}/{job.name}") != msg
        self._last_failure_msg[f"{job.namespace}/{job.name}"] = msg
        changed = self._set_job_condition(
            job, JOB_QUEUE_NOT_FOUND, QUEUE_NOT_FOUND_REASON, msg,
            status=st.CONDITION_TRUE, now=now, write=True,
        )
        if first_report or changed:
            self.recorder.event(
                job, EVENT_TYPE_WARNING, QUEUE_NOT_FOUND_REASON, msg
            )

    # ------------------------------------------------------------------
    # Helpers
    # ------------------------------------------------------------------

    def _job_priority(self, job: TPUJob) -> int:
        sp = job.spec.run_policy.scheduling_policy
        if sp is None or not sp.priority_class:
            return 0
        return self._priorities.get(sp.priority_class, 0)

    def _footprint(self, job: TPUJob) -> Optional[Tuple[str, int]]:
        """(generation, total chips) for a job: slice shape x numSlices."""
        shape = topology.resolve_shape_or_none(
            job.spec.tpu.accelerator_type, job.spec.tpu.topology
        )
        if shape is None:
            return None
        return shape.generation, shape.chips * max(1, job.spec.tpu.num_slices)

    def _resolve(self, job: TPUJob, cluster_queues, local_queues) -> Optional[str]:
        """LocalQueue-in-namespace -> ClusterQueue name, or None."""
        lq = local_queues.get((job.namespace, job_queue_name(job)))
        if lq is None:
            return None
        cq_name = lq.spec.cluster_queue
        return cq_name if cq_name in cluster_queues else None

    def _patch_suspend(self, job: TPUJob, value: bool) -> Optional[TPUJob]:
        """Flip ``runPolicy.suspend`` on the live object (the one
        spec-write this package is allowed; see tests/test_lint.py)."""
        client = self.tpujobs.tpujobs(job.namespace)

        def flip():
            live = client.get(job.name)
            if bool(live.spec.run_policy.suspend) == value:
                return live
            live.spec.run_policy.suspend = value
            return client.update(live)

        try:
            return retry.retry_on_conflict(flip, retry.DEFAULT_RETRY)
        except NotFoundError:
            return None

    def _set_job_condition(
        self, job: TPUJob, type_: str, reason: str, message: str, *,
        status: str, now: float, write: bool, **attrs,
    ) -> bool:
        """Extra ``attrs`` ride the flight-recorder entry so the goodput
        ledger can attribute queue-wait time to a specific ClusterQueue
        without parsing the human-readable message."""
        if not st.update_job_conditions(
            job, type_, reason, message, status=status, now=now
        ):
            return False
        self.flight_recorder.record(
            job.namespace, job.name, flightrecorder.CONDITION,
            reason=reason, message=message, type=type_, status=status,
            **attrs,
        )
        if write:
            self._write_status(job)
        return True

    def _write_status(self, job: TPUJob) -> None:
        client = self.tpujobs.tpujobs(job.namespace)

        def attempt():
            try:
                client.update_status(job)
            except ConflictError:
                live = client.get(job.name)
                live.status = job.status
                client.update_status(live)

        try:
            retry.retry_on_conflict(attempt, retry.DEFAULT_RETRY)
        except NotFoundError:
            pass

    def _refresh_gauges(self) -> None:
        with self._lock:
            self.pending_workloads.remove_matching()
            self.admitted_workloads.remove_matching()
            for name, count in self._pending_counts.items():
                self.pending_workloads.set(float(count), name)
            for name, count in self._admitted_counts.items():
                self.admitted_workloads.set(float(count), name)

    def _mirror_queue_status(self, cluster_queues: Dict[str, ClusterQueue]) -> None:
        """kube-style status mirror on each ClusterQueue, written only on
        change (the controller's changed-status discipline)."""
        for name, cq in cluster_queues.items():
            want = {
                "pendingWorkloads": self._pending_counts.get(name, 0),
                "admittedWorkloads": self._admitted_counts.get(name, 0),
                "usage": self.ledger.usage_by_generation(name),
            }
            have = cq.status.to_dict()
            want_trim = {k: v for k, v in want.items() if v}
            if want_trim == have:
                continue
            obj = cq.to_dict()
            obj["status"] = want
            try:
                self.api.update_status("clusterqueues", obj)
            except (ConflictError, NotFoundError):
                pass  # next pass re-mirrors from fresh truth


# ----------------------------------------------------------------------
# Bootstrap (cmd/operator.py --cluster-queue)
# ----------------------------------------------------------------------


def parse_cluster_queue_spec(spec: str) -> ClusterQueue:
    """Parse a ``--cluster-queue`` flag value into a ClusterQueue.

    Syntax: ``name[@cohort]:gen=chips[,gen=chips...]`` — e.g.
    ``team-a@research:v5e=16,v5p=8``.  Bootstrap queues borrow without
    limit and reclaim within their cohort (the permissive defaults;
    declarative manifests can say otherwise).
    """
    head, sep, quota_part = spec.partition(":")
    if not sep or not quota_part:
        raise ValueError(
            f"--cluster-queue {spec!r}: expected name[@cohort]:gen=chips[,...]"
        )
    name, _, cohort = head.partition("@")
    if not name:
        raise ValueError(f"--cluster-queue {spec!r}: queue name is empty")
    quotas = []
    for entry in quota_part.split(","):
        generation, eq, chips = entry.partition("=")
        if not eq or not generation:
            raise ValueError(
                f"--cluster-queue {spec!r}: bad quota entry {entry!r}"
            )
        try:
            nominal = int(chips)
        except ValueError:
            raise ValueError(
                f"--cluster-queue {spec!r}: chip count {chips!r} is not an integer"
            )
        quotas.append({"generation": generation, "nominalQuota": nominal})
    return ClusterQueue.from_dict({
        "metadata": {"name": name},
        "spec": {
            "cohort": cohort,
            "quotas": quotas,
            "preemption": {"reclaimWithinCohort": RECLAIM_ANY},
        },
    })


def bootstrap_queues(api: InMemoryAPIServer, specs: List[str],
                     namespace: str = "") -> None:
    """Create the ``--cluster-queue`` ClusterQueues plus a same-named
    LocalQueue each in ``namespace`` (default "default"), skipping any
    that already exist (declarative manifests win)."""
    namespace = namespace or "default"
    for spec in specs:
        cq = parse_cluster_queue_spec(spec)
        try:
            api.create("clusterqueues", cq.to_dict())
        except AlreadyExistsError:
            pass
        lq = LocalQueue.from_dict({
            "metadata": {"name": cq.name, "namespace": namespace},
            "spec": {"clusterQueue": cq.name},
        })
        try:
            api.create("localqueues", lq.to_dict())
        except AlreadyExistsError:
            pass
