"""In-process Kueue analog: multi-tenant quota & admission queueing.

TPUJobs that name a LocalQueue (``spec.runPolicy.schedulingPolicy.queue``)
are created suspended and admitted by the QueueManager flipping
``runPolicy.suspend`` once chip quota is reserved in their ClusterQueue —
the same suspend-based handshake the reference operator delegates to
sigs.k8s.io/kueue.

- quota.py   — chip-denominated usage ledger with cohort borrowing and
               reclaim accounting (release-then-reserve discipline, like
               scheduler/cache.py).
- manager.py — the QueueManager controller: watches TPUJobs + queues,
               admits priority-then-FIFO, evicts borrowers on reclaim.

The QueueManager is the single writer of ``suspend`` while enabled
(enforced by a lint rule in tests/test_lint.py).
"""

from .manager import QueueManager, bootstrap_queues, parse_cluster_queue_spec  # noqa: F401
from .quota import QuotaLedger, insufficient_quota_message  # noqa: F401
