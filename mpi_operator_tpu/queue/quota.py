"""Chip-denominated quota ledger for ClusterQueues.

Kueue's cache/quota bookkeeping (sigs.k8s.io/kueue ClusterQueue usage,
flavor borrowing) collapsed to the one dimension TPU fleets ration:
``google.com/tpu`` chips, partitioned by TPU generation.  One Charge per
admitted workload; the ledger answers "does this workload fit" under
cohort borrowing rules and names the youngest borrowers to evict when a
lender wants its nominal quota back.

Discipline mirrors scheduler/cache.py: ``reserve`` releases any prior
charge for the same key first (re-reserve replaces, never stacks),
``release`` is idempotent, and ``reconcile`` rebuilds the whole ledger
from observed truth.  The invariant — usage always equals the sum of
live charges, never negative, never double-freed — is property-tested in
tests/test_queue.py.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..runtime import locktrace
from typing import Dict, Iterable, List, Optional, Tuple

# (namespace, name) of the admitted TPUJob.
JobKey = Tuple[str, str]


def insufficient_quota_message(queue: str, generation: str, chips: int,
                               free: int) -> str:
    """The kube-style admission failure message (Kueue wording)."""
    return (
        f"insufficient quota in ClusterQueue {queue}: needs {chips} "
        f"google.com/tpu ({generation}), {free} free"
    )


@dataclass(frozen=True)
class QueueQuota:
    """One ClusterQueue's quota for one generation."""

    nominal: int = 0
    borrowing_limit: Optional[int] = None  # None = unbounded borrowing


@dataclass(frozen=True)
class Charge:
    """Chips one admitted workload holds against one ClusterQueue."""

    queue: str
    generation: str
    chips: int
    admitted_at: float = 0.0


@dataclass
class _QueueEntry:
    cohort: str = ""
    quotas: Dict[str, QueueQuota] = field(default_factory=dict)


class QuotaLedger:
    """Usage accounting for a set of ClusterQueues, cohort-aware."""

    def __init__(self):
        self._lock = locktrace.rlock("queue.quota")
        self._queues: Dict[str, _QueueEntry] = {}
        self._charges: Dict[JobKey, Charge] = {}
        # (queue, generation) -> admitted chips, kept incrementally.
        self._usage: Dict[Tuple[str, str], int] = {}

    # -- queue topology --------------------------------------------------

    def set_queue(self, name: str, cohort: str = "",
                  quotas: Optional[Dict[str, QueueQuota]] = None) -> None:
        with self._lock:
            self._queues[name] = _QueueEntry(cohort=cohort,
                                             quotas=dict(quotas or {}))

    def remove_queue(self, name: str) -> None:
        """Drop a queue and every charge held against it (cache.remove_node
        analog: charges leave with their queue, usage never dangles)."""
        with self._lock:
            self._queues.pop(name, None)
            for key in [k for k, c in self._charges.items()
                        if c.queue == name]:
                self.release(key)

    def queues(self) -> List[str]:
        with self._lock:
            return sorted(self._queues)

    def cohort_of(self, queue: str) -> str:
        with self._lock:
            entry = self._queues.get(queue)
            return entry.cohort if entry else ""

    def _cohort_members(self, queue: str) -> List[str]:
        entry = self._queues.get(queue)
        if entry is None or not entry.cohort:
            return [queue]
        return [n for n, e in self._queues.items() if e.cohort == entry.cohort]

    # -- accounting ------------------------------------------------------

    def nominal(self, queue: str, generation: str) -> int:
        with self._lock:
            entry = self._queues.get(queue)
            if entry is None:
                return 0
            quota = entry.quotas.get(generation)
            return quota.nominal if quota else 0

    def usage(self, queue: str, generation: str) -> int:
        with self._lock:
            return self._usage.get((queue, generation), 0)

    def usage_by_generation(self, queue: str) -> Dict[str, int]:
        with self._lock:
            return {
                gen: chips
                for (q, gen), chips in sorted(self._usage.items())
                if q == queue and chips
            }

    def borrowed(self, queue: str, generation: str) -> int:
        """Chips this queue holds beyond its nominal quota."""
        with self._lock:
            return max(
                0, self.usage(queue, generation) - self.nominal(queue, generation)
            )

    def charge_of(self, key: JobKey) -> Optional[Charge]:
        with self._lock:
            return self._charges.get(key)

    def charges(self) -> Dict[JobKey, Charge]:
        with self._lock:
            return dict(self._charges)

    # -- admission arithmetic --------------------------------------------

    def free(self, queue: str, generation: str) -> int:
        """Chips this queue could still admit for ``generation``: its own
        nominal headroom plus whatever the cohort has left to lend,
        capped by the queue's borrowingLimit."""
        with self._lock:
            entry = self._queues.get(queue)
            if entry is None:
                return 0
            quota = entry.quotas.get(generation)
            if quota is None:
                return 0
            used = self.usage(queue, generation)
            if not entry.cohort:
                return max(0, quota.nominal - used)
            members = self._cohort_members(queue)
            cohort_nominal = sum(self.nominal(m, generation) for m in members)
            cohort_used = sum(self.usage(m, generation) for m in members)
            slack = max(0, cohort_nominal - cohort_used)
            # A borrowingLimit caps total usage at nominal + limit.
            if quota.borrowing_limit is not None:
                cap = quota.nominal + quota.borrowing_limit - used
                slack = min(slack, max(0, cap))
            return slack

    def fits(self, queue: str, generation: str, chips: int) -> Tuple[bool, int]:
        """(does a ``chips``-sized workload fit now, free chips)."""
        with self._lock:
            free = self.free(queue, generation)
            return chips <= free, free

    def reserve(self, key: JobKey, queue: str, generation: str, chips: int,
                admitted_at: float = 0.0) -> None:
        """Charge ``chips`` against ``queue``. Releases any prior charge
        for ``key`` first (re-reserve replaces, never stacks); raises
        RuntimeError with the admission-failure message when the
        workload does not fit."""
        with self._lock:
            self.release(key)
            ok, free = self.fits(queue, generation, chips)
            if not ok:
                raise RuntimeError(
                    insufficient_quota_message(queue, generation, chips, free)
                )
            self._charges[key] = Charge(queue, generation, chips, admitted_at)
            slot = (queue, generation)
            self._usage[slot] = self._usage.get(slot, 0) + chips

    def release(self, key: JobKey) -> None:
        """Return ``key``'s chips. Idempotent — releasing an uncharged key
        is a no-op, so completion + eviction racing never double-frees."""
        with self._lock:
            charge = self._charges.pop(key, None)
            if charge is None:
                return
            slot = (charge.queue, charge.generation)
            remaining = self._usage.get(slot, 0) - charge.chips
            if remaining > 0:
                self._usage[slot] = remaining
            else:
                self._usage.pop(slot, None)

    # -- reclaim ---------------------------------------------------------

    def reclaim_candidates(self, lender: str, generation: str,
                           chips: int) -> Optional[List[JobKey]]:
        """Which borrowers to evict so a ``chips``-sized workload fits in
        ``lender`` — Kueue's reclaimWithinCohort move.  Victims are the
        globally youngest charges (largest admitted_at) in cohort queues
        that are over their nominal quota; each simulated eviction stops
        charging its queue once that queue is back under nominal.
        Returns None when even evicting every borrower cannot make the
        workload fit (so callers evict nobody for nothing)."""
        with self._lock:
            entry = self._queues.get(lender)
            if entry is None or not entry.cohort:
                return None
            # Reclaim serves the lender's *nominal* entitlement only: a
            # workload that itself needs to borrow cannot evict others.
            if self.usage(lender, generation) + chips > self.nominal(
                lender, generation
            ):
                return None
            members = set(self._cohort_members(lender))
            sim_usage = {
                m: self.usage(m, generation) for m in members
            }
            borrowers = sorted(
                (
                    (key, charge)
                    for key, charge in self._charges.items()
                    if charge.queue in members and charge.queue != lender
                    and charge.generation == generation
                ),
                key=lambda kv: (-kv[1].admitted_at, kv[0]),
            )
            victims: List[JobKey] = []
            for key, charge in borrowers:
                free = self.free(lender, generation)
                if chips <= free:
                    break
                # Only charges keeping their queue over nominal are
                # borrowed quota; evicting within nominal reclaims nothing.
                if sim_usage[charge.queue] <= self.nominal(
                    charge.queue, generation
                ):
                    continue
                victims.append(key)
                sim_usage[charge.queue] -= charge.chips
                # free() sees live usage; model the eviction by charging
                # the simulated release against the real ledger copy.
                self._usage[(charge.queue, generation)] = max(
                    0, self._usage.get((charge.queue, generation), 0)
                    - charge.chips
                )
            fits_now = chips <= self.free(lender, generation)
            # Undo the simulation.
            for key in victims:
                charge = self._charges[key]
                slot = (charge.queue, generation)
                self._usage[slot] = self._usage.get(slot, 0) + charge.chips
            if not fits_now:
                return None
            return victims

    # -- rebuild ---------------------------------------------------------

    def reconcile(self, charges: Iterable[Tuple[JobKey, Charge]]) -> None:
        """Full rebuild from observed truth (cache.reconcile analog):
        every pass starts from what the API server actually admits, so
        drift between manager restarts cannot leak chips."""
        with self._lock:
            self._charges = {}
            self._usage = {}
            for key, charge in charges:
                if charge.queue not in self._queues:
                    continue
                self._charges[key] = charge
                slot = (charge.queue, charge.generation)
                self._usage[slot] = self._usage.get(slot, 0) + charge.chips
