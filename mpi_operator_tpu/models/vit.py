"""Vision Transformer (ViT) in Flax — the third transformer family.

The reference operator ships no model code at all (its examples run user
Horovod containers over tf_cnn_benchmarks CNNs —
/root/reference/examples/v2beta1/tensorflow-benchmarks/,
README.md:175-206); this framework's model zoo is first-class, and ViT
closes the gap between its conv family (resnet.py) and its language
families (bert.py, llama.py): image workloads on the transformer stack.

TPU-first choices:

- **patchify is a matmul, not a conv**: non-overlapping p×p patches are
  a pure reshape ([B, H/p, p, W/p, p, C] → [B, N, p²·C]) followed by a
  Dense — lands directly on the MXU with no conv lowering;
- attention through the projection-layout flash kernel
  (``ops.flash_attention_bshd`` — zero layout copies, see PERF.md) with
  the same ``attention_impl`` dispatch surface as bert/llama;
- pre-LN blocks (the ViT/AugReg convention), bf16 compute / f32 params,
  f32 logits via the shared ``ops.losses.f32_logits`` idiom;
- dp/fsdp/tp sharding rules in the same shape as the other families.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax.numpy as jnp
import optax
from jax.sharding import PartitionSpec as P

from ..parallel.mesh import FSDP, TP


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    num_classes: int = 1000
    dim: int = 768
    n_layers: int = 12
    n_heads: int = 12
    ffn_dim: int = 3072
    norm_eps: float = 1e-6
    dtype: Any = jnp.bfloat16
    # 'flash' (projection-layout pallas kernel) or 'dense' (XLA oracle).
    attention_impl: str = "flash"
    # 128 is safe everywhere; 256 measured best at bench scale on v5e
    # (TUNE_CAPTURE r5) — bench.py defaults to 256.
    flash_block_q: int = 128
    flash_block_k: int = 128
    # Per-layer jax.checkpoint for large-batch sweeps.
    remat: bool = False
    remat_policy: str = "dots"

    @property
    def n_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads


def vit_base(**overrides) -> ViTConfig:
    """ViT-B/16 (86M params)."""
    return dataclasses.replace(ViTConfig(), **overrides)


def tiny(**overrides) -> ViTConfig:
    base = ViTConfig(
        image_size=32, patch_size=8, num_classes=16, dim=32, n_layers=2,
        n_heads=2, ffn_dim=64, dtype=jnp.float32, attention_impl="dense",
    )
    return dataclasses.replace(base, **overrides)


class EncoderBlock(nn.Module):
    config: ViTConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        b, s, _ = x.shape
        hd = cfg.head_dim
        dense = lambda feats, name: nn.Dense(
            feats, dtype=cfg.dtype, param_dtype=jnp.float32, name=name
        )
        ln = lambda name: nn.LayerNorm(
            epsilon=cfg.norm_eps, dtype=cfg.dtype, name=name
        )

        h = ln("attn_norm")(x)
        q = dense(cfg.dim, "wq")(h).reshape(b, s, cfg.n_heads, hd)
        k = dense(cfg.dim, "wk")(h).reshape(b, s, cfg.n_heads, hd)
        v = dense(cfg.dim, "wv")(h).reshape(b, s, cfg.n_heads, hd)
        if cfg.attention_impl == "flash":
            from ..ops.attention import flash_attention_bshd

            att = flash_attention_bshd(
                q, k, v, causal=False,
                block_q=cfg.flash_block_q, block_k=cfg.flash_block_k,
            )
        elif cfg.attention_impl == "dense":
            from ..ops.attention import attention_reference

            att = attention_reference(
                q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
                v.transpose(0, 2, 1, 3), causal=False,
            ).transpose(0, 2, 1, 3)
        else:
            raise ValueError(
                f"vit attention_impl must be 'flash' or 'dense', got "
                f"{cfg.attention_impl!r}"
            )
        x = x + dense(cfg.dim, "wo")(att.reshape(b, s, cfg.dim))
        h = ln("mlp_norm")(x)
        h = nn.gelu(dense(cfg.ffn_dim, "ffn_in")(h))
        return x + dense(cfg.dim, "ffn_out")(h)


class ViT(nn.Module):
    config: ViTConfig

    @nn.compact
    def __call__(self, images):
        """images [B, H, W, C] → logits [B, num_classes] (f32)."""
        cfg = self.config
        b, hh, ww, c = images.shape
        p = cfg.patch_size
        if hh % p or ww % p:
            raise ValueError(
                f"image {hh}x{ww} not divisible by patch size {p}"
            )
        # Patchify as reshape + Dense: exact for non-overlapping patches
        # and a single MXU matmul instead of a conv lowering.
        patches = images.astype(cfg.dtype).reshape(
            b, hh // p, p, ww // p, p, c
        ).transpose(0, 1, 3, 2, 4, 5).reshape(b, -1, p * p * c)
        x = nn.Dense(
            cfg.dim, dtype=cfg.dtype, param_dtype=jnp.float32, name="embed"
        )(patches)

        cls = self.param(
            "cls", nn.initializers.zeros_init(), (1, 1, cfg.dim), jnp.float32
        )
        x = jnp.concatenate(
            [jnp.broadcast_to(cls.astype(cfg.dtype), (b, 1, cfg.dim)), x],
            axis=1,
        )
        pos = self.param(
            "pos_embed", nn.initializers.normal(0.02),
            (1, cfg.n_patches + 1, cfg.dim), jnp.float32,
        )
        x = x + pos.astype(cfg.dtype)

        block = EncoderBlock
        if cfg.remat:
            from .llama import remat_policy_for

            block = nn.remat(
                EncoderBlock, static_argnums=(), policy=remat_policy_for(cfg)
            )
        for i in range(cfg.n_layers):
            x = block(cfg, name=f"layer_{i}")(x)
        x = nn.LayerNorm(
            epsilon=cfg.norm_eps, dtype=cfg.dtype, name="final_norm"
        )(x)
        # Classification from the CLS token; f32 logits for a stable CE
        # with compute-dtype operands (ops/losses.py:f32_logits idiom).
        from ..ops.losses import f32_logits

        # Small-normal head (not the fine-tune-style zeros init): a zero
        # head kills every upstream gradient on step one (d_x = g @ 0).
        head = self.param(
            "head", nn.initializers.normal(0.02),
            (cfg.dim, cfg.num_classes), jnp.float32,
        )
        return f32_logits(x[:, 0], head)


def init_params(model: ViT, rng, batch: int = 2):
    cfg = model.config
    images = jnp.zeros(
        (batch, cfg.image_size, cfg.image_size, 3), jnp.float32
    )
    return model.init(rng, images)["params"]


def loss_fn(model: ViT, params, images, labels):
    logits = model.apply({"params": params}, images)
    return jnp.mean(
        optax.softmax_cross_entropy_with_integer_labels(logits, labels)
    )


def make_train_step(model: ViT, optimizer, accum_steps: int = 1):
    from ..parallel.accum import make_update_step

    return make_update_step(
        lambda p, im, lb: loss_fn(model, p, im, lb), optimizer, accum_steps
    )


def flops_per_image(cfg: ViTConfig) -> float:
    """Forward FLOPs per image (2×MAC convention, matmul params only —
    the same accounting the bert/llama suites use). Patch embed + per-
    layer qkv/o/ffn + attention's 4·N·d per token + head."""
    n = cfg.n_patches + 1
    per_token_params = (
        cfg.patch_size ** 2 * 3 * cfg.dim          # embed (patch tokens)
        + cfg.n_layers * (4 * cfg.dim ** 2 + 2 * cfg.dim * cfg.ffn_dim)
    )
    attn = cfg.n_layers * 4 * n * n * cfg.dim      # 2 matmuls × 2×MAC
    return 2.0 * per_token_params * n + attn + 2.0 * cfg.dim * cfg.num_classes


def param_sharding_rules(mesh):
    """tp/fsdp rules in the family-standard shape (see llama.py)."""
    from ..parallel.sharding import ends_with, mesh_axis

    tp = mesh_axis(mesh, TP)
    fsdp = mesh_axis(mesh, FSDP)
    return [
        (ends_with("wq/kernel", "wk/kernel", "wv/kernel", "ffn_in/kernel"),
         P(fsdp, tp)),
        (ends_with("wo/kernel", "ffn_out/kernel"), P(tp, fsdp)),
        (ends_with("embed/kernel"), P(fsdp, tp)),
        (ends_with("head",), P(fsdp, tp)),
    ]
