"""Encoder-decoder (seq2seq) transformer in Flax — the fourth
transformer family, completing the architecture classes in the zoo
(conv: resnet; encoder: bert/vit; decoder: llama; sparse: moe;
pipelined: llama_pp; here: encoder-decoder with cross-attention).

The reference operator ships no model code (user containers own the
math — SURVEY.md §2.4). TPU-first choices match the siblings:

- all three attention kinds (encoder self, decoder causal self, decoder
  cross) run the projection-layout flash kernels
  (``ops.flash_attention_bshd`` — zero layout copies; cross-attention
  exercises the kernels' Sq != Sk path that the ops tier pins);
- pre-LN blocks, bf16 compute / f32 params, f32 logits through the
  shared ``ops.losses.f32_logits`` idiom, learned absolute positions
  (T5-style relative position buckets would need an additive-bias lane
  in the kernels — not worth the fusion break);
- teacher-forced training loss with shifted decoder inputs; the same
  ``parallel.accum`` update-step wrapper as every other family.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax.numpy as jnp
import optax
from jax.sharding import PartitionSpec as P

from ..parallel.mesh import FSDP, TP


@dataclasses.dataclass(frozen=True)
class Seq2SeqConfig:
    vocab_size: int = 32128
    dim: int = 512
    n_enc_layers: int = 6
    n_dec_layers: int = 6
    n_heads: int = 8
    ffn_dim: int = 2048
    max_seq_len: int = 512
    norm_eps: float = 1e-6
    dtype: Any = jnp.bfloat16
    attention_impl: str = "flash"  # 'flash' (flat kernels) | 'dense'
    flash_block_q: int = 128
    flash_block_k: int = 128

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads


def t5_small_shape(**overrides) -> Seq2SeqConfig:
    """t5-small-shaped config (~60M params; structure, not weights)."""
    return dataclasses.replace(Seq2SeqConfig(), **overrides)


def tiny(**overrides) -> Seq2SeqConfig:
    base = Seq2SeqConfig(
        vocab_size=128, dim=32, n_enc_layers=2, n_dec_layers=2, n_heads=2,
        ffn_dim=64, max_seq_len=64, dtype=jnp.float32,
        attention_impl="dense",
    )
    return dataclasses.replace(base, **overrides)


def _attend(cfg, q, k, v, causal):
    """Shared attention dispatch: flat flash or the dense oracle.
    q [B, Sq, H, D]; k, v [B, Sk, H, D]."""
    if cfg.attention_impl == "flash":
        from ..ops.attention import flash_attention_bshd

        return flash_attention_bshd(
            q, k, v, causal=causal,
            block_q=cfg.flash_block_q, block_k=cfg.flash_block_k,
        )
    if cfg.attention_impl == "dense":
        from ..ops.attention import attention_reference

        T = lambda x: x.transpose(0, 2, 1, 3)
        return T(attention_reference(T(q), T(k), T(v), causal=causal))
    raise ValueError(
        f"seq2seq attention_impl must be 'flash' or 'dense', got "
        f"{cfg.attention_impl!r}"
    )


class _Attention(nn.Module):
    """One attention sublayer (self or cross) in projection layout."""

    config: Seq2SeqConfig
    causal: bool = False

    @nn.compact
    def __call__(self, x, kv):
        cfg = self.config
        b, sq, _ = x.shape
        sk = kv.shape[1]
        hd = cfg.head_dim
        dense = lambda feats, name: nn.Dense(
            feats, use_bias=False, dtype=cfg.dtype, param_dtype=jnp.float32,
            name=name,
        )
        q = dense(cfg.dim, "wq")(x).reshape(b, sq, cfg.n_heads, hd)
        k = dense(cfg.dim, "wk")(kv).reshape(b, sk, cfg.n_heads, hd)
        v = dense(cfg.dim, "wv")(kv).reshape(b, sk, cfg.n_heads, hd)
        att = _attend(cfg, q, k, v, self.causal)
        return dense(cfg.dim, "wo")(att.reshape(b, sq, cfg.dim))


class _MLP(nn.Module):
    config: Seq2SeqConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        dense = lambda feats, name: nn.Dense(
            feats, use_bias=False, dtype=cfg.dtype, param_dtype=jnp.float32,
            name=name,
        )
        return dense(cfg.dim, "ffn_out")(
            nn.gelu(dense(cfg.ffn_dim, "ffn_in")(x))
        )


class _EncoderBlock(nn.Module):
    config: Seq2SeqConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        ln = lambda name: nn.LayerNorm(
            epsilon=cfg.norm_eps, dtype=cfg.dtype, name=name
        )
        h = ln("attn_norm")(x)
        x = x + _Attention(cfg, causal=False, name="self_attn")(h, h)
        x = x + _MLP(cfg, name="mlp")(ln("mlp_norm")(x))
        return x


class _DecoderBlock(nn.Module):
    config: Seq2SeqConfig

    @nn.compact
    def __call__(self, x, enc):
        cfg = self.config
        ln = lambda name: nn.LayerNorm(
            epsilon=cfg.norm_eps, dtype=cfg.dtype, name=name
        )
        h = ln("self_norm")(x)
        x = x + _Attention(cfg, causal=True, name="self_attn")(h, h)
        h = ln("cross_norm")(x)
        x = x + _Attention(cfg, causal=False, name="cross_attn")(h, enc)
        x = x + _MLP(cfg, name="mlp")(ln("mlp_norm")(x))
        return x


class Seq2Seq(nn.Module):
    config: Seq2SeqConfig

    @nn.compact
    def __call__(self, src_tokens, dec_tokens):
        """src_tokens [B, S_src], dec_tokens [B, S_dec] (teacher-forced
        decoder inputs) → f32 logits [B, S_dec, V]."""
        cfg = self.config
        embed = nn.Embed(
            cfg.vocab_size, cfg.dim, dtype=cfg.dtype,
            param_dtype=jnp.float32, name="embed",  # shared enc/dec table
        )
        pos = nn.Embed(
            cfg.max_seq_len, cfg.dim, dtype=cfg.dtype,
            param_dtype=jnp.float32, name="pos_embed",
        )

        def with_pos(tokens):
            b, s = tokens.shape
            return embed(tokens) + pos(
                jnp.broadcast_to(jnp.arange(s), (b, s))
            )

        enc = with_pos(src_tokens)
        for i in range(cfg.n_enc_layers):
            enc = _EncoderBlock(cfg, name=f"enc_{i}")(enc)
        enc = nn.LayerNorm(
            epsilon=cfg.norm_eps, dtype=cfg.dtype, name="enc_norm"
        )(enc)

        dec = with_pos(dec_tokens)
        for i in range(cfg.n_dec_layers):
            dec = _DecoderBlock(cfg, name=f"dec_{i}")(dec, enc)
        dec = nn.LayerNorm(
            epsilon=cfg.norm_eps, dtype=cfg.dtype, name="dec_norm"
        )(dec)

        # Tied head on the shared table, f32 logits (losses.f32_logits).
        from ..ops.losses import f32_logits

        return f32_logits(dec, embed.embedding.T)


def init_params(model: Seq2Seq, rng, batch: int = 2, src: int = 16,
                dec: int = 8):
    src_t = jnp.zeros((batch, src), jnp.int32)
    dec_t = jnp.zeros((batch, dec), jnp.int32)
    return model.init(rng, src_t, dec_t)["params"]


def loss_fn(model: Seq2Seq, params, src_tokens, targets,
            bos_id: int = 0):
    """Teacher-forced seq2seq CE: decoder inputs are the targets shifted
    right behind ``bos_id``."""
    dec_in = jnp.concatenate(
        [jnp.full_like(targets[:, :1], bos_id), targets[:, :-1]], axis=1
    )
    logits = model.apply({"params": params}, src_tokens, dec_in)
    return jnp.mean(
        optax.softmax_cross_entropy_with_integer_labels(logits, targets)
    )


def make_train_step(model: Seq2Seq, optimizer, accum_steps: int = 1):
    from ..parallel.accum import make_update_step

    return make_update_step(
        lambda p, s, t: loss_fn(model, p, s, t), optimizer, accum_steps
    )


def param_sharding_rules(mesh):
    """tp/fsdp rules in the family-standard shape (see llama.py)."""
    from ..parallel.sharding import active_mesh_axis, ends_with, mesh_axis

    tp = mesh_axis(mesh, TP)
    fsdp = mesh_axis(mesh, FSDP)
    return [
        (ends_with("wq/kernel", "wk/kernel", "wv/kernel", "ffn_in/kernel"),
         P(fsdp, tp)),
        (ends_with("wo/kernel", "ffn_out/kernel"), P(tp, fsdp)),
        # Without a real (size>1) tp, fsdp splits the vocab dim — a
        # feature-dim shard forces a full remat of dx in the backward
        # scatter (llama.py).
        (ends_with("embed/embedding"),
         P(tp, fsdp) if active_mesh_axis(mesh, TP) else P(fsdp, None)),
    ]
