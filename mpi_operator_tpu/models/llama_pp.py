"""Pipelined Llama: the transformer blocks run as a GPipe pipeline over
the ``pp`` mesh axis (``parallel.pipeline``), completing the trainer's
six-axis story for a real model family.

Layout: embedding, final RMSNorm, and the LM head are computed on every
device (replicated compute — they are a sliver of the FLOPs); the L
blocks are stage-stacked ``[P, L/P, ...]`` and shard over ``pp``, with
activations hopping stage→stage via ``lax.ppermute`` inside the GPipe
scan. The microbatch dim can additionally shard over ``dp``, and on a
mesh with an ``fsdp`` axis the block weights ALSO shard ZeRO-3-style
over fsdp (first weight dim): each stage all-gathers one layer's
weights just before using it, and AD's transpose of that gather is the
reduce-scatter that keeps gradients sharded — GPipe x ZeRO-3 with two
explicit collectives. The whole thing differentiates end-to-end (the
reversed scan IS the backward schedule), so the standard
optimizer/accum plumbing applies unchanged.

The reference delegates pipelining to user MPI programs entirely
(SURVEY.md §2.4 "TP/PP/SP: absent"); this is the framework-owned
equivalent, built as pure SPMD collectives.

Tensor parallelism composes too: the pipeline's shard_map is manual
over pp/dp/fsdp/sp only and leaves ``tp`` an AUTO axis, so GSPMD keeps
inserting the Megatron column/row collectives inside each stage while
activations ppermute between stages (kernel output features shard over
tp, ``_block_leaf_placement``). Sequence parallelism composes as well:
with ``attention_impl='ring'`` (contiguous or zigzag layout — the
global permute lives at the loss edges, outside the stages) or
``'ulysses'`` (per-shard all-to-alls inside the manual region) the
stages run the per-shard sp kernels with global RoPE positions derived
from the shard index — dp x fsdp x tp x sp x pp in one train step.

MoE pipelines too: ``ep`` rides as another AUTO axis (expert
dispatch/combine all-to-alls stay GSPMD-derived inside the stages),
and the router load-balance loss flows through the pipeline's
``with_aux`` accumulator — per-row routing makes the pipelined loss,
aux and capacity drops included, exactly the plain model's. MoE
composes with dp/tp/ep (fsdp's dense-kernel gather and sp's
per-sequence capacity do not apply).

Restrictions: ``n_layers`` must divide by the pp size, and fsdp
sharding (dense models)
covers the blocks (embed/head replicate). Checkpoints hold the
stage-stacked [P, L/P, ...] layout: resume on the same pp size is
shape-identical; resuming onto a DIFFERENT pp size needs a restack
(unstack to [L, ...] and re-split — the layer order is pp-invariant).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..parallel.mesh import DP, EP, FSDP, PP, SP, TP
from ..parallel.pipeline import microbatch, pipeline, unmicrobatch
from .llama import Block, LlamaConfig, RMSNorm, remat_policy_for


def _axis_size(mesh, name) -> int:
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    return sizes.get(name, 1)


def _fsdp_size(mesh) -> int:
    return _axis_size(mesh, FSDP)


def _block_leaf_spec(leaf) -> P:
    """MANUAL-axis spec for one stage-stacked block leaf [P, L/P, d, ...]:
    stage dim over pp, the first weight dim over fsdp (ZeRO-3 storage;
    stages all-gather a layer's weights just before using it). tp never
    appears here — it stays an AUTO axis inside the pipeline's
    shard_map, managed by GSPMD."""
    return P(PP, None, FSDP, *([None] * (leaf.ndim - 3)))


def _block_leaf_placement(leaf, fsdp: bool, tp: bool) -> P:
    """STORAGE spec for a stage-stacked block leaf: the manual spec
    plus, for matrix kernels ([P, L/P, in, out] — norm scales are 3-D),
    the output-feature dim over tp. GSPMD reads this layout at the
    shard_map boundary and inserts the tp collectives inside the
    stages."""
    spec = list(_block_leaf_spec(leaf)) if fsdp else (
        [PP] + [None] * (leaf.ndim - 1))
    if tp and leaf.ndim >= 4:
        spec[-1] = TP
    return P(*spec)


def stack_block_params(params, n_layers: int, n_stages: int):
    """Convert a standard Llama init's ``layer_i`` subtrees into the
    stage-stacked pytree the pipeline wants: leaves [P, L/P, ...]."""
    if n_layers % n_stages:
        raise ValueError(
            f"n_layers {n_layers} not divisible by pp stages {n_stages}"
        )
    layers = [params[f"layer_{i}"] for i in range(n_layers)]
    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *layers)
    per = n_layers // n_stages
    return jax.tree_util.tree_map(
        lambda w: w.reshape((n_stages, per) + w.shape[1:]), stacked
    )


def unstack_block_params(blocks):
    """Invert ``stack_block_params``: stage-stacked leaves [P, L/P, ...]
    back into ``{layer_i: ...}`` subtrees (layer order is pp-invariant).
    Lets non-pipelined consumers — decoding, a resume onto a pp=1 mesh —
    use a pipelined checkpoint directly."""
    flat = jax.tree_util.tree_map(
        lambda w: w.reshape((w.shape[0] * w.shape[1],) + w.shape[2:]), blocks
    )
    leaves, _ = jax.tree_util.tree_flatten(flat)
    n_layers = leaves[0].shape[0]
    return {
        f"layer_{i}": jax.tree_util.tree_map(lambda w: w[i], flat)
        for i in range(n_layers)
    }


def restack_block_params(blocks, n_stages_new: int):
    """Re-split stage-stacked block leaves [P, L/P, ...] onto a new pp
    size [P', L/P', ...] (layer order is pp-invariant, so this is a pure
    reshape) — the elastic-resume path for pipelined checkpoints."""
    def re(w):
        p, per = w.shape[0], w.shape[1]
        n_layers = p * per
        if n_layers % n_stages_new:
            raise ValueError(
                f"{n_layers} layers not divisible by new pp size "
                f"{n_stages_new}"
            )
        return w.reshape(
            (n_stages_new, n_layers // n_stages_new) + w.shape[2:]
        )

    return jax.tree_util.tree_map(re, blocks)


def init_pp_params(cfg: LlamaConfig, n_stages: int, rng):
    """Fresh pipelined params for ``cfg``. Inits through a flash-
    attention variant — param shapes don't depend on the attention
    impl, and tracing the ring at init would demand a bound sp axis
    the init-time forward doesn't have (the mirror image of the
    'ring-shard' replace inside make_pp_loss_fn)."""
    import dataclasses

    from .llama import Llama, init_params

    model = Llama(dataclasses.replace(cfg, attention_impl="flash"))
    return pp_params_from_init(init_params(model, rng), cfg, n_stages)


def pp_params_from_init(params, cfg: LlamaConfig, n_stages: int):
    """Regroup a standard init into the pipelined layout:
    {embed, blocks (stage-stacked), final_norm, lm_head}."""
    out = {
        "embed": params["embed"],
        "blocks": stack_block_params(params, cfg.n_layers, n_stages),
        "final_norm": params["final_norm"],
    }
    if not cfg.tie_embeddings:
        out["lm_head"] = params["lm_head"]
    return out


def _on_mesh(spec: P, mesh) -> P:
    """Drop spec axes the mesh does not carry (e.g. a pp-stacked
    checkpoint placed on a no-pp mesh for the sequential fallback)."""
    return P(*(
        a if (a is None or a in mesh.axis_names) else None for a in spec
    ))


def _placement_with_path(path, leaf, fsdp: bool, tp: bool, ep: bool) -> P:
    """Storage spec for one stacked block leaf, MoE-aware: expert
    kernels [P, L/P, E, d_in, d_out] put the expert dim over ep and the
    hidden (F) dim over tp (both AUTO axes inside the pipeline); the
    tiny router replicates; dense leaves fall through to
    ``_block_leaf_placement``."""
    ps = jax.tree_util.keystr(path)
    if leaf.ndim == 5 and "expert_w" in ps:
        spec = [PP, None, EP if ep else None, None, None]
        if tp:
            # F is d_out for wg/wu ([E, D, F]) and d_in for wd
            # ([E, F, D]) — mirror moe.param_sharding_rules.
            spec[3 if "expert_wd" in ps else 4] = TP
        return P(*spec)
    if "router" in ps:
        return P(PP)
    return _block_leaf_placement(leaf, fsdp, tp)


def shard_pp_params(pp_params, mesh):
    """Blocks shard over pp on the stage dim — and, when the mesh has an
    fsdp axis, over fsdp on the first weight dim (ZeRO-3 storage; the
    stage loop all-gathers one layer at a time), over tp on kernel
    output features, and over ep on the expert dim (both GSPMD-managed
    inside the stages). Embed/norm/head replicate: they are used on
    every stage and are a sliver of the block weights for deep models."""
    fsdp = _fsdp_size(mesh) > 1
    tp = _axis_size(mesh, TP) > 1
    ep = _axis_size(mesh, EP) > 1
    blocks = jax.tree_util.tree_map_with_path(
        lambda path, w: jax.device_put(
            w,
            NamedSharding(mesh, _on_mesh(
                _placement_with_path(path, w, fsdp, tp, ep), mesh
            )),
        ),
        pp_params["blocks"],
    )
    rest = {
        k: jax.tree_util.tree_map(
            lambda w: jax.device_put(w, NamedSharding(mesh, P())), v
        )
        for k, v in pp_params.items() if k != "blocks"
    }
    return {**rest, "blocks": blocks}


def shard_pp_opt_state(opt_state, mesh):
    """Place optimizer-state leaves for the pipelined layout. Moment
    leaves mirroring the stage-stacked blocks (ndim >= 3 — every block
    leaf is [P, L/P, d, ...]; embed/norm/head are at most 2-D) shard
    like the blocks; everything else, including step counters,
    replicates over the WHOLE mesh. Explicit placement matters: leaving
    init outputs committed to one device makes later jits reject the
    mixed device sets — and gives checkpoint resume a wrong template."""
    fsdp = _fsdp_size(mesh) > 1
    repl = NamedSharding(mesh, P())

    tp = _axis_size(mesh, TP) > 1
    ep = _axis_size(mesh, EP) > 1

    def place(path, w):
        if getattr(w, "ndim", 0) >= 3:
            return jax.device_put(
                w,
                NamedSharding(mesh, _on_mesh(
                    _placement_with_path(path, w, fsdp, tp, ep), mesh
                )),
            )
        return jax.device_put(w, repl)

    return jax.tree_util.tree_map_with_path(place, opt_state)


def make_pp_loss_fn(cfg: LlamaConfig, mesh, microbatch_size: int):
    """Next-token CE with the blocks pipelined over pp. Params must be in
    the ``pp_params_from_init`` layout. Honors ``cfg.xent_chunk`` and
    ``cfg.remat`` (each layer inside a stage is checkpointed)."""
    if cfg.attention_impl not in (
        "flash", "flash-bhsd", "dense", "ring", "ulysses"
    ):
        raise ValueError(
            f"pipelined Llama runs flash/dense attention inside stages "
            f"(or the ppermute ring / Ulysses all-to-alls when the mesh "
            f"has sp), not {cfg.attention_impl!r}"
        )
    names = mesh.axis_names
    fsdp = _fsdp_size(mesh) > 1
    tp = _axis_size(mesh, TP) > 1
    sp = _axis_size(mesh, SP)
    moe = cfg.is_moe
    if moe and fsdp:
        raise ValueError(
            "pipelined MoE composes with dp/tp/ep, not fsdp — the ZeRO-3 "
            "per-layer gather assumes dense [in, out] kernels, and the "
            "expert dim wants ep"
        )
    if moe and sp > 1:
        raise ValueError(
            "pipelined MoE does not compose with sp: routing capacity is "
            "per sequence, and a sequence shard would route against a "
            "fraction of it"
        )
    zigzag = False
    if cfg.attention_impl in ("ring", "ulysses"):
        if sp <= 1:
            raise ValueError(
                f"attention_impl={cfg.attention_impl!r} in the pipeline "
                f"needs an sp mesh axis of size > 1"
            )
        if cfg.zigzag_ring and cfg.attention_impl == "ring":
            # The real sequence is validated by zigzag_indices at trace
            # time; this catches the config-level mismatch early.
            if cfg.max_seq_len % (2 * sp):
                raise ValueError(
                    f"zigzag needs seq divisible by 2*sp={2 * sp}"
                )
            zigzag = True
        # The stages run inside a region that is ALSO manual over sp, so
        # the Block's attention must call the per-shard kernels, not
        # wrap its own shard_map.
        import dataclasses as _dc

        block = Block(_dc.replace(
            cfg, attention_impl=cfg.attention_impl + "-shard"
        ))
    elif sp > 1:
        raise ValueError(
            f"the mesh has sp={sp} but attention_impl={cfg.attention_impl!r}"
            f" computes shard-local attention — each sequence shard would "
            f"silently attend only to itself; use attention_impl='ring' "
            f"or 'ulysses'"
        )
    else:
        block = Block(cfg)
    # Microbatch rows shard over every batch axis (dp AND fsdp — the
    # same layout shard_batch produces); leaving fsdp off forces XLA to
    # replicate-and-repartition activations at the shard_map boundary.
    batch_axes = tuple(a for a in (DP, FSDP) if a in names)
    # With a ring, the sequence dim of one microbatch [mb, S, D] is
    # manual over sp too.
    seq_axis = SP if sp > 1 else None
    state_spec = P(batch_axes if batch_axes else None, seq_axis, None)
    # tp and ep stay AUTO axes: the pipeline shard_map is manual over
    # pp/dp/fsdp/sp only, so GSPMD keeps inserting the tensor-parallel
    # collectives (Megatron column/row splits) and the expert
    # dispatch/combine all-to-alls inside each stage.
    auto = {a for a in (TP, EP) if _axis_size(mesh, a) > 1}
    manual = frozenset(a for a in names if a not in auto) if auto else None

    def stage_fn(stage_params, h):
        if sp > 1:
            # h carries the LOCAL sequence shard: RoPE needs the global
            # positions of its rows (contiguous run, or the two zigzag
            # half-chunks — the same ids the ring uses for masking).
            from ..ops.ring_attention import _shard_ids

            local = _shard_ids(
                jax.lax.axis_index(SP), sp, h.shape[1], zigzag
            )
        else:
            local = jnp.arange(h.shape[1])
        positions = jnp.broadcast_to(local, h.shape[:2])

        def layer(carry, p_layer):
            h, aux_sum = carry

            def run(h):
                if fsdp:
                    # ZeRO-3 moment: materialize THIS layer's full
                    # weights from their fsdp shards; under remat the
                    # gather replays in backward, so full weights never
                    # persist. AD's transpose of the gather is the
                    # reduce-scatter that keeps grads sharded.
                    p_full = jax.tree_util.tree_map(
                        lambda w: jax.lax.all_gather(
                            w, FSDP, axis=0, tiled=True
                        ),
                        p_layer,
                    )
                else:
                    p_full = p_layer
                return block.apply({"params": p_full}, h, positions)

            if cfg.remat:
                run = jax.checkpoint(run, policy=remat_policy_for(cfg))
            h, aux = run(h)
            return (h, aux_sum + aux), None

        (h, aux_sum), _ = jax.lax.scan(
            layer, (h, jnp.zeros((), jnp.float32)), stage_params
        )
        return (h, aux_sum) if moe else h

    def loss_fn(params, tokens):
        emb = params["embed"]["embedding"]  # [V, D] f32
        h = emb[tokens].astype(cfg.dtype)
        if zigzag:
            # Permute ONCE at the model edges (GSPMD land, full S):
            # device i of the ring ends up holding chunks i and 2n-1-i,
            # balancing causal work; every non-attention op is pointwise
            # over sequence, and the stages' _shard_ids agree.
            from ..ops.ring_attention import zigzag_indices, zigzag_inverse

            seq = tokens.shape[1]
            h = h[:, jnp.asarray(zigzag_indices(seq, sp))]
        x = microbatch(h, microbatch_size)  # [M, mb, S, D]
        y = pipeline(
            stage_fn, params["blocks"], x, mesh, state_spec=state_spec,
            params_spec=jax.tree_util.tree_map(
                _block_leaf_spec, params["blocks"]
            ) if fsdp else None,
            manual_axes=manual,
            with_aux=moe,
        )
        if moe:
            y, aux_raw = y
            # Raw sum over (microbatch, dp-shard) chunks of a per-chunk
            # group MEAN — dividing by the chunk count recovers the
            # full-batch mean the plain model computes (routing is
            # per-row, so the numbers agree exactly). On a mesh with no
            # pp axis, pipeline()'s sequential fallback runs each
            # microbatch GLOBALLY (dp handled by GSPMD), so the chunk
            # count is just M.
            n_chunks = x.shape[0]
            if PP in names:
                n_chunks *= _axis_size(mesh, DP) * _axis_size(mesh, FSDP)
            aux_total = aux_raw / n_chunks
        h = unmicrobatch(y)
        if zigzag:
            # Natural order for the next-token shift in the loss.
            h = h[:, jnp.asarray(zigzag_inverse(tokens.shape[1], sp))]
        h = RMSNorm(cfg.norm_eps).apply(
            {"params": params["final_norm"]}, h
        )
        w = (
            params["embed"]["embedding"].T
            if cfg.tie_embeddings
            else params["lm_head"]["kernel"]
        )
        from ..ops.losses import lm_xent_chunked

        chunk = cfg.xent_chunk if cfg.xent_chunk > 0 else tokens.shape[1]
        ce = lm_xent_chunked(h[:, :-1], w, tokens[:, 1:], chunk=chunk)
        if moe:
            return ce + cfg.router_aux_coef * aux_total
        return ce

    return loss_fn


def make_pp_train_step(cfg: LlamaConfig, mesh, optimizer,
                       microbatch_size: int, accum_steps: int = 1):
    from ..parallel.accum import make_update_step

    return make_update_step(
        make_pp_loss_fn(cfg, mesh, microbatch_size), optimizer, accum_steps
    )
