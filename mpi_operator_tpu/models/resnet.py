"""ResNet v1.5 in Flax — the benchmark workload.

Reference analog: the README's headline benchmark is tf_cnn_benchmarks
ResNet-101 with Horovod allreduce (/root/reference/README.md:175-206,
examples/v2beta1/tensorflow-benchmarks/tensorflow-benchmarks.yaml).  This
is the same model family (v1.5: stride 2 on the 3x3 of each downsampling
bottleneck), built TPU-first: bfloat16 compute with float32 params and
batch stats, NHWC layouts that XLA tiles onto the MXU, and a jit-able
train step whose gradients allreduce over mesh axes via GSPMD instead of
Horovod/NCCL.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax

ModuleDef = Any

# Stage layouts per depth.
STAGE_SIZES = {
    18: (2, 2, 2, 2),
    34: (3, 4, 6, 3),
    50: (3, 4, 6, 3),
    101: (3, 4, 23, 3),
    152: (3, 8, 36, 3),
}
BOTTLENECK = {18: False, 34: False, 50: True, 101: True, 152: True}


class BottleneckBlock(nn.Module):
    filters: int
    strides: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), strides=(self.strides, self.strides))(y)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters * 4, (1, 1), strides=(self.strides, self.strides),
                name="conv_proj",
            )(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class BasicBlock(nn.Module):
    filters: int
    strides: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), strides=(self.strides, self.strides))(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters, (1, 1), strides=(self.strides, self.strides),
                name="conv_proj",
            )(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class _ScanBody(nn.Module):
    """Adapter giving a ResNet block the (carry, _) -> (carry, None)
    shape ``nn.scan`` wants."""

    inner: ModuleDef

    @nn.compact
    def __call__(self, x, _):
        return self.inner()(x), None


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    bottleneck: bool = True
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.bfloat16
    # TPU stem: the 7x7/s2 conv on a 3-channel input underfeeds the MXU
    # (contraction depth 7*3=21 of 128 lanes). space_to_depth regroups the
    # input into 2x2 pixel blocks ([N,H,W,3] -> [N,H/2,W/2,12]) so the
    # equivalent stride-1 4x4 conv contracts over 4*12=48 — the standard
    # MLPerf ResNet TPU transform. Same function class: any 7x7/s2 stem
    # kernel maps exactly onto the 4x4 layout (see s2d_stem_kernel);
    # training from scratch just initializes the 4x4 form directly.
    space_to_depth: bool = False
    # BN reductions are half the train step (PERF.md); "pallas" routes
    # the stats and dγ/dβ passes through ops/bn.py's fused kernels.
    bn_impl: str = "xla"
    # bn_impl="pallas" only: layers below this element count take XLA
    # reductions (compile-time economics, ops/bn.py:PALLAS_MIN_ELEMS).
    # 0 = every BN layer through the kernels.
    bn_pallas_min_elems: Optional[int] = None
    # lax.scan over each stage's identical blocks (all but the strided
    # first one): the stage body compiles ONCE instead of per block —
    # ResNet-101's 30 repeated blocks dominate both the XLA graph and,
    # under bn_impl="pallas", the Mosaic kernel-instance count (each
    # pallas_call instance costs ~1 s of compile with no dedup; measured
    # via chipless AOT). Param layout changes: repeated blocks stack
    # under "stage{i}_rest" with a leading [n] axis.
    scan_stages: bool = False

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(
            nn.Conv, use_bias=False, dtype=self.dtype, param_dtype=jnp.float32
        )
        if self.bn_impl == "pallas":
            from ..ops.bn import PALLAS_MIN_ELEMS, TpuBatchNorm

            _BN = partial(
                TpuBatchNorm,
                pallas_min_elems=(
                    PALLAS_MIN_ELEMS if self.bn_pallas_min_elems is None
                    else self.bn_pallas_min_elems
                ),
            )
        elif self.bn_impl == "xla":
            _BN = nn.BatchNorm
        else:
            raise ValueError(f"unknown bn_impl {self.bn_impl!r}")
        norm = partial(
            _BN,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.dtype,
            param_dtype=jnp.float32,
        )
        block = BottleneckBlock if self.bottleneck else BasicBlock

        x = x.astype(self.dtype)
        if self.space_to_depth:
            b, h, w, c = x.shape
            if h % 2 or w % 2:
                raise ValueError(
                    f"space_to_depth stem needs even spatial dims, got "
                    f"{h}x{w}; pad the input or use space_to_depth=False"
                )
            x = x.reshape(b, h // 2, 2, w // 2, 2, c)
            x = x.transpose(0, 1, 3, 2, 4, 5).reshape(b, h // 2, w // 2, 4 * c)
            # pad (2,1): the 4x4 kernel is the 7x7 embedded in 8x8 with a
            # leading zero row/col, i.e. taps at original offsets -4..+3
            # around each output's 2x2 block -> 2 blocks left, 1 right.
            x = conv(self.num_filters, (4, 4), strides=(1, 1),
                     padding=[(2, 1), (2, 1)], name="conv_init")(x)
        else:
            x = conv(self.num_filters, (7, 7), strides=(2, 2),
                     padding=[(3, 3), (3, 3)], name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        for i, block_count in enumerate(self.stage_sizes):
            mk = partial(
                block, filters=self.num_filters * 2**i,
                conv=conv, norm=norm, act=nn.relu,
            )
            if not self.scan_stages:
                for j in range(block_count):
                    x = mk(strides=2 if i > 0 and j == 0 else 1,
                           name=f"stage{i}_block{j}")(x)
                continue
            # First block owns the stride + projection; the remaining
            # identical blocks run as ONE scanned body.
            x = mk(strides=2 if i > 0 else 1, name=f"stage{i}_block0")(x)
            n_rest = block_count - 1
            if n_rest:
                scanned = nn.scan(
                    _ScanBody,
                    variable_axes={"params": 0, "batch_stats": 0},
                    split_rngs={"params": True},
                    length=n_rest,
                    metadata_params={nn.PARTITION_NAME: None},
                )
                x, _ = scanned(
                    inner=partial(mk, strides=1), name=f"stage{i}_rest"
                )(x, None)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype, param_dtype=jnp.float32,
                     name="head")(x)
        return x.astype(jnp.float32)


def resnet(
    depth: int,
    num_classes: int = 1000,
    dtype=jnp.bfloat16,
    space_to_depth: bool = False,
    bn_impl: str = "xla",
    scan_stages: bool = False,
    bn_pallas_min_elems: "Optional[int]" = None,
) -> ResNet:
    return ResNet(
        stage_sizes=STAGE_SIZES[depth],
        bottleneck=BOTTLENECK[depth],
        num_classes=num_classes,
        dtype=dtype,
        space_to_depth=space_to_depth,
        bn_impl=bn_impl,
        scan_stages=scan_stages,
        bn_pallas_min_elems=bn_pallas_min_elems,
    )


def s2d_stem_kernel(k7):
    """Map a [7, 7, C, O] stem kernel onto the space_to_depth [4, 4, 4C, O]
    layout, exactly: embed into 8x8 with a leading zero row/col (the
    kernel tap at original offset -4, which the 7x7 never reads), then
    regroup rows/cols into (tap, subpixel) pairs matching the s2d input
    channel order (dy, dx, c)."""
    import numpy as np

    k7 = np.asarray(k7)
    c, o = k7.shape[2], k7.shape[3]
    k8 = np.zeros((8, 8, c, o), k7.dtype)
    k8[1:, 1:] = k7
    return (
        k8.reshape(4, 2, 4, 2, c, o)
        .transpose(0, 2, 1, 3, 4, 5)
        .reshape(4, 4, 4 * c, o)
    )


resnet50 = partial(resnet, 50)
resnet101 = partial(resnet, 101)


def flops_per_image(depth: int, image_size: int = 224) -> float:
    """Forward FLOPs/image at 224x224 in the 2xMAC convention (the one
    TPU peak-TFLOP specs use, so TFLOP/s / peak = honest MFU). The
    commonly quoted "ResNet-101 = 7.8 GFLOPs" is GMACs; x2 gives these
    (torchvision/ptflops figures). Scales quadratically in resolution."""
    base = {18: 3.6e9, 34: 7.3e9, 50: 8.2e9, 101: 15.7e9, 152: 23.1e9}[depth]
    return base * (image_size / 224) ** 2


def create_train_state(model: ResNet, rng, image_size: int = 224, batch: int = 8):
    """Init params + batch stats with a dummy batch.

    The init runs under jit: eager init executes every op individually,
    which with ``bn_impl="pallas"`` means ~one remote Mosaic compile per
    BN layer on tunnel-attached TPUs (~100 round-trips; this hung a
    round-3 bench capture for 29+ minutes before being killed). One
    jitted program is one compile."""
    init = jax.jit(partial(model.init, train=True))
    variables = init(
        rng, jnp.zeros((batch, image_size, image_size, 3), jnp.float32)
    )
    return variables["params"], variables["batch_stats"]


def make_train_step(model: ResNet, optimizer):
    """Build a jit-able SGD train step: (params, batch_stats, opt_state,
    images, labels) -> (params, batch_stats, opt_state, loss).

    Under a mesh, GSPMD turns the gradient reduction into an allreduce over
    the batch-sharded axes — the Horovod `--variable_update=horovod` analog
    with zero lines of communication code.
    """

    def loss_fn(params, batch_stats, images, labels):
        logits, updates = model.apply(
            {"params": params, "batch_stats": batch_stats},
            images,
            train=True,
            mutable=["batch_stats"],
        )
        one_hot = jax.nn.one_hot(labels, logits.shape[-1])
        loss = -jnp.mean(jnp.sum(one_hot * jax.nn.log_softmax(logits), axis=-1))
        return loss, updates["batch_stats"]

    def train_step(params, batch_stats, opt_state, images, labels):
        (loss, new_stats), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, batch_stats, images, labels
        )
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, new_stats, opt_state, loss

    return train_step
