"""Sparse Mixture-of-Experts FFN with expert parallelism over an ``ep``
mesh axis.

The reference operator delegates all model math to user containers; MoE
is part of this framework's compute layer the way ring attention is
(SURVEY.md §2.4). The design is the canonical TPU formulation (GShard /
Switch / t5x): routing becomes *static-shape dispatch and combine
einsums*, so there is no data-dependent gather — XLA tiles the expert
matmuls onto the MXU and inserts the all-to-alls from the shardings
alone (experts sharded over ``ep``, groups over ``dp``/``fsdp``).

Shapes (G groups = batch, S tokens/group, E experts, C capacity, D model
dim, F expert hidden dim):

    router probs    [G, S, E]     f32 softmax
    dispatch        [G, S, E, C]  0/1 — token (g, s) → slot (e, c)
    combine         [G, S, E, C]  dispatch × gate weight
    expert inputs   [E, G, C, D]  = einsum('gsec,gsd->egcd', dispatch, x)
    expert SwiGLU   [E, D, F] / [E, F, D] stacked weights, ep-sharded
    output          [G, S, D]     = einsum('gsec,egcd->gsd', combine, h)

Capacity is per group: C = ceil(top_k · S / E · capacity_factor).
Tokens that overflow an expert's slots are dropped for that choice
(their combine weight is 0) — Switch semantics; the residual connection
around the FFN carries them through unchanged.

Load-balance auxiliary loss is the Switch formulation
``E · Σ_e f_e · p_e`` (f_e = fraction of tokens whose top-1 choice is e,
p_e = mean router probability), ≈ 1.0 at perfect balance.
"""

from __future__ import annotations

import math
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..parallel.mesh import DP, EP, FSDP, TP
from ..parallel.sharding import active_mesh_axis as _axis


def expert_capacity(
    tokens_per_group: int, n_experts: int, top_k: int, capacity_factor: float
) -> int:
    return max(1, math.ceil(top_k * tokens_per_group / n_experts * capacity_factor))


def topk_gates(probs, top_k: int, *, normalize: bool = True):
    """Top-k selection + the gate-weight convention, single-sourced for
    the training dispatch (``routing``) and the decode path
    (``generate._moe_step``): returns (gates [..., K], idx [..., K],
    dense [..., E] combine weights). ``normalize=True`` is the Mixtral
    convention (selected gates sum to 1)."""
    e = probs.shape[-1]
    gates, idx = jax.lax.top_k(probs, top_k)
    if normalize:
        gates = gates / jnp.maximum(
            jnp.sum(gates, axis=-1, keepdims=True), 1e-9
        )
    dense_w = jnp.sum(
        jax.nn.one_hot(idx, e, dtype=probs.dtype) * gates[..., None],
        axis=-2,
    )
    return gates, idx, dense_w


def routing(probs, top_k: int, capacity: int, *, normalize: bool = True):
    """Static-shape top-k routing → (dispatch, combine, aux_loss).

    probs: [G, S, E] router probabilities (f32). Choice priority is
    k-major (every token's 1st choice claims slots before any 2nd
    choice), matching GShard so a token's primary expert is the last to
    drop it under pressure.
    """
    g, s, e = probs.shape
    gates, idx, _ = topk_gates(probs, top_k, normalize=normalize)

    oh = jax.nn.one_hot(idx, e, dtype=jnp.float32)  # [G, S, K, E]
    # Slot assignment: cumulative count over (k, s) within each group.
    oh_k = oh.transpose(0, 2, 1, 3)  # [G, K, S, E]
    pos = jnp.cumsum(oh_k.reshape(g, top_k * s, e), axis=1).reshape(
        g, top_k, s, e
    )
    pos = (pos - 1.0) * oh_k  # position of each (k, s) inside its expert
    pos_sel = jnp.sum(pos, axis=-1)  # [G, K, S]
    keep = (pos_sel < capacity) & (jnp.sum(oh_k, axis=-1) > 0)

    slot = jax.nn.one_hot(
        pos_sel.astype(jnp.int32), capacity, dtype=jnp.float32
    )  # [G, K, S, C]
    disp_k = (
        oh_k[..., None] * slot[..., None, :] * keep[..., None, None]
    )  # [G, K, S, E, C]
    dispatch = jnp.sum(disp_k, axis=1)  # sum over K → [G, S, E, C]
    gates_k = gates.transpose(0, 2, 1)  # [G, K, S]
    combine = jnp.sum(disp_k * gates_k[..., None, None], axis=1)  # [G, S, E, C]

    # Switch load-balance loss on top-1 assignments.
    top1 = jax.nn.one_hot(idx[..., 0], e, dtype=jnp.float32)  # [G, S, E]
    f = jnp.mean(top1, axis=1)  # [G, E] fraction routed
    p = jnp.mean(probs, axis=1)  # [G, E] mean prob
    aux = e * jnp.mean(jnp.sum(f * p, axis=-1))
    return dispatch, combine, aux


class MoEMLP(nn.Module):
    """Drop-in replacement for the dense SwiGLU MLP: returns (out, aux)."""

    dim: int
    ffn_dim: int
    n_experts: int
    top_k: int = 2
    capacity_factor: float = 1.25
    dtype: Any = jnp.bfloat16
    mesh: Optional[Any] = None
    # dtype of the combine weights in the output einsum. The compute
    # dtype (default) keeps both MXU operands bf16; f32 keeps the
    # combine exact at ~2x cost on that einsum (~5% of the MoE layer at
    # mixtral shapes). ROUTER-gradient parity holds either way up to
    # bf16 rounding — the combine weights' VALUES never enter
    # d(combine) = dy·h (bilinear einsum); tests/test_moe.py pins that
    # numerically. The cast DOES perturb dh = combine^T·dy (expert and
    # upstream gradients) along with the forward, like any bf16 op.
    combine_dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x):
        g, s, d = x.shape
        e, f = self.n_experts, self.ffn_dim
        cap = expert_capacity(s, e, self.top_k, self.capacity_factor)

        # Router in f32: tiny matmul, and bf16 softmax here visibly skews
        # balance at scale.
        router = self.param(
            "router", nn.initializers.lecun_normal(), (d, e), jnp.float32
        )
        probs = jax.nn.softmax(
            jnp.einsum("gsd,de->gse", x.astype(jnp.float32), router), axis=-1
        )
        dispatch, combine, aux = routing(probs, self.top_k, cap)
        dispatch = dispatch.astype(self.dtype)
        combine = combine.astype(self.combine_dtype or self.dtype)

        init = nn.initializers.lecun_normal(batch_axis=(0,))
        w_gate = self.param("expert_wg", init, (e, d, f), jnp.float32)
        w_up = self.param("expert_wu", init, (e, d, f), jnp.float32)
        w_down = self.param("expert_wd", init, (e, f, d), jnp.float32)

        ep = _axis(self.mesh, EP)
        batch_axes = tuple(a for a in (DP, FSDP) if _axis(self.mesh, a))
        constrain = (
            (lambda t, spec: jax.lax.with_sharding_constraint(t, spec))
            if self.mesh is not None and (ep or batch_axes)
            else (lambda t, spec: t)
        )
        from jax.sharding import PartitionSpec as P

        # All-to-all moment: groups-sharded tokens → experts-sharded rows.
        xin = x.astype(self.dtype)
        expert_in = jnp.einsum("gsec,gsd->egcd", dispatch, xin)
        expert_in = constrain(
            expert_in, P(ep, batch_axes if batch_axes else None, None, None)
        )

        tp = _axis(self.mesh, TP)
        h_gate = jnp.einsum(
            "egcd,edf->egcf", expert_in, w_gate.astype(self.dtype)
        )
        h_up = jnp.einsum("egcd,edf->egcf", expert_in, w_up.astype(self.dtype))
        h = constrain(
            nn.silu(h_gate) * h_up,
            P(ep, batch_axes if batch_axes else None, None, tp),
        )
        expert_out = jnp.einsum("egcf,efd->egcd", h, w_down.astype(self.dtype))
        expert_out = constrain(
            expert_out, P(ep, batch_axes if batch_axes else None, None, None)
        )

        # All-to-all back: experts-sharded rows → groups-sharded tokens.
        # Compute-dtype operands with f32 ACCUMULATION (the
        # ops/losses.py:f32_logits rationale): an f32xf32 einsum of this
        # size runs as multiple MXU passes. Each output row sums at most
        # top_k weighted terms, so bf16-rounding the combine weights
        # perturbs the (bf16) output below its own rounding step.
        out = jnp.einsum(
            "gsec,egcd->gsd", combine, expert_out,
            preferred_element_type=jnp.float32,
        )
        return out.astype(x.dtype), aux


def param_sharding_rules(mesh):
    """Sharding rules for MoE params (compose with the host model's):
    expert dim over ep, expert hidden dim over tp, model dim over fsdp;
    the router is tiny — replicate it."""
    from jax.sharding import PartitionSpec as P

    from ..parallel.sharding import ends_with, mesh_axis

    ep = mesh_axis(mesh, EP)
    tp = mesh_axis(mesh, TP)
    fsdp = mesh_axis(mesh, FSDP)
    return [
        (ends_with("expert_wg", "expert_wu"), P(ep, fsdp, tp)),
        (ends_with("expert_wd"), P(ep, tp, fsdp)),
        (ends_with("router"), P()),
    ]
