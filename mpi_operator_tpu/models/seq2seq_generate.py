"""Autoregressive decoding for the seq2seq family.

Same TPU-first discipline as the llama decoder (``models/generate.py``):
static shapes (preallocated decoder KV cache written with
``lax.dynamic_update_slice``), one ``lax.scan`` over steps, and the
decode math re-implements the block forward functionally — equivalence
against the training forward is pinned by test (teacher-forced decode
logits must match ``Seq2Seq.__call__`` exactly).

Encoder-decoder specifics:

- the encoder runs ONCE as a full-sequence pass (identical math to the
  training encoder, re-implemented functionally over the param tree);
- each decoder layer's cross-attention K/V are precomputed from the
  encoder output ONCE (they never change during decoding) — per step
  only the q projection and the [B, 1, S_src] cross scores are new;
- the decoder self-attention cache is the llama-style static cache.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from .seq2seq import Seq2SeqConfig


def _ln(x, p, eps):
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean((xf - mu) ** 2, axis=-1, keepdims=True)
    norm = (xf - mu) * jax.lax.rsqrt(var + eps)
    out = norm * p["scale"] + p["bias"]
    return out.astype(x.dtype)


def _proj(p, name, x, cfg):
    return x @ p[name]["kernel"].astype(cfg.dtype)


def _mlp(p, x, cfg):
    h = jax.nn.gelu(_proj(p, "ffn_in", x, cfg))
    return _proj(p, "ffn_out", h, cfg)


def _full_self_attention(p, x, cfg, causal):
    """Full-sequence attention for the one-shot encoder pass.
    x: [B, S, D_model]."""
    b, s, _ = x.shape
    hd = cfg.head_dim
    from ..ops.attention import attention_reference

    q = _proj(p, "wq", x, cfg).reshape(b, s, cfg.n_heads, hd)
    k = _proj(p, "wk", x, cfg).reshape(b, s, cfg.n_heads, hd)
    v = _proj(p, "wv", x, cfg).reshape(b, s, cfg.n_heads, hd)
    T = lambda t: t.transpose(0, 2, 1, 3)
    att = T(attention_reference(T(q), T(k), T(v), causal=causal))
    return _proj(p, "wo", att.reshape(b, s, cfg.dim), cfg)


def encode(params, cfg: Seq2SeqConfig, src_tokens):
    """The training encoder, functionally: [B, S_src] → [B, S_src, D]."""
    b, s = src_tokens.shape
    embed = params["embed"]["embedding"]
    pos = params["pos_embed"]["embedding"]
    x = (embed[src_tokens] + pos[jnp.arange(s)][None]).astype(cfg.dtype)
    for i in range(cfg.n_enc_layers):
        p = params[f"enc_{i}"]
        h = _ln(x, p["attn_norm"], cfg.norm_eps)
        x = x + _full_self_attention(p["self_attn"], h, cfg, causal=False)
        x = x + _mlp(p["mlp"], _ln(x, p["mlp_norm"], cfg.norm_eps), cfg)
    return _ln(x, params["enc_norm"], cfg.norm_eps)


def init_caches(params, cfg: Seq2SeqConfig, enc, batch: int, max_len: int):
    """(self-attn caches, precomputed cross K/V) for every decoder
    layer. Cross K/V never change during decoding — computed once."""
    hd = cfg.head_dim
    s_src = enc.shape[1]
    self_caches, cross_kvs = [], []
    for i in range(cfg.n_dec_layers):
        p = params[f"dec_{i}"]
        self_caches.append((
            jnp.zeros((batch, max_len, cfg.n_heads, hd), cfg.dtype),
            jnp.zeros((batch, max_len, cfg.n_heads, hd), cfg.dtype),
        ))
        ck = _proj(p["cross_attn"], "wk", enc, cfg).reshape(
            batch, s_src, cfg.n_heads, hd
        )
        cv = _proj(p["cross_attn"], "wv", enc, cfg).reshape(
            batch, s_src, cfg.n_heads, hd
        )
        cross_kvs.append((ck, cv))
    return self_caches, cross_kvs


def _attend_one(q, k, v, mask=None):
    """One-position attention: q [B, H, Dh]; k, v [B, S, H, Dh]."""
    s = jnp.einsum(
        "bhd,bshd->bhs", q.astype(jnp.float32), k.astype(jnp.float32)
    ) * (q.shape[-1] ** -0.5)
    if mask is not None:
        s = jnp.where(mask[:, None, :], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhs,bshd->bhd", p, v.astype(jnp.float32))


def _decode_step(params, cfg: Seq2SeqConfig, self_caches, cross_kvs,
                 token, pos):
    """One decoder position against the caches. token [B]; pos scalar.
    Returns (logits [B, V] f32, self_caches')."""
    b = token.shape[0]
    hd = cfg.head_dim
    embed = params["embed"]["embedding"]
    x = (embed[token] + params["pos_embed"]["embedding"][pos]).astype(
        cfg.dtype
    )
    new_caches = []
    for i in range(cfg.n_dec_layers):
        p = params[f"dec_{i}"]
        # Causal self-attention against the cache.
        h = _ln(x, p["self_norm"], cfg.norm_eps)
        q = _proj(p["self_attn"], "wq", h, cfg).reshape(b, cfg.n_heads, hd)
        k = _proj(p["self_attn"], "wk", h, cfg).reshape(b, cfg.n_heads, hd)
        v = _proj(p["self_attn"], "wv", h, cfg).reshape(b, cfg.n_heads, hd)
        ck, cv = self_caches[i]
        ck = jax.lax.dynamic_update_slice(ck, k[:, None], (0, pos, 0, 0))
        cv = jax.lax.dynamic_update_slice(cv, v[:, None], (0, pos, 0, 0))
        new_caches.append((ck, cv))
        visible = jnp.arange(ck.shape[1])[None, :] <= pos
        att = _attend_one(q, ck, cv, jnp.broadcast_to(visible, (b, ck.shape[1])))
        x = x + _proj(
            p["self_attn"], "wo",
            att.reshape(b, cfg.dim).astype(cfg.dtype), cfg,
        )
        # Cross-attention against the precomputed encoder K/V.
        h = _ln(x, p["cross_norm"], cfg.norm_eps)
        qc = _proj(p["cross_attn"], "wq", h, cfg).reshape(b, cfg.n_heads, hd)
        ek, ev = cross_kvs[i]
        catt = _attend_one(qc, ek, ev)
        x = x + _proj(
            p["cross_attn"], "wo",
            catt.reshape(b, cfg.dim).astype(cfg.dtype), cfg,
        )
        x = x + _mlp(p["mlp"], _ln(x, p["mlp_norm"], cfg.norm_eps), cfg)
    x = _ln(x, params["dec_norm"], cfg.norm_eps)
    from ..ops.losses import f32_logits

    return f32_logits(x, embed.T), new_caches


@partial(jax.jit, static_argnames=("cfg", "max_new", "bos_id"))
def generate(params, src_tokens, cfg: Seq2SeqConfig, max_new: int,
             bos_id: int = 0):
    """Greedy decode ``max_new`` tokens conditioned on ``src_tokens``
    [B, S_src], starting from ``bos_id``. Returns [B, max_new]."""
    b = src_tokens.shape[0]
    enc = encode(params, cfg, src_tokens)
    self_caches, cross_kvs = init_caches(params, cfg, enc, b, max_new)

    def step(carry, t):
        caches, token = carry
        logits, caches = _decode_step(
            params, cfg, caches, cross_kvs, token, t
        )
        nxt = jnp.argmax(logits, axis=-1).astype(src_tokens.dtype)
        return (caches, nxt), nxt

    init = (self_caches, jnp.full((b,), bos_id, src_tokens.dtype))
    _, emitted = jax.lax.scan(step, init, jnp.arange(max_new))
    return emitted.T  # [B, max_new]


def decode_logits_teacher_forced(params, cfg: Seq2SeqConfig, src_tokens,
                                 dec_tokens):
    """Teacher-forced logits through the CACHED decode path — must equal
    ``Seq2Seq.__call__(src, dec)`` exactly (the equivalence test)."""
    b, s_dec = dec_tokens.shape
    enc = encode(params, cfg, src_tokens)
    self_caches, cross_kvs = init_caches(params, cfg, enc, b, s_dec)

    def step(carry, t):
        caches = carry
        logits, caches = _decode_step(
            params, cfg, caches, cross_kvs, dec_tokens[:, t], t
        )
        return caches, logits

    _, logits = jax.lax.scan(step, self_caches, jnp.arange(s_dec))
    return logits.transpose(1, 0, 2)  # [B, S_dec, V]
