"""Llama-family decoder transformer in Flax — the FSDP/TP/SP flagship.

BASELINE.md milestone config 4 ("Flax Llama-3-8B FSDP on v5p-64"). The
reference operator never touches model code (it schedules user Horovod
containers, SURVEY.md §2.4); in our framework the model library is
first-class and TPU-first:

- bfloat16 compute / float32 params, f32 logits for the loss;
- attention through the pallas flash kernel (``ops.flash_attention``) or
  ring attention over an ``sp`` mesh axis (``ops.ring_attention``) for
  long-context sequence parallelism;
- GSPMD sharding rules (``param_sharding_rules``) lay qkv/mlp kernels out
  over ``tp`` and everything large over ``fsdp``, so the train step's
  collectives (all-gather params, reduce-scatter grads, allreduce over
  tp) ride ICI;
- per-layer ``jax.checkpoint`` (remat) trades FLOPs for HBM.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax
from jax.sharding import PartitionSpec as P

from ..parallel.mesh import FSDP, SP, TP


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128256
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    ffn_dim: int = 14336
    max_seq_len: int = 8192
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    remat: bool = True
    # What the per-layer checkpoint saves: 'full' recomputes everything
    # in the backward pass (min HBM, +~33% FLOPs); 'dots' saves matmul
    # outputs and recomputes only cheap elementwise ops
    # (jax.checkpoint_policies.dots_with_no_batch_dims_saveable — the
    # standard TPU transformer policy: the MXU never re-runs, HBM still
    # drops the big attention/FFN intermediates).
    remat_policy: str = "full"
    tie_embeddings: bool = False
    # 'flash' (pallas kernel), 'dense' (XLA reference), 'ring'
    # (sequence-parallel ppermute ring over the sp mesh axis), or
    # 'ulysses' (sequence-parallel via two all-to-alls over sp:
    # head-sharded full-sequence flash between them; sp must divide the
    # head count). 'ring'/'ulysses' require a mesh.
    attention_impl: str = "flash"
    # Flash kernel tile sizes — the on-hardware MFU tuning surface
    # (bench.py --flash-block-q/-k). 128 matches the MXU/lane shape and
    # is safe for any seq; at training scale 256/256 measured best on
    # v5e for llama/bert/vit alike (larger q-tiles divide the kernel's
    # internal k/v re-read; 512 exceeds the 16M scoped-vmem limit in
    # the backward kernel — TUNE_CAPTURE r5). bench.py defaults to 256.
    flash_block_q: int = 128
    flash_block_k: int = 128
    # With ring attention: lay the sequence out zigzag (device i holds
    # chunks i and 2n-1-i) so causal work balances across the ring. The
    # model permutes after the embedding and unpermutes before the head;
    # RoPE sees the true positions, so dense configs compute exactly
    # standard attention — only the layout (and the ring's load)
    # changes. MoE configs are the one caveat: WHICH tokens drop when an
    # expert overflows capacity follows token order (moe.py's cumsum
    # slotting), so under overflow a zigzag run drops a different —
    # equally arbitrary — token set than a contiguous run.
    zigzag_ring: bool = False
    # Sparse MoE FFN (models/moe.py): 0 = dense SwiGLU; > 0 replaces every
    # block's MLP with n_experts experts routed top-k, experts sharded
    # over the ep mesh axis. The train loss adds router_aux_coef × the
    # Switch load-balance loss.
    n_experts: int = 0
    moe_top_k: int = 2
    capacity_factor: float = 1.25
    router_aux_coef: float = 0.01
    # > 0: the train loss computes cross-entropy in sequence chunks of
    # this size (ops/losses.py:lm_xent_chunked) instead of materializing
    # the full [B, S, V] f32 logits — peak logits memory drops to
    # O(chunk * V) in both passes. 0 = standard full-logits path.
    xent_chunk: int = 0

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @property
    def is_moe(self) -> bool:
        return self.n_experts > 0


def llama3_8b(**overrides) -> LlamaConfig:
    return dataclasses.replace(LlamaConfig(), **overrides)


def tiny(**overrides) -> LlamaConfig:
    """Test-scale config: real structure, toy widths."""
    base = LlamaConfig(
        vocab_size=256, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
        ffn_dim=128, max_seq_len=128, dtype=jnp.float32, remat=False,
        attention_impl="dense",
    )
    return dataclasses.replace(base, **overrides)


def mixtral_8x7b(**overrides) -> LlamaConfig:
    """Mixtral-style sparse MoE: Llama structure, 8 experts routed top-2."""
    base = LlamaConfig(
        vocab_size=32000, dim=4096, n_layers=32, n_heads=32, n_kv_heads=8,
        ffn_dim=14336, max_seq_len=32768, rope_theta=1e6,
        n_experts=8, moe_top_k=2,
    )
    return dataclasses.replace(base, **overrides)


def tiny_moe(**overrides) -> LlamaConfig:
    """Test-scale MoE config (4 experts, top-2)."""
    merged = {"n_experts": 4, "moe_top_k": 2, **overrides}
    return tiny(**merged)


# The one name->config mapping both CLIs (cmd.train, cmd.generate) use —
# a checkpoint trained under a name must always be loadable under it.
CONFIGS = {
    "llama3-8b": llama3_8b,
    "llama-tiny": tiny,
    "mixtral-8x7b": mixtral_8x7b,
    "llama-moe-tiny": tiny_moe,
}


def config_for(name: str, **overrides) -> LlamaConfig:
    if name not in CONFIGS:
        raise KeyError(
            f"unknown llama model {name!r}; want one of {sorted(CONFIGS)}"
        )
    return CONFIGS[name](**overrides)


def _rope(x, positions, theta: float):
    """Rotary embeddings. x: [B, S, H, D_head]; positions: [B, S]."""
    d = x.shape[-1]
    freqs = 1.0 / (theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B, S, D/2]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


class F32LogitsDense(nn.Module):
    """Bias-free projection producing f32 logits from compute-dtype
    operands: the kernel lives in f32 (param tree identical to
    ``nn.Dense(name=...)`` — {name: {kernel}}), the matmul runs with
    operands in the input's dtype and ``preferred_element_type=f32``.
    ``nn.Dense(dtype=f32)`` would instead promote BOTH operands to f32,
    which the TPU MXU executes as multiple passes, several x slower."""

    features: int

    @nn.compact
    def __call__(self, x):
        from ..ops.losses import f32_logits

        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(),
            (x.shape[-1], self.features), jnp.float32,
        )
        return f32_logits(x, kernel)


class RMSNorm(nn.Module):
    eps: float = 1e-5

    @nn.compact
    def __call__(self, x):
        scale = self.param("scale", nn.initializers.ones_init(), (x.shape[-1],))
        xf = x.astype(jnp.float32)
        norm = xf * jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + self.eps)
        return (norm * scale).astype(x.dtype)


def remat_policy_for(cfg: "LlamaConfig"):
    """jax.checkpoint policy for cfg.remat_policy — shared by the plain
    per-layer remat and the pipelined stage remat (llama_pp.py)."""
    if cfg.remat_policy == "full":
        return None
    if cfg.remat_policy == "dots":
        # dots alone discards the flash kernels' (out, lse) residuals —
        # they are custom-call outputs, not dots — so the attention
        # FORWARD kernel reruns inside every backward. Save them by
        # name too (ops/attention.py tags them): O(S·H·D) extra bytes
        # per layer buys back a whole attention forward per layer.
        from ..ops.attention import ATTN_LSE_NAME, ATTN_OUT_NAME

        return jax.checkpoint_policies.save_from_both_policies(
            jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
            jax.checkpoint_policies.save_only_these_names(
                ATTN_OUT_NAME, ATTN_LSE_NAME
            ),
        )
    raise ValueError(
        f"remat_policy must be 'full' or 'dots', got {cfg.remat_policy!r}"
    )


def _use_zigzag(cfg: "LlamaConfig", mesh) -> bool:
    """The ONE decision for zigzag layout — the model-level permute and
    the per-layer ring call must always agree."""
    if cfg.attention_impl == "ring-shard":
        # Already inside a manual sp region (the pp×sp pipeline): the
        # caller owns the global permute; the flag alone decides.
        return cfg.zigzag_ring
    if not (cfg.attention_impl == "ring" and cfg.zigzag_ring and mesh is not None):
        return False
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    return sizes.get(SP, 1) > 1


class Attention(nn.Module):
    config: LlamaConfig
    mesh: Optional[Any] = None  # required for attention_impl='ring'/'ulysses'

    @nn.compact
    def __call__(self, x, positions):
        cfg = self.config
        b, s, _ = x.shape
        hd = cfg.head_dim
        dense = lambda feats, name: nn.Dense(
            feats, use_bias=False, dtype=cfg.dtype, param_dtype=jnp.float32,
            name=name,
        )
        q = dense(cfg.n_heads * hd, "wq")(x).reshape(b, s, cfg.n_heads, hd)
        k = dense(cfg.n_kv_heads * hd, "wk")(x).reshape(b, s, cfg.n_kv_heads, hd)
        v = dense(cfg.n_kv_heads * hd, "wv")(x).reshape(b, s, cfg.n_kv_heads, hd)

        q = _rope(q, positions, cfg.rope_theta)
        k = _rope(k, positions, cfg.rope_theta)

        # Transpose-free dispatch first: flash (projection-layout
        # kernel, zero layout copies — PERF.md) and the ring/ulysses
        # sequence-parallel twins run on q/k/v exactly as RoPE produced
        # them ([B, S, H, D]).
        from ..ops.ring_attention import sp_attention, sp_attention_bshd

        out = sp_attention_bshd(
            q, k, v, self.mesh, cfg.attention_impl, causal=True,
            zigzag=_use_zigzag(cfg, self.mesh),
            block_q=cfg.flash_block_q, block_k=cfg.flash_block_k,
        )
        if out is not None:
            return dense(cfg.dim, "wo")(out.reshape(b, s, cfg.n_heads * hd))
        # [B, H, S, D] layout: flash-bhsd (the transpose-convention
        # kernel, kept as the hardware A/B), the dense oracle, and the
        # pipeline's '-shard' impls ONLY when tp does not divide the
        # head counts. (The round-4 wedge — flat '-shard' gradients
        # aborting/hanging the XLA:CPU runtime in the pp×sp×tp nesting
        # — was root-caused to the AUTO-axis partitioner reaching the
        # interpret-mode kernel internals; the flat path now completes
        # the kernel region to manual over tp and handles '-shard'
        # above. hack/wedge_repro.py keeps the negative control.)

        q, k, v = (t.transpose(0, 2, 1, 3) for t in (q, k, v))
        out = sp_attention(
            q, k, v, self.mesh, cfg.attention_impl, causal=True,
            zigzag=_use_zigzag(cfg, self.mesh),
            block_q=cfg.flash_block_q, block_k=cfg.flash_block_k,
        )
        out = out.transpose(0, 2, 1, 3).reshape(b, s, cfg.n_heads * hd)
        return dense(cfg.dim, "wo")(out)


class MLP(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        dense = lambda feats, name: nn.Dense(
            feats, use_bias=False, dtype=cfg.dtype, param_dtype=jnp.float32,
            name=name,
        )
        gate = dense(cfg.ffn_dim, "w_gate")(x)
        up = dense(cfg.ffn_dim, "w_up")(x)
        return dense(cfg.dim, "w_down")(nn.silu(gate) * up)


class Block(nn.Module):
    config: LlamaConfig
    mesh: Optional[Any] = None

    @nn.compact
    def __call__(self, x, positions):
        """Returns (x, aux): aux is the router load-balance loss for MoE
        configs, a constant 0 for dense ones (uniform pytree shape keeps
        remat and scan-style wrappers oblivious)."""
        cfg = self.config
        x = x + Attention(cfg, self.mesh, name="attn")(
            RMSNorm(cfg.norm_eps, name="attn_norm")(x), positions
        )
        h = RMSNorm(cfg.norm_eps, name="mlp_norm")(x)
        if cfg.is_moe:
            from .moe import MoEMLP

            y, aux = MoEMLP(
                dim=cfg.dim, ffn_dim=cfg.ffn_dim, n_experts=cfg.n_experts,
                top_k=cfg.moe_top_k, capacity_factor=cfg.capacity_factor,
                dtype=cfg.dtype, mesh=self.mesh, name="moe",
            )(h)
        else:
            y, aux = MLP(cfg, name="mlp")(h), jnp.float32(0.0)
        return x + y, aux


class Llama(nn.Module):
    config: LlamaConfig
    mesh: Optional[Any] = None

    @nn.compact
    def __call__(self, tokens, return_hidden: bool = False):
        """``return_hidden=True`` skips the LM head and returns
        ``(hidden, aux)`` — the chunked-loss path applies the head
        incrementally (ops/losses.py) so full logits never materialize.
        """
        cfg = self.config
        positions = jnp.broadcast_to(
            jnp.arange(tokens.shape[1]), tokens.shape
        )
        emb = nn.Embed(
            cfg.vocab_size, cfg.dim, dtype=cfg.dtype, param_dtype=jnp.float32,
            name="embed",
        )
        h = emb(tokens)
        # Zigzag ring layout: permute the sequence once after the
        # embedding (device i ends up holding chunks i and 2n-1-i of the
        # sp ring) and hand RoPE the TRUE positions of the permuted rows;
        # every non-attention op is pointwise over sequence, so only the
        # two permutes at the model's edges and the balanced ring differ
        # from the contiguous layout.
        unperm = None
        if _use_zigzag(cfg, self.mesh):
            from ..ops.ring_attention import zigzag_indices, zigzag_inverse

            n = dict(zip(self.mesh.axis_names, self.mesh.devices.shape))[SP]
            seq = tokens.shape[1]
            perm = jnp.asarray(zigzag_indices(seq, n))
            unperm = jnp.asarray(zigzag_inverse(seq, n))
            h = h[:, perm]
            positions = jnp.broadcast_to(perm, tokens.shape)
        block = Block
        if cfg.remat:
            block = nn.remat(
                Block, static_argnums=(), policy=remat_policy_for(cfg)
            )
        aux_total = jnp.float32(0.0)
        for i in range(cfg.n_layers):
            h, aux = block(cfg, self.mesh, name=f"layer_{i}")(h, positions)
            aux_total = aux_total + aux
        h = RMSNorm(cfg.norm_eps, name="final_norm")(h)
        if unperm is not None:
            h = h[:, unperm]  # back to natural order for the LM head/loss
        if return_hidden:
            return h, aux_total
        # Untied lm_head (Llama-3 does not tie embeddings); f32 logits for
        # a stable softmax-CE. Operands stay in the compute dtype (bf16
        # in production) with f32 ACCUMULATION — an f32xf32 matmul runs
        # as multiple MXU passes on TPU, several x slower, for precision
        # the f32 accumulator already provides.
        if cfg.tie_embeddings:
            # Explicit dot (ops/losses.py:f32_logits): Embed.attend would
            # cast the f32 accumulation back to the module dtype and drop
            # the f32 logits guarantee.
            from ..ops.losses import f32_logits

            logits = f32_logits(h, emb.embedding.T)
        else:
            logits = F32LogitsDense(cfg.vocab_size, name="lm_head")(h)
        # MoE configs also hand back the summed router aux loss; dense
        # callers keep the plain-logits contract.
        return (logits, aux_total) if cfg.is_moe else logits


def init_params(model: Llama, rng, batch: int = 2, seq: int = 16):
    tokens = jnp.zeros((batch, seq), jnp.int32)
    return model.init(rng, tokens)["params"]


def loss_fn(model: Llama, params, tokens, include_aux: bool = True):
    """Next-token cross-entropy (+ router aux loss for MoE configs). The
    full sequence goes through the model (keeping the length divisible by
    the sp axis for ring attention); the shift happens on the logits.

    ``include_aux=False`` returns the pure CE — evaluation/perplexity
    (cmd.eval) must not fold the load-balance regularizer into the
    reported number. Training keeps the default.

    With ``cfg.xent_chunk > 0`` the head + CE run chunked
    (ops/losses.py:lm_xent_chunked): same masked mean, but the [B, S, V]
    f32 logits never materialize."""
    cfg = model.config
    aux_coef = cfg.router_aux_coef if include_aux else 0.0
    if cfg.xent_chunk > 0:
        from ..ops.losses import lm_xent_chunked

        h, aux = model.apply({"params": params}, tokens, return_hidden=True)
        if cfg.tie_embeddings:
            w = params["embed"]["embedding"].T
        else:
            w = params["lm_head"]["kernel"]
        ce = lm_xent_chunked(
            h[:, :-1], w, tokens[:, 1:], chunk=cfg.xent_chunk
        )
        return ce + aux_coef * (aux if cfg.is_moe else 0.0)
    out = model.apply({"params": params}, tokens)
    if cfg.is_moe:
        logits, aux = out
    else:
        logits, aux = out, 0.0
    ce = optax.softmax_cross_entropy_with_integer_labels(
        logits[:, :-1], tokens[:, 1:]
    )
    return jnp.mean(ce) + aux_coef * aux


def make_train_step(model: Llama, optimizer, accum_steps: int = 1):
    """``accum_steps > 1``: average gradients over that many sequential
    microbatches (split on the batch dim) before the single optimizer
    update — see ``parallel.accum``."""
    from ..parallel.accum import make_update_step

    return make_update_step(
        lambda p, toks: loss_fn(model, p, toks), optimizer, accum_steps
    )


def param_sharding_rules(mesh):
    """(predicate, PartitionSpec) rules for ``parallel.shard_params``.

    Megatron-style tensor parallelism: column-parallel qkv/gate/up
    (output features over tp), row-parallel wo/down (input features over
    tp), embeddings split vocab over tp; the other matrix dim takes fsdp.
    Falls back gracefully when the mesh lacks a tp axis (pure FSDP).
    """
    from ..parallel.sharding import active_mesh_axis, ends_with, mesh_axis

    from . import moe as moe_lib

    tp = mesh_axis(mesh, TP)
    fsdp = mesh_axis(mesh, FSDP)
    return moe_lib.param_sharding_rules(mesh) + [
        (ends_with("wq/kernel", "wk/kernel", "wv/kernel",
                   "w_gate/kernel", "w_up/kernel"), P(fsdp, tp)),
        (ends_with("wo/kernel", "w_down/kernel"), P(tp, fsdp)),
        # The token table feeds a gather/scatter, not a matmul: without
        # a REAL (size>1) tp axis, a feature-dim fsdp shard makes GSPMD
        # fully rematerialize layer-0 dx to reach the scatter's layout
        # (a per-step [B,S,D] all-gather + spmd_partitioner warning);
        # splitting the vocab dim over fsdp partitions the scatter by
        # row instead, no reshard.
        (ends_with("embed/embedding"),
         P(tp, fsdp) if active_mesh_axis(mesh, TP) else P(fsdp, None)),
        (ends_with("lm_head/kernel"), P(fsdp, tp)),
        (ends_with("scale",), P()),
    ]
