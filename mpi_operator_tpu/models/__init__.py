"""JAX/Flax example workloads — the TPU-native replacements for the
reference's Horovod/tf_cnn_benchmarks example images (reference analog:
/root/reference/examples/v2beta1/tensorflow-benchmarks/,
horovod examples, pi.cc)."""
