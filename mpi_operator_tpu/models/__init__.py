"""JAX/Flax model library — the TPU-native replacement for the
reference's user-container workloads (reference analog:
/root/reference/examples/v2beta1/tensorflow-benchmarks/, horovod
examples, pi.cc).

- ``resnet``: ResNet v1.5 (the headline benchmark family, BASELINE.md).
- ``bert``: BERT-base encoder, MLM pretraining (milestone config 3).
- ``llama``: Llama-family decoder with FSDP/TP/SP shardings and
  flash/ring/ulysses attention (milestone config 4).
- ``llama_pp``: the same blocks as a GPipe pipeline over pp (x ZeRO-3
  fsdp weight sharding).
- ``moe``: Mixtral-style sparse MoE layer, experts over ep.
- ``generate``: KV-cache autoregressive decoding for llama (static
  shapes, one scanned program for prefill + generation).
"""

# No eager submodule imports: consumers import the single model family
# they need (bench.py / __graft_entry__ pull resnet only, inside
# functions) without paying for flax/optax/pallas of the others.
__all__ = ["bert", "generate", "llama", "llama_pp", "moe", "resnet"]
