"""Autoregressive decoding for Llama with a static KV cache.

The reference operator has no model code at all (user images own the
math — SURVEY.md §2.4); the framework ships training AND inference for
its model families. Decoding is built the TPU way:

- **static shapes**: the KV cache is preallocated [B, H_kv, S_max, D]
  and written in place with ``lax.dynamic_update_slice``; attention
  always scores against the full cache with a position mask. One
  compiled program serves each (prompt length, max_new) shape pair —
  bucket/pad prompts on the host to bound the number of compilations;
- **lax.scan over steps**: prompt prefill and new-token generation are
  the same scanned single-token step (teacher-forced for the prompt,
  argmax/sample after), no Python loop, no retracing;
- **GQA-aware**: cache stores the n_kv_heads, query heads map onto
  them group-wise, kv never expands in HBM.

The decode math re-implements the block forward functionally (the
training path runs whole sequences through flax modules; decode runs
one position against the cache). Equivalence is pinned by test:
teacher-forced decode logits must match the training forward exactly.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from ..ops.attention import NEG_INF
from ..ops.losses import f32_logits
from .llama import LlamaConfig, _rope


def _rms(x, scale, eps):
    xf = x.astype(jnp.float32)
    norm = xf * jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return (norm * scale).astype(x.dtype)


def _attn_step(p, cache_k, cache_v, x, pos, cfg: LlamaConfig):
    """One position through one attention block. x: [B, D]; cache_k/v:
    [B, H_kv, S_max, Dh]; pos: scalar index. Returns (out, k', v')."""
    b, _ = x.shape
    hd = cfg.head_dim
    q = (x @ p["wq"]["kernel"].astype(cfg.dtype)).reshape(
        b, cfg.n_heads, hd
    )
    k = (x @ p["wk"]["kernel"].astype(cfg.dtype)).reshape(
        b, cfg.n_kv_heads, hd
    )
    v = (x @ p["wv"]["kernel"].astype(cfg.dtype)).reshape(
        b, cfg.n_kv_heads, hd
    )
    positions = jnp.full((b, 1), pos)
    q = _rope(q[:, None], positions, cfg.rope_theta)[:, 0]
    k = _rope(k[:, None], positions, cfg.rope_theta)[:, 0]

    cache_k = jax.lax.dynamic_update_slice(
        cache_k, k[:, :, None].astype(cache_k.dtype), (0, 0, pos, 0)
    )
    cache_v = jax.lax.dynamic_update_slice(
        cache_v, v[:, :, None].astype(cache_v.dtype), (0, 0, pos, 0)
    )

    groups = cfg.n_heads // cfg.n_kv_heads
    qg = q.reshape(b, cfg.n_kv_heads, groups, hd)
    scores = jnp.einsum(
        "bkgd,bksd->bkgs", qg.astype(jnp.float32),
        cache_k.astype(jnp.float32),
    ) * (hd ** -0.5)
    s_max = cache_k.shape[2]
    mask = jnp.arange(s_max)[None, None, None, :] <= pos
    scores = jnp.where(mask, scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum(
        "bkgs,bksd->bkgd", probs, cache_v.astype(jnp.float32)
    ).reshape(b, cfg.n_heads * hd).astype(cfg.dtype)
    return out @ p["wo"]["kernel"].astype(cfg.dtype), cache_k, cache_v


def _mlp_step(p, x, cfg: LlamaConfig):
    gate = x @ p["w_gate"]["kernel"].astype(cfg.dtype)
    up = x @ p["w_up"]["kernel"].astype(cfg.dtype)
    return (jax.nn.silu(gate) * up) @ p["w_down"]["kernel"].astype(cfg.dtype)


def _moe_step(p, x, cfg: LlamaConfig):
    """One position through a sparse-MoE FFN. At decode each token
    routes alone, so there is no capacity competition and no drops: the
    exact training semantics reduce to a dense all-experts einsum
    weighted by the normalized top-k gates (static shapes; computes all
    E experts — the TPU-friendly trade for a batch-1-per-token path)."""
    from .moe import topk_gates

    probs = jax.nn.softmax(
        x.astype(jnp.float32) @ p["router"], axis=-1
    )  # [B, E]
    _, _, w = topk_gates(probs, cfg.moe_top_k)  # [B, E] dense weights
    hg = jnp.einsum("bd,edf->bef", x, p["expert_wg"].astype(cfg.dtype))
    hu = jnp.einsum("bd,edf->bef", x, p["expert_wu"].astype(cfg.dtype))
    h = jax.nn.silu(hg) * hu
    out_e = jnp.einsum(
        "bef,efd->bed", h, p["expert_wd"].astype(cfg.dtype)
    )
    return jnp.einsum(
        "be,bed->bd", w, out_e.astype(jnp.float32)
    ).astype(x.dtype)


def _decode_step(params, cfg: LlamaConfig, caches, token, pos):
    """One token through the whole model. token: [B] int; caches: list of
    (k, v) per layer. Returns (logits [B, V] f32, new caches)."""
    x = params["embed"]["embedding"][token].astype(cfg.dtype)  # [B, D]
    new_caches = []
    for i in range(cfg.n_layers):
        p = params[f"layer_{i}"]
        h = _rms(x, p["attn_norm"]["scale"], cfg.norm_eps)
        a, ck, cv = _attn_step(
            p["attn"], caches[i][0], caches[i][1], h, pos, cfg
        )
        x = x + a
        h = _rms(x, p["mlp_norm"]["scale"], cfg.norm_eps)
        if cfg.is_moe:
            x = x + _moe_step(p["moe"], h, cfg)
        else:
            x = x + _mlp_step(p["mlp"], h, cfg)
        new_caches.append((ck, cv))
    x = _rms(x, params["final_norm"]["scale"], cfg.norm_eps)
    if cfg.tie_embeddings:
        w = params["embed"]["embedding"].T
    else:
        w = params["lm_head"]["kernel"]
    # Head operands in the model's compute dtype with f32 accumulation
    # (ops/losses.py:f32_logits): halves the [D, V] weight read in bf16
    # configs; tiny test configs (dtype=f32) are numerically unchanged.
    logits = f32_logits(x.astype(cfg.dtype), w)
    return logits, new_caches


def init_cache(cfg: LlamaConfig, batch: int, max_len: int, dtype=None):
    dtype = dtype or cfg.dtype
    return [
        (
            jnp.zeros((batch, cfg.n_kv_heads, max_len, cfg.head_dim), dtype),
            jnp.zeros((batch, cfg.n_kv_heads, max_len, cfg.head_dim), dtype),
        )
        for _ in range(cfg.n_layers)
    ]


@partial(jax.jit, static_argnames=("cfg", "max_new", "sample"))
def _generate_impl(params, prompt, cfg, max_new, sample, temperature, rng):
    b, s0 = prompt.shape
    total = s0 + max_new
    caches = init_cache(cfg, b, total)

    def step(carry, t):
        caches, token, rng = carry
        logits, caches = _decode_step(params, cfg, caches, token, t)
        if sample:
            rng, sub = jax.random.split(rng)
            chosen = jax.random.categorical(sub, logits / temperature)
        else:
            chosen = jnp.argmax(logits, axis=-1)
        # Teacher-force while still inside the prompt.
        in_prompt = t + 1 < s0
        next_token = jnp.where(
            in_prompt,
            prompt[:, jnp.minimum(t + 1, s0 - 1)],
            chosen.astype(prompt.dtype),
        )
        return (caches, next_token, rng), next_token

    init = (caches, prompt[:, 0], rng)
    _, emitted = jax.lax.scan(step, init, jnp.arange(total - 1))
    # emitted[t] is the token at position t+1.
    return jnp.concatenate([prompt[:, :1], emitted.T], axis=1)


def generate(
    params,
    prompt,
    cfg: LlamaConfig,
    *,
    max_new: int,
    temperature: float = 0.0,
    rng=None,
):
    """Decode ``max_new`` tokens after ``prompt`` [B, S0]. One compiled
    scan covers prefill + generation: for the first S0-1 steps the next
    input is the teacher-forced prompt token, afterwards the model's own
    prediction. temperature 0 = greedy; > 0 = softmax sampling (needs
    ``rng``; the temperature itself is a traced operand, so sweeping it
    does not recompile). Returns [B, S0 + max_new] tokens. MoE configs
    decode via the dense all-experts path (``_moe_step``)."""
    if temperature > 0 and rng is None:
        raise ValueError("sampling (temperature > 0) needs an rng key")
    sample = rng is not None and temperature > 0
    return _generate_impl(
        params, prompt, cfg, max_new, sample,
        jnp.float32(temperature if sample else 1.0),
        rng if rng is not None else jax.random.PRNGKey(0),
    )
