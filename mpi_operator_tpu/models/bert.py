"""BERT-family encoder in Flax — the multi-host milestone workload.

BASELINE.md milestone config 3 ("Flax BERT-base on v5e-16 multi-host").
Masked-language-model pretraining objective; bidirectional attention
through the pallas flash kernel (no causal mask); bfloat16 compute with
float32 params. Sharding: dp/fsdp over batch and params via the generic
``parallel.shard_params`` heuristic, plus tp rules for the dense kernels.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax.numpy as jnp
import optax
from jax.sharding import PartitionSpec as P

from ..parallel.mesh import FSDP, TP


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    dim: int = 768
    n_layers: int = 12
    n_heads: int = 12
    ffn_dim: int = 3072
    max_seq_len: int = 512
    type_vocab_size: int = 2
    norm_eps: float = 1e-12
    dtype: Any = jnp.bfloat16
    # 'flash' (pallas), 'dense' (XLA reference), or the sequence-parallel
    # strategies over an sp mesh axis for long sequences: 'ring'
    # (non-causal ppermute ring) / 'ulysses' (two all-to-alls). The
    # sp strategies need a mesh on the module.
    attention_impl: str = "flash"
    # Flash kernel tile sizes (bench.py --flash-block-q/-k analog for
    # the BERT suite) — pure scheduling knobs, outputs are invariant.
    # 128 is safe for any seq; 256 measured best at bench scale on v5e
    # (TUNE_CAPTURE r5: 54.0% vs 38.8% MFU) — bench.py defaults to 256.
    flash_block_q: int = 128
    flash_block_k: int = 128
    # Per-layer jax.checkpoint: BERT-base activations fit HBM at the
    # stock batch so this defaults off; large-batch MFU sweeps
    # (bench --bert-batch 256) turn it on to fit.
    remat: bool = False
    remat_policy: str = "dots"  # 'full' | 'dots' (llama.remat_policy_for)


def bert_base(**overrides) -> BertConfig:
    return dataclasses.replace(BertConfig(), **overrides)


def tiny(**overrides) -> BertConfig:
    base = BertConfig(
        vocab_size=128, dim=32, n_layers=2, n_heads=2, ffn_dim=64,
        max_seq_len=64, dtype=jnp.float32, attention_impl="dense",
    )
    return dataclasses.replace(base, **overrides)


class EncoderLayer(nn.Module):
    config: BertConfig
    mesh: Any = None  # required for attention_impl='ring'/'ulysses'

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        b, s, _ = x.shape
        hd = cfg.dim // cfg.n_heads
        dense = lambda feats, name: nn.Dense(
            feats, dtype=cfg.dtype, param_dtype=jnp.float32, name=name
        )

        q = dense(cfg.dim, "wq")(x).reshape(b, s, cfg.n_heads, hd)
        k = dense(cfg.dim, "wk")(x).reshape(b, s, cfg.n_heads, hd)
        v = dense(cfg.dim, "wv")(x).reshape(b, s, cfg.n_heads, hd)
        # Transpose-free dispatch first (flash + ring/ulysses twins on
        # the raw projection layout; ops/ring_attention.py); impls that
        # need the [B, H, S, D] convention (flash-bhsd A/B, dense
        # oracle) fall through to the transposed path.
        from ..ops.ring_attention import sp_attention, sp_attention_bshd

        att = sp_attention_bshd(
            q, k, v, self.mesh, cfg.attention_impl, causal=False,
            block_q=cfg.flash_block_q, block_k=cfg.flash_block_k,
        )
        if att is not None:
            att = att.reshape(b, s, cfg.dim)
        else:
            q, k, v = (t.transpose(0, 2, 1, 3) for t in (q, k, v))
            att = sp_attention(
                q, k, v, self.mesh, cfg.attention_impl, causal=False,
                block_q=cfg.flash_block_q, block_k=cfg.flash_block_k,
            )
            att = att.transpose(0, 2, 1, 3).reshape(b, s, cfg.dim)
        x = nn.LayerNorm(epsilon=cfg.norm_eps, dtype=cfg.dtype, name="attn_norm")(
            x + dense(cfg.dim, "wo")(att)
        )
        h = nn.gelu(dense(cfg.ffn_dim, "ffn_in")(x))
        x = nn.LayerNorm(epsilon=cfg.norm_eps, dtype=cfg.dtype, name="ffn_norm")(
            x + dense(cfg.dim, "ffn_out")(h)
        )
        return x


class Bert(nn.Module):
    config: BertConfig
    mesh: Any = None  # required for attention_impl='ring'/'ulysses'

    @nn.compact
    def __call__(self, tokens, token_types=None, mlm_positions=None):
        """``mlm_positions`` ([B, P] int, optional): gather the encoder
        output at just those positions before the MLM head, so the
        transform + vocab decode run on P ≈ 0.15·S masked slots instead
        of all S — the classic BERT-pretraining head optimization (the
        head's vocab matmul is ~6.7x smaller at the standard 15% mask
        rate). Returns [B, P, V] logits instead of [B, S, V]."""
        cfg = self.config
        b, s = tokens.shape
        embed = nn.Embed(
            cfg.vocab_size, cfg.dim, dtype=cfg.dtype, param_dtype=jnp.float32,
            name="tok_embed",
        )
        h = embed(tokens)
        h = h + nn.Embed(
            cfg.max_seq_len, cfg.dim, dtype=cfg.dtype, param_dtype=jnp.float32,
            name="pos_embed",
        )(jnp.broadcast_to(jnp.arange(s), (b, s)))
        if token_types is not None:
            h = h + nn.Embed(
                cfg.type_vocab_size, cfg.dim, dtype=cfg.dtype,
                param_dtype=jnp.float32, name="type_embed",
            )(token_types)
        h = nn.LayerNorm(epsilon=cfg.norm_eps, dtype=cfg.dtype, name="embed_norm")(h)
        layer = EncoderLayer
        if cfg.remat:
            from .llama import remat_policy_for

            layer = nn.remat(
                EncoderLayer, static_argnums=(), policy=remat_policy_for(cfg)
            )
        for i in range(cfg.n_layers):
            h = layer(cfg, self.mesh, name=f"layer_{i}")(h)
        if mlm_positions is not None:
            h = jnp.take_along_axis(
                h, mlm_positions[..., None].astype(jnp.int32), axis=1
            )
        # MLM head: transform + tied decoder, f32 logits.
        h = nn.Dense(
            cfg.dim, dtype=cfg.dtype, param_dtype=jnp.float32, name="mlm_dense"
        )(h)
        h = nn.gelu(h)
        h = nn.LayerNorm(epsilon=cfg.norm_eps, dtype=cfg.dtype, name="mlm_norm")(h)
        # Tied decoder with f32 accumulation but compute-dtype operands
        # (ops/losses.py:f32_logits rationale); Embed.attend would round
        # the accumulation back to bf16.
        from ..ops.losses import f32_logits

        return f32_logits(h, embed.embedding.T)


def init_params(model: Bert, rng, batch: int = 2, seq: int = 16):
    tokens = jnp.zeros((batch, seq), jnp.int32)
    return model.init(rng, tokens)["params"]


def mlm_loss(model: Bert, params, tokens, mlm_positions_mask, mlm_targets):
    """Masked-LM cross-entropy; ``mlm_positions_mask`` is 1.0 where the
    token was masked out (loss counted), 0.0 elsewhere. Computes the
    full [B, S, V] logits — use :func:`mlm_loss_positions` for the
    gathered-head variant (same value for matching masks)."""
    logits = model.apply({"params": params}, tokens)
    ce = optax.softmax_cross_entropy_with_integer_labels(logits, mlm_targets)
    weight = mlm_positions_mask.astype(jnp.float32)
    return jnp.sum(ce * weight) / jnp.maximum(jnp.sum(weight), 1.0)


def mlm_loss_positions(model: Bert, params, tokens, mlm_positions,
                       mlm_targets, mlm_weights):
    """Masked-LM cross-entropy over gathered positions (the TF-BERT
    ``max_predictions_per_seq`` interface): ``mlm_positions`` [B, P]
    indexes the masked slots, ``mlm_targets`` [B, P] their original
    tokens, ``mlm_weights`` [B, P] 1.0 for real predictions / 0.0 for
    padding slots. The MLM head runs on P positions, not S."""
    logits = model.apply(
        {"params": params}, tokens, mlm_positions=mlm_positions
    )
    ce = optax.softmax_cross_entropy_with_integer_labels(logits, mlm_targets)
    w = mlm_weights.astype(jnp.float32)
    return jnp.sum(ce * w) / jnp.maximum(jnp.sum(w), 1.0)


def make_train_step(model: Bert, optimizer, accum_steps: int = 1):
    """``accum_steps > 1``: average gradients over that many sequential
    microbatches (split on the batch dim) before the single optimizer
    update — see ``parallel.accum``. (MLM's per-microbatch masked-token
    weighting makes this the mean of weighted means, the standard
    approximation when mask counts vary across microbatches.)"""
    from ..parallel.accum import make_update_step

    return make_update_step(
        lambda p, t, m, tg: mlm_loss(model, p, t, m, tg),
        optimizer, accum_steps,
    )


def make_train_step_positions(model: Bert, optimizer, accum_steps: int = 1):
    """Train step over the gathered-positions MLM batch layout
    ``(tokens, mlm_positions, mlm_targets, mlm_weights)`` — the head
    computes P-position logits only (see :func:`mlm_loss_positions`)."""
    from ..parallel.accum import make_update_step

    return make_update_step(
        lambda p, t, pos, tg, w: mlm_loss_positions(model, p, t, pos, tg, w),
        optimizer, accum_steps,
    )


def param_sharding_rules(mesh):
    """tp/fsdp rules for ``parallel.shard_params`` (see llama.py)."""
    from ..parallel.sharding import active_mesh_axis, ends_with, mesh_axis

    tp = mesh_axis(mesh, TP)
    fsdp = mesh_axis(mesh, FSDP)
    return [
        (ends_with("wq/kernel", "wk/kernel", "wv/kernel", "ffn_in/kernel"),
         P(fsdp, tp)),
        (ends_with("wo/kernel", "ffn_out/kernel"), P(tp, fsdp)),
        # Only the vocab-sized table is safe to split over tp; pos/type
        # tables (512- and 2-row) stay on the fsdp heuristic. Without a
        # real (size>1) tp, fsdp goes on the vocab dim: a feature-dim
        # shard forces a full remat of layer-0 dx in the backward
        # scatter (llama.py).
        (ends_with("tok_embed/embedding"),
         P(tp, fsdp) if active_mesh_axis(mesh, TP) else P(fsdp, None)),
    ]
