"""Fault-injecting wrappers around the in-memory API server.

``ChaoticAPIServer`` duck-types ``runtime.apiserver.InMemoryAPIServer``:
verbs consult the engine before delegating, so an injected fault means
the write *never happened* (the strictest interpretation a client must
survive).  ``watch()`` returns a ``ChaoticWatch`` that drops, delays, and
compacts (410 Gone) the event stream per policy.

Everything not explicitly wrapped passes through via ``__getattr__`` —
the wrapper stays honest as the inner server grows surface area.
"""

from __future__ import annotations

from typing import Optional

from ..runtime.apiserver import GoneError, InMemoryAPIServer, WatchEvent
from .engine import WATCH_DELAY, WATCH_DROP, WATCH_GONE, ChaosEngine


def _event_key(event: WatchEvent) -> str:
    meta = event.object.get("metadata") or {}
    ns = meta.get("namespace", "")
    name = meta.get("name", "")
    return f"{ns}/{name}" if ns else name


class ChaoticWatch:
    """Wraps a runtime Watch; the server keeps delivering to the inner
    watch, and faults are applied at drain time (the informer pump's
    single consumption point)."""

    def __init__(self, inner, engine: ChaosEngine, raw: InMemoryAPIServer):
        self._inner = inner
        self._engine = engine
        self._raw = raw
        # Delayed events: (rounds_until_release, event), FIFO per round.
        self._delayed: list[list] = []

    @property
    def resource(self) -> str:
        return self._inner.resource

    @property
    def namespace(self) -> Optional[str]:
        return self._inner.namespace

    def baseline(self) -> list[dict]:
        """Relist against the *raw* server: a compaction recovery that
        itself flaked forever would make convergence unfalsifiable."""
        return self._raw.list(self.resource, self.namespace)

    def drain(self) -> list[WatchEvent]:
        released: list[WatchEvent] = []
        for entry in self._delayed:
            entry[0] -= 1
        while self._delayed and self._delayed[0][0] <= 0:
            released.append(self._delayed.pop(0)[1])
        out: list[WatchEvent] = list(released)
        incoming = self._inner.drain()
        for event in incoming:
            fate = self._engine.watch_fault(self.resource, _event_key(event))
            if fate == WATCH_GONE:
                # Compaction storm: everything buffered (delivered or
                # delayed) is behind the compaction point and is lost;
                # the informer must relist.
                self._delayed.clear()
                raise GoneError(
                    "watch", self.resource, "chaos: stream compacted"
                )
            if fate == WATCH_DROP:
                continue
            if fate == WATCH_DELAY:
                delay = self._engine.policy.watch.delay_rounds
                self._delayed.append([delay, event])
                continue
            out.append(event)
        return out

    def next(self, timeout: Optional[float] = None) -> Optional[WatchEvent]:
        # The blocking path is used by consumers outside the informer
        # pump (e.g. test helpers); faults apply on the drain path only.
        return self._inner.next(timeout)

    def stop(self) -> None:
        self._delayed.clear()
        self._inner.stop()


class ChaoticAPIServer:
    """InMemoryAPIServer facade that injects verb faults per policy."""

    def __init__(self, inner: InMemoryAPIServer, engine: ChaosEngine):
        self._inner = inner
        self._engine = engine

    @property
    def inner(self) -> InMemoryAPIServer:
        return self._inner

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def _maybe_fault(self, verb: str, resource: str, name: str) -> None:
        error = self._engine.fault_for(verb, resource, name)
        if error is not None:
            raise error

    # -- verbs -----------------------------------------------------------

    def get(self, resource: str, namespace: str, name: str) -> dict:
        self._maybe_fault("get", resource, name)
        return self._inner.get(resource, namespace, name)

    def list(
        self,
        resource: str,
        namespace: Optional[str] = None,
        label_selector: Optional[dict] = None,
    ) -> list[dict]:
        self._maybe_fault("list", resource, "*")
        return self._inner.list(resource, namespace, label_selector)

    def create(self, resource: str, obj: dict) -> dict:
        name = (obj.get("metadata") or {}).get("name", "")
        self._maybe_fault("create", resource, name)
        return self._inner.create(resource, obj)

    def update(self, resource: str, obj: dict) -> dict:
        name = (obj.get("metadata") or {}).get("name", "")
        self._maybe_fault("update", resource, name)
        return self._inner.update(resource, obj)

    def update_status(self, resource: str, obj: dict) -> dict:
        name = (obj.get("metadata") or {}).get("name", "")
        self._maybe_fault("update_status", resource, name)
        return self._inner.update_status(resource, obj)

    def delete(self, resource: str, namespace: str, name: str) -> None:
        self._maybe_fault("delete", resource, name)
        return self._inner.delete(resource, namespace, name)

    def watch(self, resource: str, namespace: Optional[str] = None):
        inner = self._inner.watch(resource, namespace)
        watch_policy = self._engine.policy.watch
        if watch_policy is not None and watch_policy.applies(resource):
            return ChaoticWatch(inner, self._engine, self._inner)
        return inner
