"""Pod- and node-level chaos against the LocalPodRunner.

``PodKiller.tick()`` is one chaos round: every running pod matching an
active ``PodChaos`` policy gets one seeded draw deciding whether it is
SIGKILLed (preemption signature, exit code 137) or loses its node
(phase=Failed with ``status.reason=NodeLost``, no exit code).  The caller
paces ticks — a thread in a live soak, explicit calls in a deterministic
replay.
"""

from __future__ import annotations

import threading

from ..api.v2beta1.constants import JOB_NAME_LABEL, JOB_ROLE_LABEL
from ..runtime.apiserver import InMemoryAPIServer
from .engine import (
    MEM_LEAK,
    NODE_DEATH,
    POD_KILL,
    SLOW_WORKER,
    TORN_WRITE,
    ChaosEngine,
)

__all__ = ["LeakInjector", "PodKiller", "TornWriteInjector", "WorkerSlower"]


def _record_fault(
    recorder, pod_meta: dict, kind: str, detail: str
) -> None:
    """Land a chaos fault on the victim job's flight-recorder timeline
    (kinds ``slow_worker``/``mem_leak``), so a postmortem shows the
    injection alongside the conditions it provoked.  No-op without a
    recorder or when the pod carries no job label."""
    if recorder is None:
        return
    labels = pod_meta.get("labels") or {}
    job = labels.get(JOB_NAME_LABEL)
    if not job:
        return
    recorder.record(
        pod_meta.get("namespace", ""),
        job,
        kind,
        reason="ChaosInjected",
        message=f"pod {pod_meta.get('name', '')}: {detail}",
        pod=pod_meta.get("name", ""),
    )


class PodKiller:
    def __init__(self, engine: ChaosEngine, api: InMemoryAPIServer, runner):
        # List against the raw server: the killer is the chaos, it should
        # not itself be a victim of injected read faults.
        self._engine = engine
        self._api = getattr(api, "inner", api)
        self._runner = runner
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def tick(self) -> int:
        """One chaos round; returns the number of kills that landed."""
        kills = 0
        for index, policy in enumerate(self._engine.policy.pods):
            if policy.kill_rate <= 0.0 and policy.node_death_rate <= 0.0:
                continue
            pods = self._api.list("pods", policy.namespace or None)
            for pod in pods:
                if (pod.get("status") or {}).get("phase") != "Running":
                    continue
                meta = pod.get("metadata") or {}
                labels = meta.get("labels") or {}
                role = labels.get(JOB_ROLE_LABEL, "")
                if policy.roles and role not in policy.roles:
                    continue
                mode = self._engine.pod_fault(index, policy)
                if mode is None:
                    continue
                namespace = meta.get("namespace", "")
                name = meta.get("name", "")
                if mode == POD_KILL:
                    landed = self._runner.kill_pod(namespace, name)
                elif mode == NODE_DEATH:
                    landed = self._runner.fail_node(namespace, name)
                else:  # pragma: no cover - engine vocabulary is closed
                    landed = False
                if landed:
                    self._engine.confirm_kill(
                        index, mode, f"{namespace}/{name}"
                    )
                    kills += 1
        return kills

    # -- background pacing (live soaks) ---------------------------------

    def start(self, interval: float = 0.05) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, args=(interval,), daemon=True,
            name="chaos-podkiller",
        )
        self._thread.start()

    def _loop(self, interval: float) -> None:
        while not self._stop.is_set():
            self.tick()
            self._stop.wait(interval)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


class WorkerSlower:
    """SlowWorker chaos: each tick gives every matching running worker
    one seeded draw deciding whether it becomes a degraded host
    (``runner.slow_worker``, which stretches the victim's step clock by
    the policy's factor at its next (re)start).  Already-slowed victims
    are skipped — a straggler stays one straggler, not a compounding
    slowdown.  Same pacing contract as PodKiller: a thread in live
    soaks, explicit ``tick()`` calls in deterministic replays.

    With a flight recorder wired, every landed slowdown also lands on
    the victim job's timeline as a ``slow_worker`` entry.
    """

    def __init__(
        self,
        engine: ChaosEngine,
        api: InMemoryAPIServer,
        runner,
        flight_recorder=None,
    ):
        self._engine = engine
        self._api = getattr(api, "inner", api)
        self._runner = runner
        self._recorder = flight_recorder
        self._slowed: set[tuple[str, str]] = set()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def tick(self) -> int:
        """One chaos round; returns the number of slowdowns that landed."""
        slowed = 0
        for index, policy in enumerate(self._engine.policy.slow):
            if policy.slow_rate <= 0.0:
                continue
            pods = self._api.list("pods", policy.namespace or None)
            for pod in pods:
                if (pod.get("status") or {}).get("phase") != "Running":
                    continue
                meta = pod.get("metadata") or {}
                labels = meta.get("labels") or {}
                role = labels.get(JOB_ROLE_LABEL, "")
                if policy.roles and role not in policy.roles:
                    continue
                key = (meta.get("namespace", ""), meta.get("name", ""))
                if key in self._slowed:
                    continue
                if not self._engine.slow_fault(index, policy):
                    continue
                if self._runner.slow_worker(key[0], key[1], policy.factor):
                    self._slowed.add(key)
                    self._engine.confirm_slow(
                        index, f"{key[0]}/{key[1]}", policy.factor
                    )
                    _record_fault(
                        self._recorder, meta, SLOW_WORKER,
                        f"slowed by factor={policy.factor}",
                    )
                    slowed += 1
        return slowed

    # -- background pacing (live soaks) ---------------------------------

    def start(self, interval: float = 0.05) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, args=(interval,), daemon=True,
            name="chaos-workerslower",
        )
        self._thread.start()

    def _loop(self, interval: float) -> None:
        while not self._stop.is_set():
            self.tick()
            self._stop.wait(interval)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


class TornWriteInjector:
    """TornWrite chaos: each tick gives every matching running worker one
    seeded draw deciding whether it dies mid-checkpoint-commit.  A landed
    fault arms a one-shot torn commit (``runner.tear_write``: at the
    victim's next (re)start its writer persists the step data but
    withholds the commit marker) and then SIGKILLs the current process
    (``runner.kill_pod``, exit 137 — the preemption signature), so the
    replacement worker both produces the torn write and later has to
    restore around one.  Same pacing contract as PodKiller: a thread in
    live soaks, explicit ``tick()`` calls in deterministic replays.

    With a flight recorder wired, every landed tear also lands on the
    victim job's timeline as a ``torn_write`` entry.
    """

    def __init__(
        self,
        engine: ChaosEngine,
        api: InMemoryAPIServer,
        runner,
        flight_recorder=None,
    ):
        self._engine = engine
        self._api = getattr(api, "inner", api)
        self._runner = runner
        self._recorder = flight_recorder
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def tick(self) -> int:
        """One chaos round; returns the number of tears that landed."""
        torn = 0
        for index, policy in enumerate(self._engine.policy.torn):
            if policy.torn_rate <= 0.0:
                continue
            pods = self._api.list("pods", policy.namespace or None)
            for pod in pods:
                if (pod.get("status") or {}).get("phase") != "Running":
                    continue
                meta = pod.get("metadata") or {}
                labels = meta.get("labels") or {}
                role = labels.get(JOB_ROLE_LABEL, "")
                if policy.roles and role not in policy.roles:
                    continue
                key = (meta.get("namespace", ""), meta.get("name", ""))
                if not self._engine.torn_fault(index, policy):
                    continue
                if self._runner.tear_write(key[0], key[1]):
                    # Kill after arming: the death is the fault being
                    # modelled; the armed tear reaches the replacement.
                    self._runner.kill_pod(key[0], key[1])
                    self._engine.confirm_torn(index, f"{key[0]}/{key[1]}")
                    _record_fault(
                        self._recorder, meta, TORN_WRITE,
                        "killed mid-commit (marker withheld)",
                    )
                    torn += 1
        return torn

    # -- background pacing (live soaks) ---------------------------------

    def start(self, interval: float = 0.05) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, args=(interval,), daemon=True,
            name="chaos-tornwriteinjector",
        )
        self._thread.start()

    def _loop(self, interval: float) -> None:
        while not self._stop.is_set():
            self.tick()
            self._stop.wait(interval)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


class LeakInjector:
    """MemoryLeak chaos: each tick gives every matching running worker
    one seeded draw deciding whether its reported HBM starts growing by
    the policy's per-window increment (``runner.leak_worker``, which
    injects TPU_MEM_LEAK_BYTES at the victim's next (re)start).
    Already-leaking victims are skipped — one leak per victim, not a
    compounding one.  Same pacing contract as PodKiller: a thread in
    live soaks, explicit ``tick()`` calls in deterministic replays.

    With a flight recorder wired, every landed leak also lands on the
    victim job's timeline as a ``mem_leak`` entry.
    """

    def __init__(
        self,
        engine: ChaosEngine,
        api: InMemoryAPIServer,
        runner,
        flight_recorder=None,
    ):
        self._engine = engine
        self._api = getattr(api, "inner", api)
        self._runner = runner
        self._recorder = flight_recorder
        self._leaked: set[tuple[str, str]] = set()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def tick(self) -> int:
        """One chaos round; returns the number of leaks that landed."""
        leaked = 0
        for index, policy in enumerate(self._engine.policy.leak):
            if policy.leak_rate <= 0.0 or policy.bytes_per_window <= 0:
                continue
            pods = self._api.list("pods", policy.namespace or None)
            for pod in pods:
                if (pod.get("status") or {}).get("phase") != "Running":
                    continue
                meta = pod.get("metadata") or {}
                labels = meta.get("labels") or {}
                role = labels.get(JOB_ROLE_LABEL, "")
                if policy.roles and role not in policy.roles:
                    continue
                key = (meta.get("namespace", ""), meta.get("name", ""))
                if key in self._leaked:
                    continue
                if not self._engine.leak_fault(index, policy):
                    continue
                if self._runner.leak_worker(
                    key[0], key[1], policy.bytes_per_window
                ):
                    self._leaked.add(key)
                    self._engine.confirm_leak(
                        index, f"{key[0]}/{key[1]}",
                        policy.bytes_per_window,
                    )
                    _record_fault(
                        self._recorder, meta, MEM_LEAK,
                        f"leaking {policy.bytes_per_window} bytes/window",
                    )
                    leaked += 1
        return leaked

    # -- background pacing (live soaks) ---------------------------------

    def start(self, interval: float = 0.05) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, args=(interval,), daemon=True,
            name="chaos-leakinjector",
        )
        self._thread.start()

    def _loop(self, interval: float) -> None:
        while not self._stop.is_set():
            self.tick()
            self._stop.wait(interval)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
