"""Fault policies for the chaos harness.

Everything here is declarative and immutable: a ``ChaosPolicy`` is a full
description of one chaos run (which verbs flake, how the watch streams
misbehave, which pods die) plus the seed that makes the run replayable.
The engine (``chaos/engine.py``) interprets the policy; the wrappers
(``chaos/apiserver.py``, ``chaos/podchaos.py``) apply it.

Reference analogs: kube-apiserver's ``APIServerTracing`` fault-injection
test shims and chaos-mesh's PodChaos/NetworkChaos CRDs, collapsed to the
three fault surfaces this operator actually exercises — apiserver verbs,
watch streams, and pod/node lifetime.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from ..api.v2beta1.constants import ROLE_LAUNCHER, ROLE_WORKER

# Verbs that mutate state; fault injection on these models write races
# (conflicts) and apiserver hiccups (500s/timeouts).
WRITE_VERBS = ("create", "update", "update_status", "delete")
READ_VERBS = ("get", "list")


def _check_rate(name: str, value: float) -> None:
    if not 0.0 <= value <= 1.0:
        raise ValueError(f"{name} must be in [0, 1], got {value!r}")


@dataclass(frozen=True)
class VerbFaults:
    """Per-call fault rates for apiserver verbs.

    Exactly one uniform draw decides each call's fate, partitioned
    conflict → server error → timeout, so rates are mutually exclusive
    and their sum is the per-call fault probability.
    """

    conflict_rate: float = 0.0
    server_error_rate: float = 0.0
    timeout_rate: float = 0.0
    verbs: tuple[str, ...] = WRITE_VERBS
    resources: tuple[str, ...] = ()  # () = every resource

    def __post_init__(self) -> None:
        _check_rate("conflict_rate", self.conflict_rate)
        _check_rate("server_error_rate", self.server_error_rate)
        _check_rate("timeout_rate", self.timeout_rate)
        if self.total_rate > 1.0:
            raise ValueError(
                f"fault rates sum to {self.total_rate}, must be <= 1"
            )

    @property
    def total_rate(self) -> float:
        return self.conflict_rate + self.server_error_rate + self.timeout_rate

    def applies(self, verb: str, resource: str) -> bool:
        if verb not in self.verbs:
            return False
        return not self.resources or resource in self.resources


@dataclass(frozen=True)
class WatchFaults:
    """Per-event fault rates for watch streams.

    ``drop`` loses the event (a lossy stream the informer can only heal
    by resync), ``delay`` re-delivers it ``delay_rounds`` drains later
    (out-of-order delivery), ``gone`` compacts the stream — everything
    buffered is lost and the drain raises 410 Gone, forcing a relist.
    """

    drop_rate: float = 0.0
    delay_rate: float = 0.0
    gone_rate: float = 0.0
    delay_rounds: int = 2
    resources: tuple[str, ...] = ()  # () = every resource

    def __post_init__(self) -> None:
        _check_rate("drop_rate", self.drop_rate)
        _check_rate("delay_rate", self.delay_rate)
        _check_rate("gone_rate", self.gone_rate)
        if self.drop_rate + self.delay_rate + self.gone_rate > 1.0:
            raise ValueError("watch fault rates must sum to <= 1")
        if self.delay_rounds < 1:
            raise ValueError("delay_rounds must be >= 1")

    def applies(self, resource: str) -> bool:
        return not self.resources or resource in self.resources


@dataclass(frozen=True)
class PodChaos:
    """Random pod kills and node deaths.

    ``kill_rate`` SIGKILLs the pod's process — the reaper classifies it
    like any crash and surfaces exit code 137, the TPU-preemption
    signature a ``podFailurePolicy`` rule can match.  ``node_death_rate``
    rips the pod out from under the runner and flips its phase to
    ``Failed`` with ``status.reason=NodeLost`` (no exit code), the shape
    an ``onPodConditions``-style reason rule matches.
    """

    kill_rate: float = 0.0
    node_death_rate: float = 0.0
    roles: tuple[str, ...] = (ROLE_WORKER, ROLE_LAUNCHER)
    namespace: str = ""  # "" = every namespace
    max_kills: int = 0  # 0 = unlimited

    def __post_init__(self) -> None:
        _check_rate("kill_rate", self.kill_rate)
        _check_rate("node_death_rate", self.node_death_rate)
        if self.kill_rate + self.node_death_rate > 1.0:
            raise ValueError("pod chaos rates must sum to <= 1")


@dataclass(frozen=True)
class SlowWorkerChaos:
    """Degraded-host injection: a matching running worker is slowed by
    ``factor`` (its step wall time stretched, optimization math intact).

    Models the straggler failure mode the step-skew observatory
    (utils/stepstats.py) exists to catch — a host that keeps making
    progress, just slower than the gang, which pod-phase chaos can never
    produce.  ``factor`` multiplies the worker's step clock: 1.0 is a
    no-op (useful as the bench's control arm), 2.0 halves its step rate.
    """

    slow_rate: float = 0.0
    factor: float = 2.0
    roles: tuple[str, ...] = (ROLE_WORKER,)
    namespace: str = ""  # "" = every namespace
    max_slow: int = 0  # 0 = unlimited

    def __post_init__(self) -> None:
        _check_rate("slow_rate", self.slow_rate)
        if self.factor < 1.0:
            raise ValueError(
                f"factor must be >= 1 (a speed-up is not chaos), "
                f"got {self.factor!r}"
            )


@dataclass(frozen=True)
class MemoryLeakChaos:
    """Leaking-worker injection: a matching running worker's *reported*
    HBM grows by ``bytes_per_window`` every telemetry window.

    Models the slow-burn memory leak the device-memory observatory
    (utils/devstats.py) exists to catch early — a watermark that climbs
    for many windows before the OOM killer fires, which pod-phase chaos
    can never produce.  ``bytes_per_window=0`` is a no-op (useful as the
    bench's control arm).
    """

    leak_rate: float = 0.0
    bytes_per_window: int = 0
    roles: tuple[str, ...] = (ROLE_WORKER,)
    namespace: str = ""  # "" = every namespace
    max_leak: int = 0  # 0 = unlimited

    def __post_init__(self) -> None:
        _check_rate("leak_rate", self.leak_rate)
        if self.bytes_per_window < 0:
            raise ValueError(
                f"bytes_per_window must be >= 0 (memory un-leaking is "
                f"not chaos), got {self.bytes_per_window!r}"
            )


@dataclass(frozen=True)
class TornWriteChaos:
    """Torn-checkpoint injection: a matching running worker's *next*
    checkpoint commit is torn — the step data reaches disk but the commit
    marker never lands — and the worker is killed at that moment.

    Models the exact death window the commit-marker protocol
    (utils/checkpoint.py) exists to survive: a preemption between the
    fsync of the checkpoint payload and the atomic rename that publishes
    it.  Restore must skip the uncommitted newest step and fall back to
    an older committed one, not crash on (or worse, trust) a torn write.
    """

    torn_rate: float = 0.0
    roles: tuple[str, ...] = (ROLE_WORKER,)
    namespace: str = ""  # "" = every namespace
    max_torn: int = 0  # 0 = unlimited

    def __post_init__(self) -> None:
        _check_rate("torn_rate", self.torn_rate)
        if self.max_torn < 0:
            raise ValueError(
                f"max_torn must be >= 0, got {self.max_torn!r}"
            )


@dataclass(frozen=True)
class ChaosPolicy:
    """One replayable chaos run: seed + the active fault policies."""

    seed: int = 0
    verbs: tuple[VerbFaults, ...] = ()
    watch: Optional[WatchFaults] = None
    pods: tuple[PodChaos, ...] = ()
    slow: tuple[SlowWorkerChaos, ...] = ()
    leak: tuple[MemoryLeakChaos, ...] = ()
    torn: tuple[TornWriteChaos, ...] = ()

    def verb_policy(self, verb: str, resource: str) -> Optional[VerbFaults]:
        """First policy matching (verb, resource); None = no faults."""
        for policy in self.verbs:
            if policy.applies(verb, resource):
                return policy
        return None
