"""Seeded chaos engine: one RNG, one ordered event log, one decision
point per fault surface.

Determinism contract: every fault decision consumes exactly one draw
from a single ``random.Random(seed)``, and every *injected* fault is
appended to an ordered event log.  Given the same policy and the same
sequence of decision calls (e.g. a single-threaded, manually-pumped
stack), the same seed therefore reproduces the identical fault sequence
— the property the soak test asserts, and the property that makes a
failing chaos run replayable from its seed alone.  Under free-running
threads the per-call *order* is up to the OS scheduler, but the invariant
suite (convergence, no leaks, ledger balance) holds for every
interleaving.
"""

from __future__ import annotations

import random
from dataclasses import dataclass
from typing import Optional

from ..runtime.apiserver import (
    ApiError,
    ConflictError,
    ServerError,
    ServerTimeoutError,
)
from ..runtime import locktrace
from ..utils.metrics import Registry, new_counter
from .policy import (
    ChaosPolicy,
    MemoryLeakChaos,
    PodChaos,
    SlowWorkerChaos,
    TornWriteChaos,
)

# Fault kinds (event-log / metric label vocabulary).
CONFLICT = "conflict"
SERVER_ERROR = "server_error"
TIMEOUT = "timeout"
WATCH_DROP = "watch_drop"
WATCH_DELAY = "watch_delay"
WATCH_GONE = "watch_gone"
POD_KILL = "pod_kill"
NODE_DEATH = "node_death"
SLOW_WORKER = "slow_worker"
MEM_LEAK = "mem_leak"
TORN_WRITE = "torn_write"


@dataclass(frozen=True)
class ChaosEvent:
    seq: int
    kind: str
    target: str  # e.g. "update pods/ns/train-worker-0"
    detail: str = ""


class ChaosEngine:
    """Interprets a ChaosPolicy with a seeded RNG and logs what it did."""

    def __init__(
        self,
        policy: ChaosPolicy,
        registry: Optional[Registry] = None,
    ):
        self.policy = policy
        self.seed = policy.seed
        self._rng = random.Random(policy.seed)
        self._lock = locktrace.lock("chaos.engine")
        self._events: list[ChaosEvent] = []
        self._kill_counts: dict[int, int] = {}
        self._slow_counts: dict[int, int] = {}
        self._leak_counts: dict[int, int] = {}
        self._torn_counts: dict[int, int] = {}
        self.faults_total = new_counter(
            "tpu_operator_chaos_faults_injected_total",
            "Faults injected by the chaos engine, by kind.",
            ("kind",),
            registry=registry,
        )
        self.pod_kills_total = new_counter(
            "tpu_operator_chaos_pod_kills_total",
            "Pods killed by the chaos engine, by mode (pod_kill|node_death).",
            ("mode",),
            registry=registry,
        )
        self.pod_slowdowns_total = new_counter(
            "tpu_operator_chaos_pod_slowdowns_total",
            "Workers degraded by the chaos engine (SlowWorker faults).",
            registry=registry,
        )
        self.pod_leaks_total = new_counter(
            "tpu_operator_chaos_pod_leaks_total",
            "Workers given an injected HBM leak by the chaos engine "
            "(MemoryLeak faults).",
            registry=registry,
        )
        self.pod_torn_writes_total = new_counter(
            "tpu_operator_chaos_pod_torn_writes_total",
            "Workers killed mid-checkpoint-commit by the chaos engine "
            "(TornWrite faults: step data persisted, commit marker "
            "withheld).",
            registry=registry,
        )

    # -- event log -------------------------------------------------------

    def record(self, kind: str, target: str, detail: str = "") -> ChaosEvent:
        with self._lock:
            event = ChaosEvent(len(self._events), kind, target, detail)
            self._events.append(event)
        self.faults_total.inc(1.0, kind)
        return event

    def events(self) -> tuple[ChaosEvent, ...]:
        with self._lock:
            return tuple(self._events)

    def timeline(self) -> list[tuple[str, str, str]]:
        """(kind, target, detail) triples in injection order — the stable
        comparison form for same-seed replay assertions."""
        return [(e.kind, e.target, e.detail) for e in self.events()]

    def roll(self) -> float:
        with self._lock:
            return self._rng.random()

    # -- apiserver verbs -------------------------------------------------

    def fault_for(
        self, verb: str, resource: str, name: str
    ) -> Optional[ApiError]:
        """Decide one verb call's fate; return the error to raise (already
        recorded) or None.  Consumes exactly one draw when a policy
        matches, zero otherwise."""
        policy = self.policy.verb_policy(verb, resource)
        if policy is None or policy.total_rate <= 0.0:
            return None
        r = self.roll()
        target = f"{verb} {resource}/{name}"
        if r < policy.conflict_rate:
            self.record(CONFLICT, target)
            return ConflictError(resource, name, "chaos: injected conflict")
        r -= policy.conflict_rate
        if r < policy.server_error_rate:
            self.record(SERVER_ERROR, target)
            return ServerError(resource, name, "chaos: injected 500")
        r -= policy.server_error_rate
        if r < policy.timeout_rate:
            self.record(TIMEOUT, target)
            return ServerTimeoutError(resource, name, "chaos: injected timeout")
        return None

    # -- watch streams ---------------------------------------------------

    def watch_fault(self, resource: str, key: str) -> Optional[str]:
        """Decide one watch event's fate: WATCH_DROP, WATCH_DELAY,
        WATCH_GONE, or None (deliver normally)."""
        watch = self.policy.watch
        if watch is None or not watch.applies(resource):
            return None
        r = self.roll()
        target = f"watch {resource}/{key}"
        if r < watch.gone_rate:
            self.record(WATCH_GONE, target)
            return WATCH_GONE
        r -= watch.gone_rate
        if r < watch.drop_rate:
            self.record(WATCH_DROP, target)
            return WATCH_DROP
        r -= watch.drop_rate
        if r < watch.delay_rate:
            self.record(WATCH_DELAY, target, f"rounds={watch.delay_rounds}")
            return WATCH_DELAY
        return None

    # -- pod / node chaos ------------------------------------------------

    def pod_fault(self, policy_index: int, policy: PodChaos) -> Optional[str]:
        """Decide one (policy, pod, tick)'s fate: POD_KILL, NODE_DEATH, or
        None.  A confirmed kill must be reported via confirm_kill so the
        max_kills budget counts only kills that actually landed."""
        if policy.kill_rate <= 0.0 and policy.node_death_rate <= 0.0:
            return None
        if policy.max_kills:
            with self._lock:
                if self._kill_counts.get(policy_index, 0) >= policy.max_kills:
                    return None
        r = self.roll()
        if r < policy.kill_rate:
            return POD_KILL
        if r < policy.kill_rate + policy.node_death_rate:
            return NODE_DEATH
        return None

    def confirm_kill(self, policy_index: int, mode: str, key: str) -> None:
        with self._lock:
            self._kill_counts[policy_index] = (
                self._kill_counts.get(policy_index, 0) + 1
            )
        self.record(mode, f"pod {key}")
        self.pod_kills_total.inc(1.0, mode)

    # -- slow workers ----------------------------------------------------

    def slow_fault(
        self, policy_index: int, policy: SlowWorkerChaos
    ) -> bool:
        """Decide one (policy, pod, tick)'s fate: slow the worker or not.
        One draw per decision (the determinism contract); a landed
        slowdown must be reported via confirm_slow so the max_slow budget
        counts only victims that actually degraded."""
        if policy.slow_rate <= 0.0:
            return False
        if policy.max_slow:
            with self._lock:
                if (
                    self._slow_counts.get(policy_index, 0)
                    >= policy.max_slow
                ):
                    return False
        return self.roll() < policy.slow_rate

    def confirm_slow(
        self, policy_index: int, key: str, factor: float
    ) -> None:
        with self._lock:
            self._slow_counts[policy_index] = (
                self._slow_counts.get(policy_index, 0) + 1
            )
        self.record(SLOW_WORKER, f"pod {key}", f"factor={factor}")
        self.pod_slowdowns_total.inc(1.0)

    # -- leaking workers -------------------------------------------------

    def leak_fault(
        self, policy_index: int, policy: MemoryLeakChaos
    ) -> bool:
        """Decide one (policy, pod, tick)'s fate: give the worker an
        injected HBM leak or not.  One draw per decision (the
        determinism contract); a landed leak must be reported via
        confirm_leak so the max_leak budget counts only victims that
        actually started leaking."""
        if policy.leak_rate <= 0.0:
            return False
        if policy.max_leak:
            with self._lock:
                if (
                    self._leak_counts.get(policy_index, 0)
                    >= policy.max_leak
                ):
                    return False
        return self.roll() < policy.leak_rate

    def confirm_leak(
        self, policy_index: int, key: str, bytes_per_window: int
    ) -> None:
        with self._lock:
            self._leak_counts[policy_index] = (
                self._leak_counts.get(policy_index, 0) + 1
            )
        self.record(
            MEM_LEAK, f"pod {key}", f"bytes_per_window={bytes_per_window}"
        )
        self.pod_leaks_total.inc(1.0)

    # -- torn checkpoint commits -----------------------------------------

    def torn_fault(
        self, policy_index: int, policy: TornWriteChaos
    ) -> bool:
        """Decide one (policy, pod, tick)'s fate: tear the worker's next
        checkpoint commit or not.  One draw per decision (the determinism
        contract); a landed tear must be reported via confirm_torn so the
        max_torn budget counts only victims that actually got armed."""
        if policy.torn_rate <= 0.0:
            return False
        if policy.max_torn:
            with self._lock:
                if (
                    self._torn_counts.get(policy_index, 0)
                    >= policy.max_torn
                ):
                    return False
        return self.roll() < policy.torn_rate

    def confirm_torn(self, policy_index: int, key: str) -> None:
        with self._lock:
            self._torn_counts[policy_index] = (
                self._torn_counts.get(policy_index, 0) + 1
            )
        self.record(TORN_WRITE, f"pod {key}")
        self.pod_torn_writes_total.inc(1.0)
