"""Seeded, deterministic fault injection for the operator stack.

The harness wraps the two process-local substrates everything else runs
on — ``runtime.apiserver.InMemoryAPIServer`` and
``runtime.podrunner.LocalPodRunner`` — behind declarative fault policies
driven by one ``random.Random(seed)``, so any chaos run is replayable
from its seed.  See docs/failure-handling.md for usage.
"""

from .apiserver import ChaoticAPIServer, ChaoticWatch
from .engine import (
    CONFLICT,
    MEM_LEAK,
    NODE_DEATH,
    POD_KILL,
    SERVER_ERROR,
    SLOW_WORKER,
    TIMEOUT,
    TORN_WRITE,
    WATCH_DELAY,
    WATCH_DROP,
    WATCH_GONE,
    ChaosEngine,
    ChaosEvent,
)
from .podchaos import LeakInjector, PodKiller, TornWriteInjector, WorkerSlower
from .policy import (
    READ_VERBS,
    WRITE_VERBS,
    ChaosPolicy,
    MemoryLeakChaos,
    PodChaos,
    SlowWorkerChaos,
    TornWriteChaos,
    VerbFaults,
    WatchFaults,
)

__all__ = [
    "CONFLICT",
    "MEM_LEAK",
    "NODE_DEATH",
    "POD_KILL",
    "READ_VERBS",
    "SERVER_ERROR",
    "SLOW_WORKER",
    "TIMEOUT",
    "TORN_WRITE",
    "WATCH_DELAY",
    "WATCH_DROP",
    "WATCH_GONE",
    "WRITE_VERBS",
    "ChaosEngine",
    "ChaosEvent",
    "ChaosPolicy",
    "ChaoticAPIServer",
    "ChaoticWatch",
    "LeakInjector",
    "MemoryLeakChaos",
    "PodChaos",
    "PodKiller",
    "SlowWorkerChaos",
    "TornWriteChaos",
    "TornWriteInjector",
    "VerbFaults",
    "WatchFaults",
    "WorkerSlower",
]
