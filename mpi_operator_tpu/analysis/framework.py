"""Rule framework: findings, the rule registry, noqa, and baselines.

The golangci-lint shape without golangci-lint: every check is a
registered :class:`Rule` with a stable ``TPUxxx`` ID, checks run over a
:class:`RepoView` (one parse per file, shared by every rule), findings
carry ``file:line`` locations, and a committed baseline file lets new
violations fail CI while legacy ones stay tracked instead of silenced.

Suppression contract (flake8 semantics, extended):

- a bare ``# noqa`` on the offending line suppresses every rule there;
- ``# noqa: TPU101,TPU203`` suppresses only the listed rule IDs;
- the five style rules migrated from ``hack/lint.py`` also honour their
  legacy flake8 aliases (``# noqa: F401`` still silences TPU001), so no
  existing suppression comment in the tree changes meaning.

Baseline contract: keys are ``rule_id|file|message`` — deliberately
line-independent so unrelated edits shifting a legacy finding by a few
lines do not resurrect it — with an occurrence count per key.  "New"
findings are occurrences in excess of the baselined count; a shrunk
count is progress, not drift (regenerate with ``--update-baseline``).
"""

from __future__ import annotations

import ast
import json
from dataclasses import dataclass, field
from pathlib import Path
from typing import Callable, Iterable, Optional

# Everything the repo lints, including the fleet benchmark (which the
# old hack/lint.py ROOTS list silently missed).
REPO_ROOTS = [
    "mpi_operator_tpu", "sdk", "hack", "tests",
    "bench.py", "bench_controlplane.py", "__graft_entry__.py",
    "conftest.py",
]

# Style rules migrated from hack/lint.py keep honouring their original
# flake8 codes in noqa comments.
LEGACY_ALIASES = {
    "TPU001": "F401",
    "TPU002": "B006",
    "TPU003": "E722",
    "TPU004": "F541",
    "TPU005": "F811",
}

SYNTAX_RULE_ID = "TPU000"


@dataclass(frozen=True, order=True)
class Finding:
    file: str  # repo-relative, forward slashes
    line: int
    rule_id: str
    message: str

    @property
    def baseline_key(self) -> str:
        return f"{self.rule_id}|{self.file}|{self.message}"

    def render(self) -> str:
        return f"{self.file}:{self.line}: {self.rule_id} {self.message}"

    def to_dict(self) -> dict:
        return {
            "rule": self.rule_id,
            "file": self.file,
            "line": self.line,
            "message": self.message,
        }


class SourceFile:
    """One parsed source file: lazy AST, line access, noqa lookup."""

    def __init__(self, path: Path, rel: str):
        self.path = path
        self.rel = rel.replace("\\", "/")
        self.text = path.read_text()
        self.lines = self.text.splitlines()
        self._tree: Optional[ast.AST] = None
        self._parsed = False
        self.syntax_error: Optional[SyntaxError] = None

    @property
    def tree(self) -> Optional[ast.AST]:
        if not self._parsed:
            self._parsed = True
            try:
                self._tree = ast.parse(self.text, filename=str(self.path))
            except SyntaxError as e:
                self.syntax_error = e
        return self._tree

    def noqa(self, lineno: int, rule_id: str) -> bool:
        if not 0 < lineno <= len(self.lines):
            return False
        line = self.lines[lineno - 1]
        idx = line.find("# noqa")
        if idx < 0:
            return False
        rest = line[idx + len("# noqa"):]
        if not rest.lstrip().startswith(":"):
            return True  # blanket suppression
        listed = {c.strip() for c in rest.lstrip()[1:].split(",")}
        accepted = {rule_id}
        alias = LEGACY_ALIASES.get(rule_id)
        if alias:
            accepted.add(alias)
        return bool(accepted & listed)


class RepoView:
    """The file set every rule runs over (one parse per file)."""

    def __init__(self, root: Path, roots: Optional[list[str]] = None):
        self.root = Path(root).resolve()
        self.files: list[SourceFile] = []
        self._by_rel: dict[str, SourceFile] = {}
        for entry in (roots if roots is not None else REPO_ROOTS):
            p = self.root / entry
            if not p.exists():
                continue
            paths = [p] if p.suffix == ".py" else sorted(p.rglob("*.py"))
            for f in paths:
                if "__pycache__" in f.parts:
                    continue
                rel = str(f.relative_to(self.root)).replace("\\", "/")
                if rel in self._by_rel:
                    continue
                sf = SourceFile(f, rel)
                self.files.append(sf)
                self._by_rel[rel] = sf

    def file(self, rel: str) -> Optional[SourceFile]:
        return self._by_rel.get(rel)

    def package_files(self) -> list[SourceFile]:
        """The operator package itself — where the semantic invariants
        (metric naming, sole writers, lock discipline) apply.  A view
        with no package tree (a test fixture, or ``--root`` pointed at a
        subset) applies them to every file instead."""
        pkg = [
            sf for sf in self.files
            if sf.rel.startswith("mpi_operator_tpu/")
        ]
        return pkg or self.files


# ----------------------------------------------------------------------
# Shared call-graph machinery
#
# lockcheck grew this first (intra-class "which locks are held on
# entry" inference); the jaxcheck family needs the same two fixpoint
# shapes over a *module-level* call graph (which helpers are reachable
# from a train step), so both live here and the checkers stay thin.
# ----------------------------------------------------------------------


def union_fixpoint(
    seed: dict, edges: dict
) -> dict:
    """Least fixpoint of ``acc[k] = seed[k] | U(acc[d] for d in
    edges[k])`` — transitive accumulation along call edges (lockcheck's
    may-acquire sets; generic transitive closure)."""
    acc = {k: frozenset(v) for k, v in seed.items()}
    changed = True
    while changed:
        changed = False
        for k in acc:
            v = acc[k]
            for dep in edges.get(k, ()):
                v = v | acc.get(dep, frozenset())
            if v != acc[k]:
                acc[k] = v
                changed = True
    return acc


def intersect_fixpoint(entry: dict, call_sites: dict) -> dict:
    """Greatest fixpoint of ``entry[k] = &((entry[caller] | extra) for
    (caller, extra) in call_sites[k])`` — "provably true on EVERY
    entry" inference (lockcheck's held-on-entry sets).  Keys whose
    entry set is already empty are external entry points and never
    shrink further."""
    entry = dict(entry)
    changed = True
    while changed:
        changed = False
        for name, sites in call_sites.items():
            if not entry.get(name):
                continue
            acc = entry[name]
            for caller, extra in sites:
                acc = acc & (entry.get(caller, frozenset()) | extra)
            if acc != entry[name]:
                entry[name] = acc
                changed = True
    return entry


@dataclass
class FunctionNode:
    """One function/method/closure definition in a module call graph."""

    name: str            # simple name
    qualname: str        # dotted lexical path ("Cls.m", "make.step")
    node: ast.AST        # the FunctionDef / AsyncFunctionDef
    lineno: int
    parent: Optional[str] = None   # enclosing def's qualname
    in_loop: bool = False          # defined lexically inside for/while
    calls: list = field(default_factory=list)  # (callee simple name, line)


class ModuleGraph:
    """Module-level call graph: every def (including closures and
    methods) plus simple-name call edges.  Resolution is by simple name
    — a heuristic vet, not a prover, matching lockcheck's contract."""

    def __init__(self, sf: SourceFile):
        self.sf = sf
        self.functions: list[FunctionNode] = []
        self.by_name: dict[str, list[FunctionNode]] = {}
        if sf.tree is not None:
            self._collect(sf.tree, parent=None, in_loop=False)

    def _collect(self, node: ast.AST, parent: Optional[str],
                 in_loop: bool) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = (f"{parent}.{child.name}" if parent else child.name)
                fn = FunctionNode(
                    child.name, qual, child, child.lineno, parent, in_loop)
                fn.calls = self._direct_calls(child)
                self.functions.append(fn)
                self.by_name.setdefault(child.name, []).append(fn)
                self._collect(child, parent=qual, in_loop=in_loop)
            elif isinstance(child, ast.ClassDef):
                self._collect(child, parent=child.name, in_loop=in_loop)
            else:
                looped = in_loop or isinstance(
                    child, (ast.For, ast.AsyncFor, ast.While))
                self._collect(child, parent=parent, in_loop=looped)

    @staticmethod
    def _direct_calls(fn_node: ast.AST) -> list:
        """(simple callee name, lineno) pairs in this def's own body —
        nested defs keep their calls (they are their own nodes)."""
        out = []
        stack = [c for c in ast.iter_child_nodes(fn_node)]
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if isinstance(node, ast.Call):
                f = node.func
                callee = (
                    f.id if isinstance(f, ast.Name)
                    else f.attr if isinstance(f, ast.Attribute) else None
                )
                if callee:
                    out.append((callee, node.lineno))
            stack.extend(ast.iter_child_nodes(node))
        return out

    def reachable(self, roots: Iterable[FunctionNode],
                  stop: Optional[Callable[[FunctionNode], bool]] = None,
                  ) -> list[FunctionNode]:
        """Defs reachable from ``roots`` via simple-name call edges.
        ``stop`` prunes traversal *through* a node (it is still
        returned) — jaxcheck stops at jitted boundaries, where implicit
        host transfers cannot hide."""
        seen: dict[str, FunctionNode] = {}
        frontier = list(roots)
        while frontier:
            fn = frontier.pop()
            if fn.qualname in seen:
                continue
            seen[fn.qualname] = fn
            if stop is not None and stop(fn):
                continue
            for callee, _ in fn.calls:
                frontier.extend(self.by_name.get(callee, ()))
        return sorted(seen.values(), key=lambda f: f.lineno)


def module_graph(sf: SourceFile) -> ModuleGraph:
    """The (cached) call graph of one source file."""
    cached = getattr(sf, "_module_graph", None)
    if cached is None:
        cached = sf._module_graph = ModuleGraph(sf)
    return cached


@dataclass(frozen=True)
class Rule:
    id: str
    name: str
    description: str
    check: Callable[[RepoView], Iterable[Finding]]


_RULES: dict[str, Rule] = {}


def rule(rule_id: str, name: str, description: str):
    """Register a check under a stable rule ID."""
    def register(fn: Callable[[RepoView], Iterable[Finding]]):
        if rule_id in _RULES:
            raise ValueError(f"duplicate rule id {rule_id}")
        _RULES[rule_id] = Rule(rule_id, name, description, fn)
        return fn
    return register


# Every rule family the analyzer ships.  A refactor that drops a rule
# module import would silently lose a whole family; the lint gate and
# hack/analyze.py both assert this registry is fully populated.
REQUIRED_RULE_FAMILIES = {
    "TPU0": "style (hack/lint.py heritage)",
    "TPU1": "metrics discipline",
    "TPU2": "hygiene",
    "TPU3": "sole-writer",
    "TPU4": "lock discipline",
    "TPU5": "jax perf-correctness",
}


def all_rules() -> list[Rule]:
    """Every registered rule, importing the rule modules on first use."""
    # Importing the rule modules registers their rules.
    from . import jaxcheck, lockcheck, rules  # noqa: F401
    return [_RULES[k] for k in sorted(_RULES)]


def missing_rule_families() -> list[str]:
    """Required family prefixes with no registered rule (should be
    empty; non-empty means a rule module stopped being imported)."""
    present = {r.id[:4] for r in all_rules()}
    return sorted(p for p in REQUIRED_RULE_FAMILIES if p not in present)


def run(repo: RepoView, select: Optional[Iterable[str]] = None) -> list[Finding]:
    """Run (selected) rules over the repo; noqa-filtered and sorted.

    ``select`` entries are rule-ID prefixes: ``TPU4`` runs the whole
    lock-discipline family, ``TPU101`` exactly one rule.  Syntax errors
    surface as TPU000 findings (and suppress the AST rules for that
    file rather than crashing them).
    """
    prefixes = tuple(select) if select else None
    findings: list[Finding] = []
    for sf in repo.files:
        if sf.tree is None and sf.syntax_error is not None:
            findings.append(Finding(
                sf.rel, sf.syntax_error.lineno or 1, SYNTAX_RULE_ID,
                f"syntax error: {sf.syntax_error.msg}",
            ))
    for r in all_rules():
        if prefixes and not r.id.startswith(prefixes):
            continue
        findings.extend(r.check(repo))
    kept = []
    for f in findings:
        sf = repo.file(f.file)
        if sf is not None and sf.noqa(f.line, f.rule_id):
            continue
        kept.append(f)
    return sorted(set(kept))


# ----------------------------------------------------------------------
# Baseline workflow
# ----------------------------------------------------------------------

BASELINE_VERSION = 1


def load_baseline(path: Path) -> dict[str, int]:
    if not Path(path).exists():
        return {}
    data = json.loads(Path(path).read_text())
    return {str(k): int(v) for k, v in data.get("findings", {}).items()}


def baseline_payload(findings: Iterable[Finding]) -> dict:
    counts: dict[str, int] = {}
    for f in findings:
        counts[f.baseline_key] = counts.get(f.baseline_key, 0) + 1
    return {
        "version": BASELINE_VERSION,
        "findings": {k: counts[k] for k in sorted(counts)},
    }


def write_baseline(path: Path, findings: Iterable[Finding]) -> None:
    Path(path).write_text(
        json.dumps(baseline_payload(findings), indent=2) + "\n"
    )


def new_findings(
    findings: Iterable[Finding], baseline: dict[str, int]
) -> list[Finding]:
    """Occurrences in excess of the baselined count per key."""
    seen: dict[str, int] = {}
    fresh = []
    for f in sorted(findings):
        seen[f.baseline_key] = seen.get(f.baseline_key, 0) + 1
        if seen[f.baseline_key] > baseline.get(f.baseline_key, 0):
            fresh.append(f)
    return fresh
