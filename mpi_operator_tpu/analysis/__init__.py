"""Static analysis for the operator's control plane.

Three layers, one rule registry:

- :mod:`framework` — ``Finding``/``Rule``/``RepoView`` plumbing, the
  ``# noqa`` contract (rule IDs plus the legacy flake8 aliases), and
  the committed-baseline workflow (``hack/analysis_baseline.json``).
- :mod:`rules` — the style tier migrated out of ``hack/lint.py``
  (TPU001–TPU005), Prometheus naming conventions (TPU1xx), control-
  plane hygiene (TPU2xx), and the sole-writer invariants (TPU3xx).
- :mod:`lockcheck` — the lock-discipline checker (TPU4xx): inferred
  attribute guards and the cross-module lock-ordering graph.

Run it all via ``hack/analyze.py`` (or ``make analyze``); the runtime
counterpart is :mod:`mpi_operator_tpu.runtime.locktrace`.
"""
