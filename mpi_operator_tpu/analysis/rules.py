"""The registered rule catalog (style, metrics, hygiene, sole-writer).

Every repo-wide AST sweep that used to live in ``tests/test_lint.py``
is a registered rule here with a stable ID; the pytest gate is now one
test (``tests/test_analysis.py::test_repo_has_no_new_findings``) and
``hack/lint.py`` is a thin shim over the TPU001–TPU005 family that
keeps its historic ``check_file`` API and flake8-style messages.

Families:

- TPU001–TPU005 — style tier (legacy aliases F401/B006/E722/F541/F811)
- TPU101–TPU114 — Prometheus metric naming, required families,
  and sole-writer metric prefixes
- TPU201–TPU207 — control-plane hygiene (logging, sleep, swallowed
  exceptions, profiling phase vocabulary)
- TPU301–TPU303 — sole-writer invariants (``runPolicy.suspend``,
  pod ``status.phase``, ``spec.nodeName``)

The lock-discipline family (TPU401/TPU402) lives in ``lockcheck.py``.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, Iterator

from .framework import Finding, RepoView, SourceFile, rule

MUTABLE_NODES = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp,
                 ast.SetComp)

# Control-plane packages: writers that must stay responsive and honest
# under fault injection (the chaos tier exercises exactly these paths).
CONTROL_PLANE_PREFIXES = (
    "mpi_operator_tpu/controller/",
    "mpi_operator_tpu/scheduler/",
    "mpi_operator_tpu/queue/",
)


def _is_operator_view(repo: RepoView) -> bool:
    """True when the view contains the operator package itself.  The
    presence rules (required metric families, logger adoption, phase
    emitters) assert that named modules keep doing something; on a
    fixture or subset view those modules are legitimately absent."""
    return any(sf.rel.startswith("mpi_operator_tpu/") for sf in repo.files)


def _callee_name(node: ast.Call) -> str:
    fn = node.func
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return ""


def _calls(sf: SourceFile) -> Iterator[tuple[int, str, ast.Call]]:
    if sf.tree is None:
        return
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Call):
            yield node.lineno, _callee_name(node), node


# ----------------------------------------------------------------------
# TPU001–TPU005: style tier (migrated verbatim from hack/lint.py)
# ----------------------------------------------------------------------


def _names_loaded(tree: ast.AST) -> set[str]:
    """Every identifier the module reads (attribute roots included;
    names inside string annotations are out of scope — rare cases are
    exempted by # noqa)."""
    used: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            used.add(node.id)
        elif isinstance(node, ast.Attribute):
            root = node
            while isinstance(root, ast.Attribute):
                root = root.value
            if isinstance(root, ast.Name):
                used.add(root.id)
    return used


def _exported(tree: ast.AST) -> set[str]:
    out: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "__all__":
                    if isinstance(node.value, (ast.List, ast.Tuple)):
                        for elt in node.value.elts:
                            if isinstance(elt, ast.Constant) and isinstance(
                                    elt.value, str):
                                out.add(elt.value)
    return out


def style_findings(sf: SourceFile) -> list[Finding]:
    """The TPU001–TPU005 findings for one file (noqa NOT applied here —
    the framework filters, so the lint shim and the analyzer share one
    implementation)."""
    cached = getattr(sf, "_style_findings", None)
    if cached is not None:
        return cached
    findings: list[Finding] = []
    tree = sf.tree
    if tree is None:
        sf._style_findings = findings
        return findings

    # --- TPU001 (F401) unused imports ---------------------------------
    is_init = sf.path.name == "__init__.py"
    used = _names_loaded(tree)
    exported = _exported(tree)
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                bound = (a.asname or a.name).split(".")[0]
                if not is_init and bound not in used and bound not in exported:
                    findings.append(Finding(
                        sf.rel, node.lineno, "TPU001",
                        f"'{a.name}' imported but unused",
                    ))
        elif isinstance(node, ast.ImportFrom):
            if node.module == "__future__":
                continue
            for a in node.names:
                if a.name == "*":
                    continue
                bound = a.asname or a.name
                # In __init__.py an import IS the export surface; an
                # explicit ``x as x`` alias is the PEP-484 re-export
                # idiom elsewhere.
                reexport = is_init or (a.asname is not None
                                       and a.asname == a.name)
                if bound not in used and bound not in exported and not reexport:
                    findings.append(Finding(
                        sf.rel, node.lineno, "TPU001",
                        f"'{a.name}' imported but unused",
                    ))

    # Format specs ({x:.1f}) parse as nested JoinedStr nodes with no
    # FormattedValue of their own — they are not f-strings to flag.
    spec_ids = {
        id(n.format_spec)
        for n in ast.walk(tree)
        if isinstance(n, ast.FormattedValue) and n.format_spec is not None
    }

    for node in ast.walk(tree):
        # --- TPU002 (B006) mutable defaults ---------------------------
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defaults = list(node.args.defaults) + [
                d for d in node.args.kw_defaults if d is not None
            ]
            for d in defaults:
                if isinstance(d, MUTABLE_NODES):
                    findings.append(Finding(
                        sf.rel, d.lineno, "TPU002",
                        f"mutable default argument in {node.name}()",
                    ))
        # --- TPU003 (E722) bare except --------------------------------
        if isinstance(node, ast.ExceptHandler) and node.type is None:
            findings.append(Finding(
                sf.rel, node.lineno, "TPU003", "bare 'except:'",
            ))
        # --- TPU004 (F541) f-string without placeholders --------------
        if isinstance(node, ast.JoinedStr) and id(node) not in spec_ids:
            if not any(isinstance(v, ast.FormattedValue)
                       for v in node.values):
                findings.append(Finding(
                    sf.rel, node.lineno, "TPU004",
                    "f-string without any placeholders",
                ))

    # --- TPU005 (F811) redefinition in the same scope -----------------
    def scope_check(body: list, where: str) -> None:
        seen: dict[str, tuple[int, set]] = {}
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                decos = {
                    d.id if isinstance(d, ast.Name)
                    else d.attr if isinstance(d, ast.Attribute) else ""
                    for d in getattr(stmt, "decorator_list", [])
                }
                legit = decos & {"overload", "setter", "deleter", "getter",
                                 "register", "property"}
                prev = seen.get(stmt.name)
                # The undecorated implementation after @overload stubs is
                # the pattern working as intended (pyflakes exempts it by
                # remembering the PRIOR binding's decorators).
                prev_overload = prev is not None and "overload" in prev[1]
                if prev is not None and not legit and not prev_overload:
                    findings.append(Finding(
                        sf.rel, stmt.lineno, "TPU005",
                        f"redefinition of '{stmt.name}' (first defined at "
                        f"line {prev[0]}) in {where}",
                    ))
                seen[stmt.name] = (stmt.lineno, decos)
                scope_check(stmt.body, f"'{stmt.name}'")

    scope_check(tree.body, "module scope")
    sf._style_findings = findings
    return findings


def _style_rule(rule_id: str):
    def check(repo: RepoView) -> Iterable[Finding]:
        for sf in repo.files:
            for f in style_findings(sf):
                if f.rule_id == rule_id:
                    yield f
    return check


rule("TPU001", "unused-import",
     "Import is never used (F401); __init__.py re-exports, __all__ "
     "entries, and explicit `x as x` aliases are exempt.")(
    _style_rule("TPU001"))
rule("TPU002", "mutable-default",
     "Mutable default argument shared across calls (B006).")(
    _style_rule("TPU002"))
rule("TPU003", "bare-except",
     "Bare `except:` catches SystemExit/KeyboardInterrupt (E722).")(
    _style_rule("TPU003"))
rule("TPU004", "pointless-fstring",
     "f-string without placeholders (F541).")(
    _style_rule("TPU004"))
rule("TPU005", "redefinition",
     "def/class redefines a name already bound in the same scope "
     "(F811); @overload/@property setters are legitimate.")(
    _style_rule("TPU005"))


# ----------------------------------------------------------------------
# TPU101–TPU114: Prometheus metric conventions
# ----------------------------------------------------------------------

_METRIC_CTORS = ("new_counter", "new_gauge", "new_histogram")


def _metric_registrations(repo: RepoView):
    """(sf, lineno, kind, name, node) for every literal metric
    registration in the package source."""
    for sf in repo.package_files():
        for lineno, callee, node in _calls(sf):
            if callee not in _METRIC_CTORS:
                continue
            if not (node.args and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                continue
            yield sf, lineno, callee, node.args[0].value, node


@rule("TPU101", "metric-namespace",
      "Every metric carries the tpu_operator_ namespace prefix.")
def check_metric_namespace(repo: RepoView) -> Iterable[Finding]:
    for sf, line, kind, name, _ in _metric_registrations(repo):
        if not name.startswith("tpu_operator_"):
            yield Finding(sf.rel, line, "TPU101",
                          f"{kind}({name!r}): missing tpu_operator_ prefix")


@rule("TPU102", "counter-suffix",
      "Counters end in _total (Prometheus convention).")
def check_counter_suffix(repo: RepoView) -> Iterable[Finding]:
    for sf, line, kind, name, _ in _metric_registrations(repo):
        if kind == "new_counter" and not name.endswith("_total"):
            yield Finding(sf.rel, line, "TPU102",
                          f"{kind}({name!r}): counter must end in _total")


@rule("TPU103", "histogram-suffix",
      "Histograms use seconds as the base unit and end in _seconds.")
def check_histogram_suffix(repo: RepoView) -> Iterable[Finding]:
    for sf, line, kind, name, _ in _metric_registrations(repo):
        if kind == "new_histogram" and not name.endswith("_seconds"):
            yield Finding(sf.rel, line, "TPU103",
                          f"{kind}({name!r}): histogram must end in _seconds")


_SUBSYSTEM_PREFIXES = [
    ("TPU104", "mpi_operator_tpu/scheduler/", "tpu_operator_scheduler_"),
    ("TPU105", "mpi_operator_tpu/queue/", "tpu_operator_queue_"),
    ("TPU106", "mpi_operator_tpu/chaos/", "tpu_operator_chaos_"),
]


def _subsystem_rule(rule_id: str, pkg_prefix: str, metric_prefix: str):
    def check(repo: RepoView) -> Iterable[Finding]:
        for sf, line, kind, name, _ in _metric_registrations(repo):
            if sf.rel.startswith(pkg_prefix) and not name.startswith(
                    metric_prefix):
                yield Finding(
                    sf.rel, line, rule_id,
                    f"{kind}({name!r}): missing {metric_prefix} prefix",
                )
    return check


for _rid, _pkg, _metric in _SUBSYSTEM_PREFIXES:
    rule(_rid, f"{_pkg.split('/')[1]}-metric-prefix",
         f"Metrics registered under {_pkg} carry the {_metric} subsystem "
         "prefix so dashboards select the subsystem with one matcher.")(
        _subsystem_rule(_rid, _pkg, _metric))


def _gauges_with_labels(repo: RepoView):
    """(sf, lineno, name, label-names-or-None) for every literal
    new_gauge registration; labels is None when not a literal tuple."""
    for sf, line, kind, name, node in _metric_registrations(repo):
        if kind != "new_gauge":
            continue
        labels_node = node.args[2] if len(node.args) > 2 else None
        if labels_node is None:
            for kw in node.keywords:
                if kw.arg == "label_names":
                    labels_node = kw.value
        labels = None
        if labels_node is None:
            labels = ()
        elif isinstance(labels_node, (ast.Tuple, ast.List)) and all(
            isinstance(e, ast.Constant) and isinstance(e.value, str)
            for e in labels_node.elts
        ):
            labels = tuple(e.value for e in labels_node.elts)
        yield sf, line, name, labels


@rule("TPU107", "gauge-not-total",
      "Gauges never end in _total — that suffix promises a counter.")
def check_gauge_not_total(repo: RepoView) -> Iterable[Finding]:
    for sf, line, name, _ in _gauges_with_labels(repo):
        if name.endswith("_total"):
            yield Finding(sf.rel, line, "TPU107",
                          f"new_gauge({name!r}): _total suffix promises "
                          "a counter")


@rule("TPU108", "info-gauge-labels",
      "_info gauges carry identity as labels (constant value 1 means "
      "the labels ARE the payload).")
def check_info_gauge_labels(repo: RepoView) -> Iterable[Finding]:
    for sf, line, name, labels in _gauges_with_labels(repo):
        if name.endswith("_info") and labels is not None and not labels:
            yield Finding(sf.rel, line, "TPU108",
                          f"new_gauge({name!r}): _info gauge needs "
                          "identity labels")


@rule("TPU109", "by-phase-gauge-label",
      "by_phase gauges declare the phase label they enumerate.")
def check_by_phase_gauge_label(repo: RepoView) -> Iterable[Finding]:
    for sf, line, name, labels in _gauges_with_labels(repo):
        if "by_phase" in name and labels is not None and "phase" not in labels:
            yield Finding(sf.rel, line, "TPU109",
                          f"new_gauge({name!r}): by_phase gauge must "
                          "declare a phase label")


# Advertised metric families: their registrations must not silently
# vanish in a refactor.  Findings anchor at the owning module's head.
_REQUIRED_FAMILIES = [
    ("mpi_operator_tpu/scheduler/core.py", {
        "tpu_operator_scheduler_scheduling_duration_seconds",
        "tpu_operator_scheduler_pending_gangs",
        "tpu_operator_scheduler_binds_total",
        "tpu_operator_scheduler_preemptions_total",
    }),
    ("mpi_operator_tpu/queue/manager.py", {
        "tpu_operator_queue_pending_workloads",
        "tpu_operator_queue_admitted_workloads",
        "tpu_operator_queue_admission_duration_seconds",
        "tpu_operator_queue_evictions_total",
    }),
    ("mpi_operator_tpu/chaos/engine.py", {
        "tpu_operator_chaos_faults_injected_total",
        "tpu_operator_chaos_pod_kills_total",
    }),
    ("mpi_operator_tpu/utils/statemetrics.py", {
        "tpu_operator_job_info",
        "tpu_operator_jobs_by_phase",
        "tpu_operator_pods_by_phase",
        "tpu_operator_job_condition",
    }),
    ("mpi_operator_tpu/utils/goodput.py", {
        "tpu_operator_job_goodput_ratio",
        "tpu_operator_job_phase_seconds",
        "tpu_operator_job_goodput_fleet_ratio",
        "tpu_operator_job_phase_fleet_seconds",
    }),
    ("mpi_operator_tpu/utils/stepstats.py", {
        "tpu_operator_job_step_skew",
        "tpu_operator_job_stragglers",
    }),
    ("mpi_operator_tpu/utils/devstats.py", {
        "tpu_operator_job_hbm_peak_bytes",
        "tpu_operator_job_hbm_headroom_ratio",
    }),
    ("mpi_operator_tpu/utils/checkpoint.py", {
        "tpu_operator_job_checkpoint_snapshot_seconds",
        "tpu_operator_job_checkpoint_write_seconds",
        "tpu_operator_job_checkpoint_commits_total",
    }),
]


@rule("TPU110", "required-metric-families",
      "The advertised metric families (scheduler/queue/chaos quartets, "
      "the kube-state family) stay registered.")
def check_required_metric_families(repo: RepoView) -> Iterable[Finding]:
    if not _is_operator_view(repo):
        return
    registered = {name for _, _, _, name, _ in _metric_registrations(repo)}
    if len(registered) < 10:
        yield Finding("mpi_operator_tpu/utils/metrics.py", 1, "TPU110",
                      "metric registrations went missing (<10 literal "
                      "registrations in the package)")
    for anchor, required in _REQUIRED_FAMILIES:
        for name in sorted(required - registered):
            yield Finding(anchor, 1, "TPU110",
                          f"required metric {name!r} is not registered")


# The goodput ledger's families are an *attribution*: a second writer
# under these prefixes would double-count phases or split the series
# across owners, and dashboards keyed on the prefix could not tell.
_GOODPUT_PREFIXES = ("tpu_operator_job_goodput", "tpu_operator_job_phase")
_GOODPUT_OWNER = "mpi_operator_tpu/utils/goodput.py"


@rule("TPU111", "goodput-metric-sole-writer",
      "The tpu_operator_job_goodput*/tpu_operator_job_phase* metric "
      "prefixes are reserved for utils/goodput.py, the goodput ledger.")
def check_goodput_sole_writer(repo: RepoView) -> Iterable[Finding]:
    for sf, line, kind, name, _ in _metric_registrations(repo):
        if not name.startswith(_GOODPUT_PREFIXES):
            continue
        if sf.rel != _GOODPUT_OWNER:
            yield Finding(
                sf.rel, line, "TPU111",
                f"{kind}({name!r}): goodput/phase metric prefixes are "
                f"reserved for {_GOODPUT_OWNER}",
            )


# The step-skew families are a cross-worker *join*: a second writer
# would split the straggler verdicts across owners and decouple the
# skew histogram from the skew_wait carve it explains.
_STEPSTATS_PREFIXES = (
    "tpu_operator_job_step", "tpu_operator_job_stragglers",
)
_STEPSTATS_OWNER = "mpi_operator_tpu/utils/stepstats.py"


@rule("TPU112", "stepstats-metric-sole-writer",
      "The tpu_operator_job_step*/tpu_operator_job_stragglers metric "
      "prefixes are reserved for utils/stepstats.py, the step-skew "
      "observatory.")
def check_stepstats_sole_writer(repo: RepoView) -> Iterable[Finding]:
    for sf, line, kind, name, _ in _metric_registrations(repo):
        if not name.startswith(_STEPSTATS_PREFIXES):
            continue
        if sf.rel != _STEPSTATS_OWNER:
            yield Finding(
                sf.rel, line, "TPU112",
                f"{kind}({name!r}): step-skew metric prefixes are "
                f"reserved for {_STEPSTATS_OWNER}",
            )


# The device-memory families are the same kind of cross-worker join:
# a second writer would split the watermark/headroom series across
# owners and decouple them from the MemoryPressure verdicts they
# explain.
_DEVSTATS_PREFIXES = ("tpu_operator_job_hbm",)
_DEVSTATS_OWNER = "mpi_operator_tpu/utils/devstats.py"


@rule("TPU113", "devstats-metric-sole-writer",
      "The tpu_operator_job_hbm* metric prefixes are reserved for "
      "utils/devstats.py, the device-memory observatory.")
def check_devstats_sole_writer(repo: RepoView) -> Iterable[Finding]:
    for sf, line, kind, name, _ in _metric_registrations(repo):
        if not name.startswith(_DEVSTATS_PREFIXES):
            continue
        if sf.rel != _DEVSTATS_OWNER:
            yield Finding(
                sf.rel, line, "TPU113",
                f"{kind}({name!r}): device-memory metric prefixes are "
                f"reserved for {_DEVSTATS_OWNER}",
            )


# The checkpoint families narrate one durability pipeline (snapshot ->
# background write -> commit marker): a second writer would interleave
# foreign samples into the write/commit ratio that the torn-write
# forensics read, and make "commits != saves" undiagnosable.
_CHECKPOINT_PREFIXES = ("tpu_operator_job_checkpoint",)
_CHECKPOINT_OWNER = "mpi_operator_tpu/utils/checkpoint.py"


@rule("TPU114", "checkpoint-metric-sole-writer",
      "The tpu_operator_job_checkpoint* metric prefix is reserved for "
      "utils/checkpoint.py, the checkpoint durability pipeline.")
def check_checkpoint_sole_writer(repo: RepoView) -> Iterable[Finding]:
    for sf, line, kind, name, _ in _metric_registrations(repo):
        if not name.startswith(_CHECKPOINT_PREFIXES):
            continue
        if sf.rel != _CHECKPOINT_OWNER:
            yield Finding(
                sf.rel, line, "TPU114",
                f"{kind}({name!r}): checkpoint metric prefixes are "
                f"reserved for {_CHECKPOINT_OWNER}",
            )


# ----------------------------------------------------------------------
# TPU201–TPU207: control-plane hygiene
# ----------------------------------------------------------------------


@rule("TPU201", "no-print-outside-cmd",
      "Operator/runtime/scheduler code logs through the structured "
      "logger; bare print() is only legitimate in cmd/ entrypoints.")
def check_no_print(repo: RepoView) -> Iterable[Finding]:
    for sf in repo.package_files():
        if sf.rel.startswith("mpi_operator_tpu/cmd/"):
            continue
        for line, callee, _ in _calls(sf):
            if callee == "print":
                yield Finding(sf.rel, line, "TPU201", "print() outside cmd/")


@rule("TPU202", "structured-logging-only",
      "Logger handles come from utils/logging.get_logger; stdlib "
      "logging.getLogger bypasses the process-global sink.")
def check_get_logger(repo: RepoView) -> Iterable[Finding]:
    for sf in repo.package_files():
        if sf.rel == "mpi_operator_tpu/utils/logging.py":
            continue
        for line, callee, _ in _calls(sf):
            if callee == "getLogger":
                yield Finding(sf.rel, line, "TPU202",
                              "logging.getLogger() bypasses utils/logging")


@rule("TPU203", "no-bare-sleep",
      "Control-plane code pauses through runtime/retry.sleep, the "
      "single monkeypatchable chokepoint the chaos soak collapses.")
def check_no_bare_sleep(repo: RepoView) -> Iterable[Finding]:
    for sf in repo.package_files():
        if not sf.rel.startswith(CONTROL_PLANE_PREFIXES):
            continue
        for line, callee, node in _calls(sf):
            if callee != "sleep":
                continue
            fn = node.func
            bare_name = isinstance(fn, ast.Name)  # `from time import sleep`
            time_attr = (
                isinstance(fn, ast.Attribute)
                and isinstance(fn.value, ast.Name)
                and fn.value.id == "time"
            )
            if bare_name or time_attr:
                yield Finding(sf.rel, line, "TPU203",
                              "bare sleep() — use runtime/retry.sleep")


@rule("TPU204", "no-swallowed-exceptions",
      "`except Exception: pass` in controller/scheduler/queue silently "
      "eats the faults the chaos tier injects.")
def check_no_swallowed(repo: RepoView) -> Iterable[Finding]:
    for sf in repo.package_files():
        if not sf.rel.startswith(CONTROL_PLANE_PREFIXES) or sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            broad = node.type is None or (
                isinstance(node.type, ast.Name)
                and node.type.id in ("Exception", "BaseException")
            )
            silent = all(isinstance(stmt, ast.Pass) for stmt in node.body)
            if broad and silent:
                yield Finding(sf.rel, node.lineno, "TPU204",
                              "except Exception: pass swallows injected "
                              "faults")


@rule("TPU205", "canonical-phase-names",
      "Every .phase(...) call site passes a PHASE_* constant or a "
      "literal registered in profiling.PHASES (closed vocabulary).")
def check_phase_vocabulary(repo: RepoView) -> Iterable[Finding]:
    from mpi_operator_tpu.utils import profiling

    if not _is_operator_view(repo):
        return
    profiling_rel = "mpi_operator_tpu/utils/profiling.py"
    if not profiling.PHASES:
        yield Finding(profiling_rel, 1, "TPU205", "phase enum went missing")
        return
    for name in profiling.PHASES:
        if not re.fullmatch(r"[a-z_]+", name):
            yield Finding(profiling_rel, 1, "TPU205",
                          f"profiling phase {name!r} must match ^[a-z_]+$")
    if len(set(profiling.PHASES)) != len(profiling.PHASES):
        yield Finding(profiling_rel, 1, "TPU205",
                      "duplicate names in profiling.PHASES")
    if profiling.UNATTRIBUTED in profiling.PHASES:
        yield Finding(profiling_rel, 1, "TPU205",
                      "UNATTRIBUTED is a derived share label, never a "
                      "phase name")

    for sf in repo.package_files():
        # The enum's home defines phase() itself (the validating
        # constructor and the `profiled` decorator's pass-through).
        if sf.rel == profiling_rel:
            continue
        for line, callee, node in _calls(sf):
            if callee != "phase" or not isinstance(node.func, ast.Attribute):
                continue
            if not node.args:
                yield Finding(sf.rel, line, "TPU205",
                              ".phase() with no name")
            elif not (isinstance(node.args[0], ast.Constant)
                      and isinstance(node.args[0].value, str)):
                # Attribute references to the canonical constants are
                # the sanctioned spelling (profiling.PHASE_RENDER,
                # never a name computed at runtime).
                arg = node.args[0]
                is_const_ref = (
                    isinstance(arg, ast.Attribute)
                    and arg.attr.startswith("PHASE_")
                ) or (isinstance(arg, ast.Name)
                      and arg.id.startswith("PHASE_"))
                if not is_const_ref:
                    yield Finding(
                        sf.rel, line, "TPU205",
                        ".phase() argument must be a PHASE_* constant or "
                        "a literal registered in profiling.PHASES",
                    )
            elif node.args[0].value not in profiling.PHASES:
                yield Finding(
                    sf.rel, line, "TPU205",
                    f"phase {node.args[0].value!r} not registered in "
                    "profiling.PHASES",
                )


_REQUIRED_LOGGER_USERS = (
    "mpi_operator_tpu/controller/tpu_job_controller.py",
    "mpi_operator_tpu/scheduler/core.py",
    "mpi_operator_tpu/runtime/podrunner.py",
    "mpi_operator_tpu/launcher/bootstrap.py",
)


@rule("TPU206", "logger-adoption",
      "The sanctioned get_logger constructor stays in use across the "
      "controller, scheduler, podrunner, and launcher layers.")
def check_logger_adoption(repo: RepoView) -> Iterable[Finding]:
    if not _is_operator_view(repo):
        return
    users = {
        sf.rel for sf in repo.package_files()
        for _, callee, _ in _calls(sf) if callee == "get_logger"
    }
    for expected in _REQUIRED_LOGGER_USERS:
        if expected not in users:
            yield Finding(expected, 1, "TPU206",
                          "must use utils/logging.get_logger")


_REQUIRED_PHASE_EMITTERS = (
    "mpi_operator_tpu/controller/tpu_job_controller.py",
    "mpi_operator_tpu/scheduler/core.py",
    "mpi_operator_tpu/scheduler/binder.py",
    "mpi_operator_tpu/queue/manager.py",
)


@rule("TPU207", "phase-attribution-coverage",
      "The hot control-plane paths keep emitting phase timings (the "
      "/debug/profile attribution layer stays wired).")
def check_phase_emitters(repo: RepoView) -> Iterable[Finding]:
    if not _is_operator_view(repo):
        return
    users = {
        sf.rel for sf in repo.package_files()
        for _, callee, node in _calls(sf)
        if callee == "phase" and isinstance(node.func, ast.Attribute)
        and sf.rel != "mpi_operator_tpu/utils/profiling.py"
    }
    for expected in _REQUIRED_PHASE_EMITTERS:
        if expected not in users:
            yield Finding(expected, 1, "TPU207", "must emit phase timings")


# ----------------------------------------------------------------------
# TPU301–TPU303: sole-writer invariants
# ----------------------------------------------------------------------


def _assignment_targets(node: ast.AST) -> list:
    if isinstance(node, ast.Assign):
        return list(node.targets)
    if isinstance(node, (ast.AugAssign, ast.AnnAssign)):
        return [node.target]
    return []


def _writes_key(target, key: str) -> bool:
    """Does this assignment target write attribute/item ``key``?"""
    if isinstance(target, ast.Attribute) and target.attr == key:
        return True
    if (isinstance(target, ast.Subscript)
            and isinstance(target.slice, ast.Constant)
            and target.slice.value == key):
        return True
    if isinstance(target, (ast.Tuple, ast.List)):
        return any(_writes_key(e, key) for e in target.elts)
    return False


def _sole_writer_rule(rule_id: str, key: str, allowed, message: str):
    allowed_prefixes = tuple(a for a in allowed if a.endswith("/"))
    allowed_files = {a for a in allowed if not a.endswith("/")}

    def check(repo: RepoView) -> Iterable[Finding]:
        for sf in repo.package_files():
            if sf.rel.startswith(allowed_prefixes) or sf.rel in allowed_files:
                continue
            if sf.tree is None:
                continue
            for node in ast.walk(sf.tree):
                for target in _assignment_targets(node):
                    if _writes_key(target, key):
                        yield Finding(sf.rel, node.lineno, rule_id, message)
    return check


rule("TPU301", "suspend-sole-writer",
     "While the admission queue is enabled the QueueManager is the "
     "single writer of runPolicy.suspend — a second writer would fight "
     "it (admit/evict flapping).  The API types' own (de)serialization "
     "is exempt.")(
    _sole_writer_rule(
        "TPU301", "suspend",
        ["mpi_operator_tpu/queue/", "mpi_operator_tpu/api/v2beta1/types.py"],
        "suspend write outside queue/ (QueueManager is the sole writer)",
    ))

# The kubelet analog owns pod lifecycle: in this codebase the
# controller never writes pod status.phase — runtime/podrunner.py is
# the node agent that flips Pending/Running/Succeeded/Failed, and the
# API types (de)serialize their own field.
rule("TPU302", "pod-phase-sole-writer",
     "Pod status.phase transitions are the kubelet analog's to make: "
     "only runtime/podrunner.py (and the API types' own round-trip) "
     "may assign the phase field.")(
    _sole_writer_rule(
        "TPU302", "phase",
        ["mpi_operator_tpu/runtime/podrunner.py",
         "mpi_operator_tpu/api/v2beta1/types.py"],
        "status.phase write outside runtime/podrunner.py (the kubelet "
        "analog is the sole writer)",
    ))

rule("TPU303", "nodename-sole-writer",
     "spec.nodeName binds are the scheduler's decision: only "
     "scheduler/binder.py may assign it.  The legacy auto-bind path in "
     "podrunner is tracked in the committed baseline, not silenced.")(
    _sole_writer_rule(
        "TPU303", "nodeName",
        ["mpi_operator_tpu/scheduler/binder.py"],
        "spec.nodeName bind outside scheduler/binder.py (the scheduler "
        "is the sole writer)",
    ))
