"""Static lock-discipline checker: inferred guards + lock-order graph.

What Go gets from ``go vet`` plus a slice of what ``-race`` and kernel
lockdep prove dynamically, recovered from the AST:

**TPU401 — guarded-attribute discipline.**  For every class that owns a
lock (``self._lock = threading.Lock()/RLock()/Condition()`` or the
``locktrace`` factories), infer which ``self._*`` attributes that lock
guards: an attribute is *guarded* when it is mutated inside a
``with self._lock:`` body (directly, or in a private method only ever
called while the lock is held — a fixpoint over the intra-class call
graph).  An attribute mutated BOTH under its inferred guard AND outside
any lock is a race: the unguarded site is the finding.  ``__init__``
is exempt (no concurrent access before construction completes).

**TPU402 — lock-order inversions.**  Build a graph whose nodes are lock
identities (``Class.attr``) and whose edges mean "acquired while
holding": syntactic ``with`` nesting, private-method fixpoint ("called
only under A, takes B"), and cross-class edges resolved through
``self.x = SomeClass(...)`` constructor assignments and annotated
``__init__`` parameters (``Optional[SomeClass]`` unwraps).  Any cycle —
A→B somewhere, B→A somewhere else — is the classic deadlock
precondition.  Self-edges are skipped: re-acquiring the same RLock is
reentrancy, not an ordering bug (the reentrant non-finding).

Both rules are heuristic by design — this is a vet, not a prover — so
false positives are first-class citizens of the baseline workflow
rather than reasons to silence the rule.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Iterable, Optional

from .framework import (
    Finding,
    RepoView,
    SourceFile,
    intersect_fixpoint,
    rule,
    union_fixpoint,
)

# Calls that create a lock object when assigned to a self attribute.
_LOCK_CTORS = {"Lock", "RLock", "Condition"}
_LOCKTRACE_CTORS = {"lock", "rlock", "condition"}

# Methods that mutate their receiver in place (dict/list/set/deque).
_MUTATOR_METHODS = {
    "append", "appendleft", "add", "insert", "extend", "update",
    "setdefault", "pop", "popitem", "popleft", "remove", "discard",
    "clear", "__setitem__", "__delitem__",
}


def _call_name(node: ast.Call) -> tuple[str, str]:
    """(root, attr) of the callee: ``threading.Lock`` -> ("threading",
    "Lock"); bare ``Lock()`` -> ("", "Lock")."""
    fn = node.func
    if isinstance(fn, ast.Attribute):
        root = fn.value
        return (root.id if isinstance(root, ast.Name) else "", fn.attr)
    if isinstance(fn, ast.Name):
        return ("", fn.id)
    return ("", "")


def _is_lock_ctor(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    root, attr = _call_name(node)
    if attr in _LOCK_CTORS and root in ("threading", ""):
        return True
    if attr in _LOCKTRACE_CTORS and root == "locktrace":
        return True
    return False


def _self_attr(node: ast.AST) -> Optional[str]:
    """'x' when node is ``self.x``, else None."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _annotation_class(node: Optional[ast.AST]) -> Optional[str]:
    """Final class-name segment of a parameter annotation, unwrapping
    Optional[...] and string ("future") annotations."""
    if node is None:
        return None
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        try:
            node = ast.parse(node.value, mode="eval").body
        except SyntaxError:
            return None
    if isinstance(node, ast.Subscript):
        base = node.value
        base_name = (
            base.id if isinstance(base, ast.Name)
            else base.attr if isinstance(base, ast.Attribute) else ""
        )
        if base_name == "Optional":
            return _annotation_class(node.slice)
        return None
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


@dataclass
class MutationSite:
    attr: str
    line: int
    method: str
    held: frozenset  # syntactic held set at the site (lock ids)
    in_nested_def: bool = False


@dataclass
class AcquireSite:
    lock: str  # lock id "Class.attr"
    line: int
    held: frozenset  # what was already held syntactically


@dataclass
class CallSite:
    callee_class: str  # "" for intra-class self calls
    callee: str
    line: int
    held: frozenset


@dataclass
class MethodInfo:
    name: str
    lineno: int
    mutations: list = field(default_factory=list)
    acquires: list = field(default_factory=list)
    calls: list = field(default_factory=list)


@dataclass
class ClassInfo:
    name: str
    sf: SourceFile
    lineno: int
    lock_attrs: set = field(default_factory=set)
    methods: dict = field(default_factory=dict)  # name -> MethodInfo
    attr_types: dict = field(default_factory=dict)  # attr -> class name
    value_referenced: set = field(default_factory=set)  # method names

    def lock_ids(self) -> frozenset:
        return frozenset(f"{self.name}.{a}" for a in self.lock_attrs)


class _MethodWalker:
    """Walks one method body tracking the syntactic held-lock stack."""

    def __init__(self, cls: ClassInfo, method: MethodInfo, classes: dict):
        self.cls = cls
        self.method = method
        self.classes = classes

    def walk(self, body: list) -> None:
        self._visit_block(body, held=(), nested=False)

    # -- helpers --------------------------------------------------------

    def _lock_id_of(self, expr: ast.AST) -> Optional[str]:
        attr = _self_attr(expr)
        if attr is not None and attr in self.cls.lock_attrs:
            return f"{self.cls.name}.{attr}"
        # ``with self.x.lock:`` / ``with self.x._lock:`` — a neighbour
        # object's lock taken directly; resolve through attr_types.
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Attribute)):
            owner = _self_attr(expr.value)
            if owner is not None:
                owner_cls = self.classes.get(self.cls.attr_types.get(owner))
                if owner_cls is not None and expr.attr in owner_cls.lock_attrs:
                    return f"{owner_cls.name}.{expr.attr}"
        return None

    def _record_mutation(self, attr: str, line: int, held: tuple,
                         nested: bool) -> None:
        if attr in self.cls.lock_attrs:
            return  # assigning the lock object itself is construction
        self.method.mutations.append(MutationSite(
            attr, line, self.method.name, frozenset(held), nested))

    def _mutation_targets(self, target: ast.AST) -> list[tuple[str, int]]:
        """(attr, line) pairs this assignment target mutates on self."""
        out = []
        attr = _self_attr(target)
        if attr is not None:
            out.append((attr, target.lineno))
            return out
        if isinstance(target, ast.Subscript):
            # self.a[...] = v mutates a; self.a.b[...] = v mutates the
            # nested object — attribute the write to 'a' (closest self
            # root) so the guard inference still sees it.
            inner = target.value
            while isinstance(inner, (ast.Subscript, ast.Attribute)):
                a = _self_attr(inner)
                if a is not None:
                    out.append((a, target.lineno))
                    return out
                inner = inner.value
            return out
        if isinstance(target, ast.Attribute):
            # self.a.b = v mutates the object held in a.
            a = _self_attr(target.value)
            if a is not None:
                out.append((a, target.lineno))
            return out
        if isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                out.extend(self._mutation_targets(e))
        return out

    # -- traversal ------------------------------------------------------

    def _visit_block(self, body: list, held: tuple, nested: bool) -> None:
        for stmt in body:
            self._visit_stmt(stmt, held, nested)

    def _visit_stmt(self, stmt: ast.AST, held: tuple, nested: bool) -> None:
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            new_held = list(held)
            for item in stmt.items:
                lock_id = self._lock_id_of(item.context_expr)
                self._visit_expr(item.context_expr, tuple(new_held), nested)
                if lock_id is not None:
                    self.method.acquires.append(AcquireSite(
                        lock_id, item.context_expr.lineno,
                        frozenset(new_held)))
                    new_held.append(lock_id)
            self._visit_block(stmt.body, tuple(new_held), nested)
            return
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # A nested def's body runs at call time, possibly on another
            # thread with no lock held — analyse it with an empty held
            # set so deferred mutations never read as guarded.
            self._visit_block(stmt.body, (), True)
            return
        if isinstance(stmt, ast.Assign):
            for target in stmt.targets:
                if _is_lock_ctor(stmt.value):
                    continue  # lock construction handled in discovery
                for attr, line in self._mutation_targets(target):
                    self._record_mutation(attr, line, held, nested)
            self._visit_expr(stmt.value, held, nested)
            return
        if isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            if getattr(stmt, "value", None) is not None:
                for attr, line in self._mutation_targets(stmt.target):
                    self._record_mutation(attr, line, held, nested)
                self._visit_expr(stmt.value, held, nested)
            return
        if isinstance(stmt, ast.Delete):
            for target in stmt.targets:
                for attr, line in self._mutation_targets(target):
                    self._record_mutation(attr, line, held, nested)
            return
        # Generic statement: visit expressions and nested blocks.
        for fname in ("test", "iter", "value", "exc"):
            sub = getattr(stmt, fname, None)
            if isinstance(sub, ast.expr):
                self._visit_expr(sub, held, nested)
        for bname in ("body", "orelse", "finalbody"):
            sub = getattr(stmt, bname, None)
            if isinstance(sub, list):
                self._visit_block(sub, held, nested)
        for hname in ("handlers",):
            for handler in getattr(stmt, hname, []) or []:
                self._visit_block(handler.body, held, nested)
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            for attr, line in self._mutation_targets(stmt.target):
                self._record_mutation(attr, line, held, nested)
        for cname in ("cases",):  # match statements
            for case in getattr(stmt, cname, []) or []:
                self._visit_block(case.body, held, nested)

    def _visit_expr(self, expr: ast.AST, held: tuple, nested: bool) -> None:
        call_func_ids = set()
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                call_func_ids.add(id(node.func))
                self._visit_call(node, held, nested)
        for node in ast.walk(expr):
            # A bound-method reference that escapes as a value (thread
            # target, callback) — NOT the func position of a call.
            if (isinstance(node, ast.Attribute)
                    and isinstance(node.ctx, ast.Load)
                    and id(node) not in call_func_ids):
                attr = _self_attr(node)
                if attr is not None and attr in self.cls.methods:
                    self.cls.value_referenced.add(attr)

    def _visit_call(self, node: ast.Call, held: tuple, nested: bool) -> None:
        fn = node.func
        if isinstance(fn, ast.Attribute):
            # self._m(...) — intra-class call
            owner = _self_attr(fn.value)
            if isinstance(fn.value, ast.Name) and fn.value.id == "self":
                self.method.calls.append(CallSite(
                    "", fn.attr, node.lineno, frozenset(held)))
                return
            if owner is not None:
                # self.x.m(...) — mutator methods mutate the attribute;
                # known neighbour classes contribute cross-class edges.
                if fn.attr in _MUTATOR_METHODS:
                    self._record_mutation(owner, node.lineno, held, nested)
                target_cls = self.cls.attr_types.get(owner)
                if target_cls:
                    self.method.calls.append(CallSite(
                        target_cls, fn.attr, node.lineno, frozenset(held)))


def _discover_class(sf: SourceFile, node: ast.ClassDef,
                    class_names: set) -> ClassInfo:
    cls = ClassInfo(node.name, sf, node.lineno)
    # Pass A: lock attrs + attr types (constructor assignments and
    # annotated __init__ params), scanning every method.
    for stmt in node.body:
        if not isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        param_types = {}
        if stmt.name == "__init__":
            args = stmt.args
            for a in list(args.posonlyargs) + list(args.args) + list(
                    args.kwonlyargs):
                ann_cls = _annotation_class(a.annotation)
                if ann_cls and ann_cls in class_names:
                    param_types[a.arg] = ann_cls
        for sub in ast.walk(stmt):
            if not isinstance(sub, ast.Assign):
                continue
            for target in sub.targets:
                attr = _self_attr(target)
                if attr is None:
                    continue
                if _is_lock_ctor(sub.value):
                    cls.lock_attrs.add(attr)
                elif isinstance(sub.value, ast.Call):
                    _, ctor = _call_name(sub.value)
                    if ctor in class_names:
                        cls.attr_types.setdefault(attr, ctor)
                elif (isinstance(sub.value, ast.Name)
                      and sub.value.id in param_types):
                    cls.attr_types.setdefault(
                        attr, param_types[sub.value.id])
    for stmt in node.body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            cls.methods[stmt.name] = MethodInfo(stmt.name, stmt.lineno)
    return cls


def build_model(repo: RepoView) -> dict[str, ClassInfo]:
    """Index every class in the package and walk its methods (cached on
    the RepoView so TPU401 and TPU402 share one walk)."""
    cached = getattr(repo, "_lockcheck_model", None)
    if cached is not None:
        return cached
    classes: dict[str, ClassInfo] = {}
    class_nodes: list[tuple[SourceFile, ast.ClassDef]] = []
    for sf in repo.package_files():
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.ClassDef):
                class_nodes.append((sf, node))
    class_names = {node.name for _, node in class_nodes}
    for sf, node in class_nodes:
        info = _discover_class(sf, node, class_names)
        # First definition wins on name collisions (rare; resolution is
        # by simple name across the package).
        classes.setdefault(node.name, info)
    for sf, node in class_nodes:
        cls = classes[node.name]
        if cls.sf is not sf or cls.lineno != node.lineno:
            continue
        for stmt in node.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                walker = _MethodWalker(cls, cls.methods[stmt.name], classes)
                walker.walk(stmt.body)
    repo._lockcheck_model = classes
    return classes


# ----------------------------------------------------------------------
# Fixpoint: assumed-held on method entry (intra-class)
# ----------------------------------------------------------------------


def entry_held_sets(cls: ClassInfo) -> dict[str, frozenset]:
    """For each method, the locks provably held on EVERY entry.

    Public methods, dunders, and methods whose bound reference escapes
    as a value (thread targets, callbacks) can be entered with nothing
    held.  A private method only ever called while a lock is held
    inherits that guard: start every candidate at the full lock set and
    intersect over call sites until the fixpoint (the shared
    :func:`framework.intersect_fixpoint`).
    """
    locks = cls.lock_ids()
    entry: dict[str, frozenset] = {}
    # Methods called from inside this class.
    called_from: dict[str, list] = {m: [] for m in cls.methods}
    for m in cls.methods.values():
        for call in m.calls:
            if call.callee_class == "" and call.callee in cls.methods:
                called_from[call.callee].append((m.name, call.held))
    for name in cls.methods:
        externally_enterable = (
            not name.startswith("_")
            or name.startswith("__")
            or name in cls.value_referenced
            or not called_from[name]
        )
        entry[name] = frozenset() if externally_enterable else locks
    return intersect_fixpoint(entry, called_from)


# ----------------------------------------------------------------------
# TPU401: guarded vs unguarded mutations
# ----------------------------------------------------------------------


def guard_findings(classes: dict[str, ClassInfo]) -> list[Finding]:
    findings = []
    for cls in classes.values():
        if not cls.lock_attrs:
            continue
        entry = entry_held_sets(cls)
        # attr -> [(site, effective_held)]
        by_attr: dict[str, list] = {}
        for m in cls.methods.values():
            if m.name == "__init__":
                continue  # no concurrent access during construction
            for site in m.mutations:
                effective = site.held | (
                    frozenset() if site.in_nested_def else entry[m.name])
                by_attr.setdefault(site.attr, []).append((site, effective))
        for attr, sites in sorted(by_attr.items()):
            guards = frozenset().union(
                *(held for _, held in sites)) if sites else frozenset()
            guards = guards & cls.lock_ids()
            if not guards:
                continue  # never guarded: plain unshared state
            unguarded = [
                (site, held) for site, held in sites if not (held & guards)
            ]
            if not unguarded:
                continue
            guard_names = ", ".join(sorted(guards))
            for site, _ in sorted(unguarded, key=lambda p: p[0].line):
                findings.append(Finding(
                    cls.sf.rel, site.line, "TPU401",
                    f"attribute '{attr}' of {cls.name} mutated in "
                    f"{site.method}() without holding its inferred guard "
                    f"({guard_names}); other sites mutate it under the "
                    "lock",
                ))
    return findings


# ----------------------------------------------------------------------
# TPU402: lock-order graph + inversions
# ----------------------------------------------------------------------


def _transitive_acquires(classes: dict[str, ClassInfo]) -> dict:
    """(class, method) -> frozenset of lock ids the call may acquire,
    including through intra- and cross-class calls (the shared
    :func:`framework.union_fixpoint`)."""
    seed: dict[tuple[str, str], frozenset] = {}
    edges: dict[tuple[str, str], list] = {}
    for cls in classes.values():
        for m in cls.methods.values():
            key = (cls.name, m.name)
            seed[key] = frozenset(a.lock for a in m.acquires)
            edges[key] = [
                (call.callee_class or cls.name, call.callee)
                for call in m.calls
            ]
    return union_fixpoint(seed, edges)


def lock_order_edges(classes: dict[str, ClassInfo]) -> dict:
    """outer-lock -> {inner-lock -> (file, line) witness}."""
    acq = _transitive_acquires(classes)
    edges: dict[str, dict[str, tuple[str, int]]] = {}

    def add(outer: str, inner: str, sf: SourceFile, line: int) -> None:
        if outer == inner:
            return  # reentrancy, not ordering
        edges.setdefault(outer, {}).setdefault(inner, (sf.rel, line))

    for cls in classes.values():
        entry = entry_held_sets(cls)
        for m in cls.methods.values():
            base = entry.get(m.name, frozenset())
            for site in m.acquires:
                for outer in base | site.held:
                    add(outer, site.lock, cls.sf, site.line)
            for call in m.calls:
                held = base | call.held
                if not held:
                    continue
                target = (call.callee_class or cls.name, call.callee)
                for inner in acq.get(target, frozenset()):
                    for outer in held:
                        add(outer, inner, cls.sf, call.line)
    return edges


def find_inversions(edges: dict) -> list[dict]:
    """Unordered lock pairs acquired in both orders, with witnesses."""
    out = []
    seen = set()
    for a, inners in sorted(edges.items()):
        for b, fwd_witness in sorted(inners.items()):
            rev_witness = edges.get(b, {}).get(a)
            if rev_witness is None:
                continue
            pair = frozenset((a, b))
            if pair in seen:
                continue
            seen.add(pair)
            out.append({
                "locks": sorted(pair),
                "forward": f"{a} -> {b}",
                "forward_at": fwd_witness,
                "reverse": f"{b} -> {a}",
                "reverse_at": rev_witness,
            })
    return out


def inversion_findings(classes: dict[str, ClassInfo]) -> list[Finding]:
    edges = lock_order_edges(classes)
    findings = []
    for inv in find_inversions(edges):
        fwd_file, fwd_line = inv["forward_at"]
        rev_file, rev_line = inv["reverse_at"]
        findings.append(Finding(
            fwd_file, fwd_line, "TPU402",
            f"lock-order inversion: {inv['forward']} here but "
            f"{inv['reverse']} at {rev_file}:{rev_line} — deadlock "
            "precondition",
        ))
    return findings


@rule("TPU401", "unguarded-mutation",
      "A self attribute is mutated both under its inferred lock guard "
      "and outside any lock — the unguarded site races the guarded "
      "ones.")
def check_guarded_mutations(repo: RepoView) -> Iterable[Finding]:
    return guard_findings(build_model(repo))


@rule("TPU402", "lock-order-inversion",
      "Two locks are acquired in both orders on different paths (the "
      "deadlock precondition), across with-nesting and resolved cross-"
      "class calls.")
def check_lock_order(repo: RepoView) -> Iterable[Finding]:
    return inversion_findings(build_model(repo))
