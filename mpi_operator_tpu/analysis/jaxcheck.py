"""JAX perf-correctness rules (TPU5xx): the training-stack analog of
the control-plane lock checker.

The MLPerf TPU-v3 pod paper (arxiv 1909.09756) and the TPU concurrency
study (arxiv 2011.03641) both attribute large step-time regressions to
two silent bug classes: recompilation (a jit cache miss per step) and
host<->device synchronization (a transfer barrier inside the step
loop).  Neither crashes; both flatten throughput.  These rules catch
the AST shapes that cause them before a bench does:

**TPU501 — static-looking jit parameter.**  A ``jax.jit``-ed function
whose signature carries a Python-scalar/shape/dict-shaped parameter
(``int``/``bool``/``str``/``tuple``/``dict`` annotation or literal
default) that is not listed in ``static_argnums``/``static_argnames``.
Traced, such a value either concretizes (a TracerError at best) or
becomes a silent retrace-per-value recompile.

**TPU502 — jit under reconstruction.**  ``jax.jit(...)`` evaluated
inside a loop body, or inside a per-step closure (a ``*_step``/
``step_fn`` function): every evaluation wraps a fresh function object,
so the jit cache misses every time — the "compiles forever" failure
mode.

**TPU503 — implicit host transfer on the step path.**  Within a step
root (a ``train_step``/``eval_step``/``step_fn`` def) and every
same-module helper reachable from it (the shared
``framework.module_graph`` call-graph pass; traversal stops at jitted
boundaries, where a transfer cannot hide): ``float()``/``int()``/
``.item()``/``.tolist()``/``np.asarray()``/``print()`` on non-constant
values.  Inside jit-ed roots the check narrows to conversions applied
directly to traced parameters.  The sanctioned spelling —
``jax.device_get(...)`` at a step boundary — is recognized and exempt.

**TPU504 — donated buffer reused.**  A positional argument donated via
``donate_argnums`` is read again after the call (donation invalidates
the buffer), or is re-donated every loop iteration without being
rebound from the call's result.

**TPU505 — train step without donation.**  A train/update step jitted
without ``donate_argnums``/``donate_argnames`` carries params and
optimizer state twice in HBM (the old operand and the new result) —
the classic 2x-memory step.

**TPU506 — host sync in a hot loop.**  A loop that invokes a jitted
callable (or a ``step_fn``-shaped method) and converts device values
with ``float()``/``int()``/``.item()``/``np.asarray()`` in the same
body forces a device round-trip per iteration.

**TPU507 — pallas tile hygiene.**  Kernel entry points under ``ops/``
must take their grid/tile defaults from the shared tile-selection
plumbing in ``ops/_common.py`` (named constants + ``clamp_tile``), not
private numeric literals — the contract the admission-time kernel
autotuner will override per geometry.

Like lockcheck, every rule is a heuristic vet, not a prover; false
positives belong in the baseline workflow, not in rule silencing.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Iterable, Optional

from .framework import (
    Finding,
    RepoView,
    SourceFile,
    module_graph,
    rule,
)

# Step roots: the names the training stack gives its per-step
# callables (models' inner defs, cmd/train's workload closures).
STEP_NAME_RE = re.compile(r"^(train|eval|update|test)_step$|^step(_fn)?$")
# Train/update steps carry optimizer state and should donate it; eval
# steps deliberately excluded (donating params during eval is wrong).
TRAIN_STEP_RE = re.compile(r"^(make_)?(train|update)_step$")
STEP_FACTORY_RE = re.compile(r"^make_\w*step$")

TILE_PARAM_RE = re.compile(r"^(block|tile)_[a-z0-9]+$")
TILE_CONST_RE = re.compile(r"^(DEFAULT_)?(BLOCK|TILE)_[A-Z0-9_]+$")

_JIT_NAMES = {"jit", "pjit"}
_NP_ROOTS = {"np", "numpy", "onp"}
_STATIC_LOOKING_ANNOTATIONS = {
    "int", "bool", "str", "dict", "Dict", "tuple", "Tuple", "Sequence",
    "Shape",
}
_CONVERTERS = {"float", "int", "bool"}
_ITEM_METHODS = {"item", "tolist"}


def _callee(call: ast.Call) -> tuple[str, str]:
    """(root, name) of the callee: ``jax.jit`` -> ("jax", "jit");
    bare ``jit`` -> ("", "jit")."""
    fn = call.func
    if isinstance(fn, ast.Attribute):
        root = fn.value
        return (root.id if isinstance(root, ast.Name) else "", fn.attr)
    if isinstance(fn, ast.Name):
        return ("", fn.id)
    return ("", "")


def _is_jit_expr(expr: ast.AST) -> bool:
    """expr names jax.jit/pjit (a decorator, or a Call's .func)."""
    if isinstance(expr, ast.Attribute):
        return expr.attr in _JIT_NAMES
    if isinstance(expr, ast.Name):
        return expr.id in _JIT_NAMES
    return False


def _is_device_get(call: ast.Call) -> bool:
    _, name = _callee(call)
    return name == "device_get"


def _literal_ints(node: Optional[ast.AST]) -> tuple[frozenset, bool]:
    """(values, resolved) for an argnums literal: int or tuple/list of
    ints.  resolved=False means the value is dynamic (a variable) and
    the rule must not assume it knows the static set."""
    if node is None:
        return frozenset(), True
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return frozenset({node.value}), True
    if isinstance(node, (ast.Tuple, ast.List)):
        vals = set()
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int):
                vals.add(e.value)
            else:
                return frozenset(), False
        return frozenset(vals), True
    return frozenset(), False


def _literal_strs(node: Optional[ast.AST]) -> tuple[frozenset, bool]:
    if node is None:
        return frozenset(), True
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return frozenset({node.value}), True
    if isinstance(node, (ast.Tuple, ast.List)):
        vals = set()
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                vals.add(e.value)
            else:
                return frozenset(), False
        return frozenset(vals), True
    return frozenset(), False


@dataclass
class JitSite:
    """One jax.jit application: a call expression or a decorator."""

    lineno: int
    target: Optional[str] = None       # name the jitted callable binds to
    fn_name: Optional[str] = None      # wrapped function's simple name
    factory_name: Optional[str] = None  # jax.jit(make_x_step(...)) shape
    static_argnums: frozenset = frozenset()
    static_argnames: frozenset = frozenset()
    donate_argnums: frozenset = frozenset()
    static_resolved: bool = True
    has_static: bool = False
    has_donate: bool = False
    decorator_of: Optional[str] = None  # def name when used as decorator
    bare_decorator: bool = False        # @jax.jit (no kwargs possible)
    in_loop: bool = False
    enclosing: tuple = ()               # enclosing def names, outer first


def _parse_jit_kwargs(site: JitSite, keywords: list) -> None:
    for kw in keywords:
        if kw.arg == "static_argnums":
            site.has_static = True
            vals, ok = _literal_ints(kw.value)
            site.static_argnums |= vals
            site.static_resolved = site.static_resolved and ok
        elif kw.arg == "static_argnames":
            site.has_static = True
            vals, ok = _literal_strs(kw.value)
            site.static_argnames |= vals
            site.static_resolved = site.static_resolved and ok
        elif kw.arg in ("donate_argnums", "donate_argnames"):
            site.has_donate = True
            if kw.arg == "donate_argnums":
                vals, _ = _literal_ints(kw.value)
                site.donate_argnums |= vals


def _jit_decorator_site(dec: ast.AST, fn: ast.AST) -> Optional[JitSite]:
    """A JitSite when ``dec`` applies jax.jit to ``fn``: bare
    ``@jax.jit``, ``@partial(jax.jit, ...)``, or ``@jax.jit(...)``."""
    name = fn.name
    if _is_jit_expr(dec):
        return JitSite(dec.lineno, target=name, fn_name=name,
                       decorator_of=name, bare_decorator=True)
    if isinstance(dec, ast.Call):
        if _is_jit_expr(dec.func):
            site = JitSite(dec.lineno, target=name, fn_name=name,
                           decorator_of=name)
            _parse_jit_kwargs(site, dec.keywords)
            return site
        _, cal = _callee(dec)
        if cal == "partial" and dec.args and _is_jit_expr(dec.args[0]):
            site = JitSite(dec.lineno, target=name, fn_name=name,
                           decorator_of=name)
            _parse_jit_kwargs(site, dec.keywords)
            return site
    return None


@dataclass
class ModuleModel:
    """Everything the TPU5xx rules need to know about one module's jit
    usage, collected in a single annotated walk."""

    sf: SourceFile
    jit_sites: list = field(default_factory=list)
    jitted_defs: dict = field(default_factory=dict)  # def name -> JitSite
    bindings: dict = field(default_factory=dict)     # bound name -> JitSite


def _build_model(sf: SourceFile) -> ModuleModel:
    model = ModuleModel(sf)
    if sf.tree is None:
        return model
    parents: dict[int, ast.AST] = {}
    context: dict[int, tuple[bool, tuple]] = {}  # id -> (in_loop, defs)

    def annotate(node: ast.AST, in_loop: bool, stack: tuple) -> None:
        context[id(node)] = (in_loop, stack)
        child_loop = in_loop or isinstance(
            node, (ast.For, ast.AsyncFor, ast.While))
        child_stack = stack
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            child_stack = stack + (node.name,)
        for child in ast.iter_child_nodes(node):
            parents[id(child)] = node
            annotate(child, child_loop, child_stack)

    annotate(sf.tree, False, ())

    for node in ast.walk(sf.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            in_loop, stack = context[id(node)]
            for dec in node.decorator_list:
                site = _jit_decorator_site(dec, node)
                if site is not None:
                    site.in_loop = in_loop
                    site.enclosing = stack
                    model.jit_sites.append(site)
                    model.jitted_defs.setdefault(node.name, site)
                    model.bindings.setdefault(node.name, site)
        elif isinstance(node, ast.Call) and _is_jit_expr(node.func):
            in_loop, stack = context[id(node)]
            site = JitSite(node.lineno, in_loop=in_loop, enclosing=stack)
            if node.args:
                wrapped = node.args[0]
                if isinstance(wrapped, ast.Name):
                    site.fn_name = wrapped.id
                elif isinstance(wrapped, ast.Attribute):
                    site.fn_name = wrapped.attr
                elif isinstance(wrapped, ast.Call):
                    _, site.factory_name = _callee(wrapped)
            _parse_jit_kwargs(site, node.keywords)
            parent = parents.get(id(node))
            if isinstance(parent, ast.Assign) and parent.value is node:
                for target in parent.targets:
                    if isinstance(target, ast.Name):
                        site.target = target.id
                        model.bindings[target.id] = site
            model.jit_sites.append(site)
            if site.fn_name:
                model.jitted_defs.setdefault(site.fn_name, site)
    return model


def _model(sf: SourceFile) -> ModuleModel:
    cached = getattr(sf, "_jaxcheck_model", None)
    if cached is None:
        cached = sf._jaxcheck_model = _build_model(sf)
    return cached


# ----------------------------------------------------------------------
# Body-walk helpers shared by TPU503/504/506
# ----------------------------------------------------------------------


def _own_body_nodes(fn_node: ast.AST) -> Iterable[ast.AST]:
    """Every node in a def's own body, not descending into nested defs
    (those are separate call-graph nodes)."""
    stack = list(ast.iter_child_nodes(fn_node))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _device_get_names(fn_node: ast.AST) -> set:
    """Local names bound from jax.device_get(...) — the sanctioned
    host-transfer spelling; conversions of these are explicit."""
    names = set()
    for node in _own_body_nodes(fn_node):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            if _is_device_get(node.value):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        names.add(t.id)
                    elif isinstance(t, (ast.Tuple, ast.List)):
                        names.update(
                            e.id for e in t.elts if isinstance(e, ast.Name))
    return names


def _sanctioned(value: ast.AST, dg_names: set) -> bool:
    """value is already an explicit host copy (device_get call or a
    name bound from one) or a compile-time constant."""
    if isinstance(value, ast.Constant):
        return True
    if isinstance(value, ast.Call) and _is_device_get(value):
        return True
    if isinstance(value, ast.Name) and value.id in dg_names:
        return True
    return False


def _conversion_calls(fn_node: ast.AST, dg_names: set,
                      param_names: Optional[set] = None):
    """(call, kind) pairs for implicit host conversions in a def's own
    body.  With ``param_names`` (jit-traced mode) only conversions
    applied directly to a traced parameter count."""

    def traced(value: ast.AST) -> bool:
        if param_names is None:
            return True
        return isinstance(value, ast.Name) and value.id in param_names

    for node in _own_body_nodes(fn_node):
        if not isinstance(node, ast.Call):
            continue
        root, name = _callee(node)
        if isinstance(node.func, ast.Name) and name in _CONVERTERS:
            if node.args and not _sanctioned(node.args[0], dg_names) \
                    and traced(node.args[0]):
                yield node, f"{name}()"
        elif isinstance(node.func, ast.Attribute) and name in _ITEM_METHODS:
            recv = node.func.value
            if not _sanctioned(recv, dg_names) and traced(recv):
                yield node, f".{name}()"
        elif root in _NP_ROOTS and name in ("asarray", "array"):
            if node.args and not _sanctioned(node.args[0], dg_names) \
                    and traced(node.args[0]):
                yield node, f"{root}.{name}()"


# ----------------------------------------------------------------------
# TPU501: static-looking jit parameters
# ----------------------------------------------------------------------


def _annotation_name(node: Optional[ast.AST]) -> Optional[str]:
    if node is None:
        return None
    if isinstance(node, ast.Subscript):  # tuple[int, ...], Dict[str, int]
        return _annotation_name(node.value)
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        try:
            return _annotation_name(ast.parse(node.value, mode="eval").body)
        except SyntaxError:
            return None
    return None


def _static_looking(arg: ast.arg, default: Optional[ast.AST]) -> Optional[str]:
    ann = _annotation_name(arg.annotation)
    if ann in _STATIC_LOOKING_ANNOTATIONS:
        return f"annotation '{ann}'"
    if isinstance(default, ast.Constant) and isinstance(
            default.value, (bool, int, str)) and default.value is not None:
        return f"default {default.value!r}"
    if isinstance(default, (ast.Tuple, ast.Dict)):
        return "tuple/dict literal default"
    return None


@rule("TPU501", "jit-nonstatic-scalar",
      "A jax.jit-ed function signature carries a Python scalar/shape/"
      "dict-shaped parameter (int/bool/str/tuple/dict annotation or "
      "literal default) not listed in static_argnums/static_argnames — "
      "a retrace-per-value recompile hazard.")
def check_jit_static(repo: RepoView) -> Iterable[Finding]:
    findings = []
    for sf in repo.package_files():
        if sf.tree is None:
            continue
        model = _model(sf)
        graph = module_graph(sf)
        for name, site in sorted(model.jitted_defs.items()):
            if site.has_static and not site.static_resolved:
                continue  # dynamic static set: cannot prove anything
            candidates = graph.by_name.get(name, [])
            if not candidates:
                continue
            fn = candidates[0].node
            args = fn.args
            positional = list(args.posonlyargs) + list(args.args)
            defaults = list(args.defaults)
            # defaults align with the TAIL of the positional list.
            pad = [None] * (len(positional) - len(defaults))
            pos_defaults = pad + defaults
            offset = 0
            if positional and positional[0].arg in ("self", "cls"):
                positional = positional[1:]
                pos_defaults = pos_defaults[1:]
                offset = 1
            for i, (arg, default) in enumerate(
                    zip(positional, pos_defaults)):
                if (i + offset) in site.static_argnums:
                    continue
                if arg.arg in site.static_argnames:
                    continue
                reason = _static_looking(arg, default)
                if reason:
                    findings.append(Finding(
                        sf.rel, fn.lineno, "TPU501",
                        f"jitted {name}() parameter '{arg.arg}' looks "
                        f"static ({reason}) but is not in static_argnums"
                        f"/static_argnames — every distinct value "
                        "retraces (or concretizes a tracer)",
                    ))
            for arg, default in zip(args.kwonlyargs, args.kw_defaults):
                if arg.arg in site.static_argnames:
                    continue
                reason = _static_looking(arg, default)
                if reason:
                    findings.append(Finding(
                        sf.rel, fn.lineno, "TPU501",
                        f"jitted {name}() keyword parameter '{arg.arg}' "
                        f"looks static ({reason}) but is not in "
                        "static_argnames — every distinct value retraces "
                        "(or concretizes a tracer)",
                    ))
    return findings


# ----------------------------------------------------------------------
# TPU502: jit reconstructed per iteration / per step
# ----------------------------------------------------------------------


@rule("TPU502", "jit-in-loop",
      "jax.jit applied inside a loop body or per-step closure: each "
      "evaluation wraps a fresh function object, so the jit cache "
      "misses (recompiles) every iteration.")
def check_jit_in_loop(repo: RepoView) -> Iterable[Finding]:
    findings = []
    for sf in repo.package_files():
        if sf.tree is None:
            continue
        for site in _model(sf).jit_sites:
            what = (f"@jit decoration of {site.decorator_of}()"
                    if site.decorator_of else "jax.jit(...) call")
            if site.in_loop:
                findings.append(Finding(
                    sf.rel, site.lineno, "TPU502",
                    f"{what} inside a loop body — a fresh jitted "
                    "callable (and a recompile) every iteration; hoist "
                    "it out of the loop",
                ))
            elif any(STEP_NAME_RE.fullmatch(n) for n in site.enclosing):
                outer = next(n for n in site.enclosing
                             if STEP_NAME_RE.fullmatch(n))
                findings.append(Finding(
                    sf.rel, site.lineno, "TPU502",
                    f"{what} inside per-step function {outer}() — "
                    "re-jitted on every step; build the jitted callable "
                    "once outside the step",
                ))
    return findings


# ----------------------------------------------------------------------
# TPU503: implicit host transfers on the step path
# ----------------------------------------------------------------------


@rule("TPU503", "host-transfer-in-step",
      "float()/int()/.item()/.tolist()/np.asarray()/print() on device "
      "values inside a step function or an un-jitted helper reachable "
      "from one — an implicit device-to-host sync on the hot path.  "
      "Explicit jax.device_get(...) at a step boundary is exempt.")
def check_step_host_transfers(repo: RepoView) -> Iterable[Finding]:
    findings = []
    for sf in repo.package_files():
        if sf.tree is None:
            continue
        model = _model(sf)
        graph = module_graph(sf)
        jitted = set(model.jitted_defs)
        roots = [fn for fn in graph.functions
                 if STEP_NAME_RE.fullmatch(fn.name)]
        if not roots:
            continue
        scope = graph.reachable(
            roots, stop=lambda fn: fn.name in jitted)
        for fn in scope:
            dg_names = _device_get_names(fn.node)
            params = None
            if fn.name in jitted:
                args = fn.node.args
                params = {
                    a.arg for a in (
                        list(args.posonlyargs) + list(args.args)
                        + list(args.kwonlyargs)
                    )
                }
                site = model.jitted_defs[fn.name]
                params -= set(site.static_argnames)
            for call, kind in _conversion_calls(fn.node, dg_names, params):
                findings.append(Finding(
                    sf.rel, call.lineno, "TPU503",
                    f"implicit host transfer on the step path: {kind} "
                    f"on a device value in {fn.name}() — wrap in "
                    "jax.device_get at a step boundary or move off the "
                    "hot path",
                ))
            for node in _own_body_nodes(fn.node):
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Name)
                        and node.func.id == "print"):
                    if params is not None and not any(
                            isinstance(a, ast.Name) and a.id in params
                            for a in node.args):
                        continue
                    findings.append(Finding(
                        sf.rel, node.lineno, "TPU503",
                        f"print() in step-path function {fn.name}() "
                        "synchronizes the device per call — use "
                        "jax.debug.print or log outside the step",
                    ))
    return findings


# ----------------------------------------------------------------------
# TPU504: donated buffers read after the call
# ----------------------------------------------------------------------


def _assign_target_names(stmt: ast.AST) -> set:
    names = set()
    targets = []
    if isinstance(stmt, ast.Assign):
        targets = stmt.targets
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        targets = [stmt.target]
    for t in targets:
        for node in ast.walk(t):
            if isinstance(node, ast.Name):
                names.add(node.id)
    return names


@rule("TPU504", "donated-arg-reuse",
      "A buffer donated through donate_argnums is read again after the "
      "call (donation invalidates it), or re-donated every loop "
      "iteration without being rebound from the call's result.")
def check_donated_reuse(repo: RepoView) -> Iterable[Finding]:
    findings = []
    for sf in repo.package_files():
        if sf.tree is None:
            continue
        model = _model(sf)
        donated = {
            name: site for name, site in model.bindings.items()
            if site.donate_argnums
        }
        if not donated:
            continue
        graph = module_graph(sf)
        for fn in graph.functions:
            body = list(_own_body_nodes(fn.node))
            loads = [
                n for n in body
                if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)
            ]
            # statement context: which assignment owns each call, and
            # whether the call sits in a loop.
            for stmt in body:
                if not isinstance(stmt, (
                        ast.Assign, ast.AugAssign, ast.Expr, ast.Return)):
                    continue
                value = getattr(stmt, "value", None)
                if value is None:
                    continue
                for call in ast.walk(value):
                    if not (isinstance(call, ast.Call)
                            and isinstance(call.func, ast.Name)
                            and call.func.id in donated):
                        continue
                    site = donated[call.func.id]
                    rebinds = _assign_target_names(stmt)
                    in_loop = _stmt_in_loop(fn.node, stmt)
                    for idx in sorted(site.donate_argnums):
                        if idx >= len(call.args):
                            continue
                        arg = call.args[idx]
                        if not isinstance(arg, ast.Name):
                            continue
                        if arg.id in rebinds:
                            continue  # state = step(state): legal
                        later = [n for n in loads
                                 if n.id == arg.id
                                 and n.lineno > call.lineno]
                        if later:
                            use = min(later, key=lambda n: n.lineno)
                            findings.append(Finding(
                                sf.rel, use.lineno, "TPU504",
                                f"'{arg.id}' was donated to "
                                f"{call.func.id}() at line {call.lineno} "
                                "and is read again here — donated "
                                "buffers are invalidated by the call",
                            ))
                        elif in_loop:
                            findings.append(Finding(
                                sf.rel, call.lineno, "TPU504",
                                f"'{arg.id}' is donated to "
                                f"{call.func.id}() every loop iteration "
                                "but never rebound from its result — "
                                "the second iteration donates a dead "
                                "buffer",
                            ))
    return findings


def _stmt_in_loop(fn_node: ast.AST, stmt: ast.AST) -> bool:
    """True when stmt is lexically inside a for/while in fn's own body."""
    def search(node: ast.AST, in_loop: bool) -> Optional[bool]:
        for child in ast.iter_child_nodes(node):
            if child is stmt:
                return in_loop or isinstance(
                    node, (ast.For, ast.AsyncFor, ast.While))
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            found = search(child, in_loop or isinstance(
                child, (ast.For, ast.AsyncFor, ast.While)))
            if found is not None:
                return found
        return None
    return bool(search(fn_node, False))


# ----------------------------------------------------------------------
# TPU505: train steps without donation
# ----------------------------------------------------------------------


@rule("TPU505", "step-without-donation",
      "A train/update step is jitted without donate_argnums/"
      "donate_argnames: params and optimizer state live twice in HBM "
      "across every step (old operand + new result).")
def check_step_donation(repo: RepoView) -> Iterable[Finding]:
    findings = []
    for sf in repo.package_files():
        if sf.tree is None:
            continue
        for site in _model(sf).jit_sites:
            if site.has_donate:
                continue
            step_name = None
            if site.fn_name and TRAIN_STEP_RE.fullmatch(site.fn_name):
                step_name = site.fn_name
            elif site.factory_name and STEP_FACTORY_RE.fullmatch(
                    site.factory_name):
                step_name = f"{site.factory_name}(...)"
            if step_name is None:
                continue
            hint = (
                "use jax.jit(fn, donate_argnums=...) instead of the bare "
                "decorator" if site.bare_decorator
                else "add donate_argnums for params/opt state"
            )
            findings.append(Finding(
                sf.rel, site.lineno, "TPU505",
                f"train step {step_name} jitted without buffer "
                f"donation — params+opt state held twice in HBM; {hint}",
            ))
    return findings


# ----------------------------------------------------------------------
# TPU506: host syncs inside hot loops
# ----------------------------------------------------------------------


def _loop_is_hot(loop: ast.AST, hot_names: set) -> bool:
    for node in ast.walk(loop):
        if isinstance(node, ast.Call):
            _, name = _callee(node)
            if name in hot_names or STEP_NAME_RE.fullmatch(name or ""):
                return True
    return False


@rule("TPU506", "hot-loop-host-sync",
      "A loop drives a jitted callable and converts device values "
      "(float()/int()/.item()/np.asarray()) in the same body — one "
      "device round-trip per iteration.  Accumulate on device, or "
      "jax.device_get explicitly at the boundary.")
def check_hot_loop_sync(repo: RepoView) -> Iterable[Finding]:
    findings = []
    for sf in repo.package_files():
        if sf.tree is None:
            continue
        model = _model(sf)
        hot_names = set(model.bindings) | set(model.jitted_defs)
        graph = module_graph(sf)
        for fn in graph.functions:
            dg_names = _device_get_names(fn.node)
            for node in _own_body_nodes(fn.node):
                if not isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
                    continue
                if not _loop_is_hot(node, hot_names):
                    continue
                for call, kind in _conversion_calls(node, dg_names):
                    findings.append(Finding(
                        sf.rel, call.lineno, "TPU506",
                        f"implicit host sync in a hot loop: {kind} "
                        "while the loop drives a jitted step — one "
                        "device round-trip per iteration",
                    ))
    return findings


# ----------------------------------------------------------------------
# TPU507: pallas tile hygiene
# ----------------------------------------------------------------------


@rule("TPU507", "pallas-tile-literal",
      "An ops/ kernel takes its grid/tile size from a private numeric "
      "literal instead of the shared tile-selection plumbing in "
      "ops/_common.py — invisible to the kernel autotuner.")
def check_tile_hygiene(repo: RepoView) -> Iterable[Finding]:
    findings = []
    for sf in repo.package_files():
        if sf.tree is None:
            continue
        if not sf.rel.startswith("mpi_operator_tpu/ops/"):
            continue
        if sf.rel.endswith("_common.py"):
            continue
        for node in ast.walk(sf.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                args = node.args
                positional = list(args.posonlyargs) + list(args.args)
                defaults = list(args.defaults)
                pad = [None] * (len(positional) - len(defaults))
                pairs = list(zip(positional, pad + defaults)) + list(
                    zip(args.kwonlyargs, args.kw_defaults))
                for arg, default in pairs:
                    if not TILE_PARAM_RE.fullmatch(arg.arg):
                        continue
                    if isinstance(default, ast.Constant) and isinstance(
                            default.value, (int, float)):
                        findings.append(Finding(
                            sf.rel, node.lineno, "TPU507",
                            f"kernel {node.name}() defaults tile "
                            f"parameter '{arg.arg}' to the literal "
                            f"{default.value} — take it from "
                            "ops/_common.py so the autotuner can "
                            "override it",
                        ))
        if sf.tree is not None:
            for stmt in sf.tree.body:
                if not isinstance(stmt, ast.Assign):
                    continue
                for target in stmt.targets:
                    if (isinstance(target, ast.Name)
                            and TILE_CONST_RE.fullmatch(target.id)
                            and isinstance(stmt.value, ast.Constant)
                            and isinstance(stmt.value.value, (int, float))):
                        findings.append(Finding(
                            sf.rel, stmt.lineno, "TPU507",
                            f"module-level tile constant {target.id} "
                            "defined outside ops/_common.py — move it "
                            "into the shared tile plumbing",
                        ))
    return findings
