"""Structural OpenAPI v3 schema enforcement — the apiserver admission
analog.

A real Kubernetes apiserver enforces a CRD's structural schema on every
write: type/required/enum/bounds violations are rejected (422 Invalid),
and unknown fields are *pruned* (silently dropped) unless the schema
marks the subtree ``x-kubernetes-preserve-unknown-fields: true``
(reference counterpart: the apiserver behavior the reference relies on
for v2/crd/kubeflow.org_mpijobs.yaml's embedded pod schema).

The in-memory apiserver applies the same contract to TPUJobs via
``validate_tpujob_object`` so malformed pod templates fail at create
time, matching what the generated CRD would do on a live cluster.

Supported schema subset (everything api/v2beta1/openapi.py emits):
object/array/string/integer/number/boolean types, properties, required,
additionalProperties (schema form), items, enum, minimum/maximum,
minItems, pattern, x-kubernetes-preserve-unknown-fields,
x-kubernetes-int-or-string.
"""

from __future__ import annotations

import re
from typing import Any, List


def validate_schema(obj: Any, schema: dict, path: str = "$") -> List[str]:
    """Return schema violations (empty list = valid). Unknown fields are
    not violations — they are pruning candidates, see ``prune``."""
    errs: List[str] = []
    if schema.get("x-kubernetes-int-or-string"):
        if not isinstance(obj, (int, str)) or isinstance(obj, bool):
            errs.append(f"{path}: expected integer or string")
        return errs
    t = schema.get("type")
    if t == "object":
        if not isinstance(obj, dict):
            return [f"{path}: expected object, got {type(obj).__name__}"]
        for req in schema.get("required", []):
            if req not in obj:
                errs.append(f"{path}: missing required field {req!r}")
        props = schema.get("properties", {})
        addl = schema.get("additionalProperties")
        for key, val in obj.items():
            if key in props:
                errs += validate_schema(val, props[key], f"{path}.{key}")
            elif isinstance(addl, dict):
                errs += validate_schema(val, addl, f"{path}.{key}")
            # unknown field: pruned, not rejected (k8s structural semantics)
    elif t == "array":
        if not isinstance(obj, list):
            return [f"{path}: expected array, got {type(obj).__name__}"]
        if "minItems" in schema and len(obj) < schema["minItems"]:
            errs.append(
                f"{path}: needs at least {schema['minItems']} item(s), got {len(obj)}"
            )
        item_schema = schema.get("items")
        if item_schema:
            for i, item in enumerate(obj):
                errs += validate_schema(item, item_schema, f"{path}[{i}]")
    elif t == "integer":
        if not isinstance(obj, int) or isinstance(obj, bool):
            return [f"{path}: expected integer, got {type(obj).__name__}"]
        if "minimum" in schema and obj < schema["minimum"]:
            errs.append(f"{path}: {obj} below minimum {schema['minimum']}")
        if "maximum" in schema and obj > schema["maximum"]:
            errs.append(f"{path}: {obj} above maximum {schema['maximum']}")
    elif t == "number":
        if not isinstance(obj, (int, float)) or isinstance(obj, bool):
            return [f"{path}: expected number, got {type(obj).__name__}"]
    elif t == "boolean":
        if not isinstance(obj, bool):
            return [f"{path}: expected boolean, got {type(obj).__name__}"]
    elif t == "string":
        if not isinstance(obj, str):
            return [f"{path}: expected string, got {type(obj).__name__}"]
        if "enum" in schema and obj not in schema["enum"]:
            errs.append(f"{path}: {obj!r} not one of {schema['enum']}")
        if "pattern" in schema and not re.search(schema["pattern"], obj):
            errs.append(f"{path}: {obj!r} does not match {schema['pattern']!r}")
    return errs


def prune(obj: Any, schema: dict) -> Any:
    """Drop fields the schema does not know about (k8s structural-schema
    pruning), except under ``x-kubernetes-preserve-unknown-fields``
    subtrees. Returns a new object; the input is not mutated."""
    if schema.get("x-kubernetes-preserve-unknown-fields"):
        # Still recurse into *declared* properties (k8s does: preserve
        # applies to unknown siblings, not to typed children).
        if isinstance(obj, dict) and schema.get("properties"):
            return {
                k: (prune(v, schema["properties"][k])
                    if k in schema["properties"] else v)
                for k, v in obj.items()
            }
        return obj
    t = schema.get("type")
    if t == "object" and isinstance(obj, dict):
        props = schema.get("properties", {})
        addl = schema.get("additionalProperties")
        out = {}
        for key, val in obj.items():
            if key in props:
                out[key] = prune(val, props[key])
            elif isinstance(addl, dict):
                out[key] = prune(val, addl)
            elif addl is True or not props:
                # untyped open object ({"type": "object"} with no
                # properties): nothing to prune against
                out[key] = val
        return out
    if t == "array" and isinstance(obj, list) and schema.get("items"):
        return [prune(item, schema["items"]) for item in obj]
    return obj


_TPUJOB_SCHEMA: dict = {}
_CLUSTER_QUEUE_SCHEMA: dict = {}
_LOCAL_QUEUE_SCHEMA: dict = {}


def tpujob_openapi_schema() -> dict:
    global _TPUJOB_SCHEMA
    if not _TPUJOB_SCHEMA:
        from .v2beta1 import openapi

        _TPUJOB_SCHEMA = openapi.tpujob_schema()
    return _TPUJOB_SCHEMA


def clusterqueue_openapi_schema() -> dict:
    global _CLUSTER_QUEUE_SCHEMA
    if not _CLUSTER_QUEUE_SCHEMA:
        from .v2beta1 import openapi

        _CLUSTER_QUEUE_SCHEMA = openapi.clusterqueue_schema()
    return _CLUSTER_QUEUE_SCHEMA


def localqueue_openapi_schema() -> dict:
    global _LOCAL_QUEUE_SCHEMA
    if not _LOCAL_QUEUE_SCHEMA:
        from .v2beta1 import openapi

        _LOCAL_QUEUE_SCHEMA = openapi.localqueue_schema()
    return _LOCAL_QUEUE_SCHEMA


def admission_schema_for(resource: str):
    """(schema, admission path) for a CRD-backed resource plural, or None
    for builtins the in-memory apiserver stores schema-free."""
    if resource == "tpujobs":
        return tpujob_openapi_schema(), "tpujob"
    if resource == "clusterqueues":
        return clusterqueue_openapi_schema(), "clusterqueue"
    if resource == "localqueues":
        return localqueue_openapi_schema(), "localqueue"
    return None


def validate_tpujob_object(obj: dict) -> List[str]:
    """Admission check for a TPUJob dict against the generated CRD
    schema. Returns violations; empty list = admitted."""
    return validate_schema(obj, tpujob_openapi_schema(), path="tpujob")
