"""Field-level validation for TPUJob.

Reference analog: ValidateMPIJob,
/root/reference/v2/pkg/apis/kubeflow/validation/validation.go:46-152.
Same structure (an ErrorList of typed field errors with JSON paths), with
the MPI-specific rules swapped for TPU rules:

- worker hostname DNS-1123 check on ``<name>-worker-<replicas-1>``
  (validation.go:53-65 analog — worker pods get stable DNS identity);
- Worker spec required, replicas >= 1 and == slice hosts x numSlices
  (inverts validation.go:117-136, where Launcher was the required one);
- Launcher optional, replicas == 1 when present (validation.go:119-127);
- restartPolicy in {Never, OnFailure} (validation.go:40-44);
- runPolicy: cleanPodPolicy in {None, Running, All}, non-negative
  ttl/activeDeadline/backoff (validation.go:88-106);
- >= 1 container per template (validation.go:146-150);
- TPU block: acceleratorType/topology must resolve (replaces the
  slotsPerWorker/mpiImplementation checks, validation.go:70-84);
- no ``nvidia.com/gpu`` resources anywhere (the reference merely blanks
  NVIDIA env on the launcher, mpi_job_controller.go:202-205; we reject).
"""

from __future__ import annotations

from dataclasses import dataclass

from ..runtime.objects import is_dns1123_label
from . import topology
from .v2beta1 import constants
from .v2beta1.types import (
    CLEAN_POD_POLICY_ALL,
    CLEAN_POD_POLICY_NONE,
    CLEAN_POD_POLICY_RUNNING,
    POD_FAILURE_POLICY_ACTION_FAIL_JOB,
    POD_FAILURE_POLICY_ACTION_IGNORE,
    POD_FAILURE_POLICY_ACTION_RESTART,
    POD_FAILURE_POLICY_OP_IN,
    POD_FAILURE_POLICY_OP_NOT_IN,
    REPLICA_TYPE_LAUNCHER,
    REPLICA_TYPE_WORKER,
    RESTART_POLICY_NEVER,
    RESTART_POLICY_ON_FAILURE,
    ReplicaSpec,
    RunPolicy,
    TPUJob,
    TPUJobSpec,
)

VALID_CLEAN_POD_POLICIES = (
    CLEAN_POD_POLICY_NONE,
    CLEAN_POD_POLICY_RUNNING,
    CLEAN_POD_POLICY_ALL,
)
VALID_RESTART_POLICIES = (RESTART_POLICY_NEVER, RESTART_POLICY_ON_FAILURE)
VALID_POD_FAILURE_POLICY_ACTIONS = (
    POD_FAILURE_POLICY_ACTION_IGNORE,
    POD_FAILURE_POLICY_ACTION_RESTART,
    POD_FAILURE_POLICY_ACTION_FAIL_JOB,
)
VALID_POD_FAILURE_POLICY_OPERATORS = (
    POD_FAILURE_POLICY_OP_IN,
    POD_FAILURE_POLICY_OP_NOT_IN,
)


@dataclass(frozen=True)
class FieldError:
    """One validation error (k8s field.Error analog)."""

    type: str  # "Required" | "Invalid" | "NotSupported"
    field: str  # JSON path, e.g. "spec.tpuReplicaSpecs[Worker].replicas"
    value: object = None
    detail: str = ""

    def __str__(self) -> str:
        if self.type == "Required":
            return f"{self.field}: Required value: {self.detail}"
        if self.type == "NotSupported":
            return f"{self.field}: Unsupported value: {self.value!r}: {self.detail}"
        return f"{self.field}: Invalid value: {self.value!r}: {self.detail}"


def required(path: str, detail: str) -> FieldError:
    return FieldError("Required", path, detail=detail)


def invalid(path: str, value: object, detail: str) -> FieldError:
    return FieldError("Invalid", path, value=value, detail=detail)


def not_supported(path: str, value: object, supported) -> FieldError:
    return FieldError(
        "NotSupported", path, value=value, detail=f"supported values: {sorted(supported)}"
    )


def validate_tpujob(job: TPUJob) -> list[FieldError]:
    errs = _validate_job_name(job)
    errs += _validate_spec(job.spec, "spec")
    return errs


def _validate_job_name(job: TPUJob) -> list[FieldError]:
    # validation.go:53-65 analog: the longest generated pod hostname must be
    # a valid DNS-1123 label.
    replicas = 1
    worker = job.spec.replica_specs.get(REPLICA_TYPE_WORKER)
    if worker is not None and worker.replicas is not None and worker.replicas > 0:
        replicas = worker.replicas
    hostname = f"{job.metadata.name}-worker-{replicas - 1}"
    label_errs = is_dns1123_label(hostname)
    if label_errs:
        return [
            invalid(
                "metadata.name",
                job.metadata.name,
                f"will not be able to create pod with invalid DNS label "
                f"{hostname!r}: {'; '.join(label_errs)}",
            )
        ]
    return []


def _validate_spec(spec: TPUJobSpec, path: str) -> list[FieldError]:
    errs = _validate_replica_specs(spec, f"{path}.tpuReplicaSpecs")
    errs += _validate_tpu(spec, path)
    errs += _validate_run_policy(spec.run_policy, f"{path}.runPolicy")
    if spec.jax_distribution.coordinator_port <= 0:
        errs.append(
            required(
                f"{path}.jaxDistribution.coordinatorPort",
                "must have a coordinator port for jax.distributed rendezvous",
            )
        )
    elif not (0 < spec.jax_distribution.coordinator_port < 65536):
        errs.append(
            invalid(
                f"{path}.jaxDistribution.coordinatorPort",
                spec.jax_distribution.coordinator_port,
                "must be a valid port number",
            )
        )
    elif spec.tpu.num_slices > 1:
        # Multislice worker 0 binds three listeners: jax.distributed on
        # coordinatorPort, the gang barrier on coordinatorPort+1, and the
        # libtpu megascale coordinator on DEFAULT_MEGASCALE_PORT — a
        # collision surfaces as a bind failure or silent rendezvous hang.
        port = spec.jax_distribution.coordinator_port
        if constants.DEFAULT_MEGASCALE_PORT in (port, port + 1):
            errs.append(
                invalid(
                    f"{path}.jaxDistribution.coordinatorPort",
                    port,
                    f"coordinatorPort and coordinatorPort+1 must not collide "
                    f"with the megascale DCN port "
                    f"{constants.DEFAULT_MEGASCALE_PORT} when numSlices > 1",
                )
            )
    return errs


def _validate_tpu(spec: TPUJobSpec, spec_path: str) -> list[FieldError]:
    errs: list[FieldError] = []
    tpu = spec.tpu
    path = f"{spec_path}.tpu"
    if not tpu.accelerator_type:
        errs.append(required(f"{path}.acceleratorType", "must declare the TPU slice type"))
        return errs
    try:
        shape = topology.resolve(tpu.accelerator_type, tpu.topology)
    except topology.TopologyError as e:
        errs.append(invalid(f"{path}.acceleratorType", tpu.accelerator_type, str(e)))
        return errs
    if tpu.num_slices < 1:
        errs.append(invalid(f"{path}.numSlices", tpu.num_slices, "must be >= 1"))
        return errs
    if tpu.hot_spares < 0:
        errs.append(
            invalid(f"{path}.hotSpares", tpu.hot_spares, "must be >= 0")
        )
    worker = spec.replica_specs.get(REPLICA_TYPE_WORKER)
    if worker is not None and worker.replicas is not None:
        want = shape.num_hosts * tpu.num_slices
        if worker.replicas != want:
            errs.append(
                invalid(
                    f"{spec_path}.tpuReplicaSpecs[{REPLICA_TYPE_WORKER}].replicas",
                    worker.replicas,
                    f"slice {shape.accelerator_type} (topology {shape.topology}) "
                    f"x {tpu.num_slices} slice(s) needs exactly {want} worker(s), "
                    f"one per TPU host",
                )
            )
    return errs


def _validate_run_policy(policy: RunPolicy, path: str) -> list[FieldError]:
    # validation.go:88-106 analog.
    errs: list[FieldError] = []
    if policy.clean_pod_policy is None:
        errs.append(required(f"{path}.cleanPodPolicy", "must have clean Pod policy"))
    elif policy.clean_pod_policy not in VALID_CLEAN_POD_POLICIES:
        errs.append(
            not_supported(
                f"{path}.cleanPodPolicy", policy.clean_pod_policy, VALID_CLEAN_POD_POLICIES
            )
        )
    for name, value in (
        ("ttlSecondsAfterFinished", policy.ttl_seconds_after_finished),
        ("activeDeadlineSeconds", policy.active_deadline_seconds),
        ("backoffLimit", policy.backoff_limit),
    ):
        if value is not None and value < 0:
            errs.append(invalid(f"{path}.{name}", value, "must be greater than or equal to 0"))
    sp = policy.scheduling_policy
    if sp is not None and sp.queue:
        for detail in is_dns1123_label(sp.queue):
            errs.append(
                invalid(f"{path}.schedulingPolicy.queue", sp.queue, detail)
            )
    if policy.pod_failure_policy is not None:
        errs += _validate_pod_failure_policy(
            policy.pod_failure_policy, f"{path}.podFailurePolicy"
        )
    return errs


def _validate_pod_failure_policy(policy, path: str) -> list[FieldError]:
    # batch/v1 validation analog: every rule names a supported action and
    # exactly one requirement; In-operator exit codes must be non-zero
    # (exit 0 is success, not a failure class).
    errs: list[FieldError] = []
    if not policy.rules:
        errs.append(required(f"{path}.rules", "must declare at least one rule"))
    for i, rule in enumerate(policy.rules):
        rpath = f"{path}.rules[{i}]"
        if rule.action not in VALID_POD_FAILURE_POLICY_ACTIONS:
            errs.append(
                not_supported(
                    f"{rpath}.action", rule.action, VALID_POD_FAILURE_POLICY_ACTIONS
                )
            )
        has_codes = rule.on_exit_codes is not None
        has_conds = bool(rule.on_pod_conditions)
        if has_codes == has_conds:
            errs.append(
                invalid(
                    rpath,
                    rule.to_dict(),
                    "must specify exactly one of onExitCodes, onPodConditions",
                )
            )
        if has_codes:
            oec = rule.on_exit_codes
            if oec.operator not in VALID_POD_FAILURE_POLICY_OPERATORS:
                errs.append(
                    not_supported(
                        f"{rpath}.onExitCodes.operator",
                        oec.operator,
                        VALID_POD_FAILURE_POLICY_OPERATORS,
                    )
                )
            if not oec.values:
                errs.append(
                    required(f"{rpath}.onExitCodes.values", "must list exit codes")
                )
            elif oec.operator == POD_FAILURE_POLICY_OP_IN and 0 in oec.values:
                errs.append(
                    invalid(
                        f"{rpath}.onExitCodes.values",
                        oec.values,
                        "must not contain 0 for the In operator",
                    )
                )
        for j, pat in enumerate(rule.on_pod_conditions):
            if not pat.type and not pat.reason:
                errs.append(
                    required(
                        f"{rpath}.onPodConditions[{j}]",
                        "must set type and/or reason",
                    )
                )
            if pat.type and pat.status not in ("True", "False", "Unknown"):
                errs.append(
                    not_supported(
                        f"{rpath}.onPodConditions[{j}].status",
                        pat.status,
                        ("True", "False", "Unknown"),
                    )
                )
    return errs


def _validate_replica_specs(spec: TPUJobSpec, path: str) -> list[FieldError]:
    # validation.go:108-136 analog with Launcher/Worker requirements swapped.
    errs: list[FieldError] = []
    if not spec.replica_specs:
        errs.append(required(path, "must have replica specs"))
        return errs
    for rtype in spec.replica_specs:
        if rtype not in (REPLICA_TYPE_LAUNCHER, REPLICA_TYPE_WORKER):
            errs.append(
                not_supported(
                    f"{path}[{rtype}]",
                    rtype,
                    (REPLICA_TYPE_LAUNCHER, REPLICA_TYPE_WORKER),
                )
            )
    launcher = spec.replica_specs.get(REPLICA_TYPE_LAUNCHER)
    if launcher is not None:
        lpath = f"{path}[{REPLICA_TYPE_LAUNCHER}]"
        errs += _validate_replica_spec(launcher, lpath)
        if launcher.replicas is not None and launcher.replicas != 1:
            errs.append(invalid(f"{lpath}.replicas", launcher.replicas, "must be 1"))

    worker = spec.replica_specs.get(REPLICA_TYPE_WORKER)
    wpath = f"{path}[{REPLICA_TYPE_WORKER}]"
    if worker is None:
        errs.append(required(wpath, f"must have {REPLICA_TYPE_WORKER} replica spec"))
        return errs
    errs += _validate_replica_spec(worker, wpath)
    if worker.replicas is not None and worker.replicas <= 0:
        errs.append(
            invalid(f"{wpath}.replicas", worker.replicas, "must be greater than or equal to 1")
        )
    return errs


def _validate_replica_spec(spec: ReplicaSpec, path: str) -> list[FieldError]:
    # validation.go:138-151 analog + the GPU-resource rejection.
    errs: list[FieldError] = []
    if spec.replicas is None:
        errs.append(required(f"{path}.replicas", "must define number of replicas"))
    if spec.restart_policy not in VALID_RESTART_POLICIES:
        errs.append(
            not_supported(f"{path}.restartPolicy", spec.restart_policy, VALID_RESTART_POLICIES)
        )
    pod_spec = spec.template.get("spec") or {}
    if not (pod_spec.get("containers") or []):
        errs.append(
            required(
                f"{path}.template.spec.containers", "must define at least one container"
            )
        )
    for kind in ("containers", "initContainers", "ephemeralContainers"):
        for i, container in enumerate(pod_spec.get(kind) or []):
            for bound in ("limits", "requests"):
                resources = (container.get("resources") or {}).get(bound) or {}
                if constants.GPU_RESOURCE_NAME in resources:
                    errs.append(
                        invalid(
                            f"{path}.template.spec.{kind}[{i}].resources.{bound}",
                            constants.GPU_RESOURCE_NAME,
                            "TPUJob pods must not request GPU resources",
                        )
                    )
    return errs
