"""TPUJob API: types, defaulting, validation, and TPU topology math."""
