"""Well-known names shared by the API, controller, and launcher.

Reference analogs: v2/pkg/apis/kubeflow/v2beta1/constants.go:5-14 plus the
kubeflow-common label names and the controller's env wiring
(/root/reference/v2/pkg/controller/mpi_job_controller.go:104-205).
"""

# Operator identity.
OPERATOR_NAME = "tpu-operator"
ENV_KUBEFLOW_NAMESPACE = "KUBEFLOW_NAMESPACE"

# Default restart policies (constants.go:22-26 analog).
DEFAULT_RESTART_POLICY = "Never"
DEFAULT_LAUNCHER_RESTART_POLICY = "OnFailure"

# Labels (kubeflow-common label-name analogs, applied by
# mpi_job_controller.go:1502-1508).
OPERATOR_NAME_LABEL = "training.kubeflow.org/operator-name"
JOB_NAME_LABEL = "training.kubeflow.org/job-name"
JOB_ROLE_LABEL = "training.kubeflow.org/job-role"
REPLICA_INDEX_LABEL = "training.kubeflow.org/replica-index"

# Role label values / object-name suffixes (mpi_job_controller.go:104-112).
ROLE_LAUNCHER = "launcher"
ROLE_WORKER = "worker"
# Hot-spare standby workers (spec.tpu.hotSpares): scheduled and
# bootstrapped like workers but parked before the barrier, so a worker
# death is repaired by *promotion* (restamp env, pre-bind to the spare's
# node) instead of the full schedule→pending→bootstrap pipeline.
ROLE_SPARE = "spare"
LAUNCHER_SUFFIX = "-launcher"
WORKER_SUFFIX = "-worker"
SPARE_SUFFIX = "-spare"

# The TPU resource name requested by worker pods — the analog of the
# reference blanking nvidia.com/gpu for the launcher (:202-205, :1379-1383);
# our validation *rejects* GPU resources outright (BASELINE.md north star).
TPU_RESOURCE_NAME = "google.com/tpu"
GPU_RESOURCE_NAME = "nvidia.com/gpu"

# Env wiring for worker pods — replaces both the hostfile ConfigMap text
# (newConfigMap, mpi_job_controller.go:1106-1128) and the OMPI/I_MPI env
# blocks (:177-201):
ENV_TPU_WORKER_ID = "TPU_WORKER_ID"  # pod index, GKE-compatible
ENV_TPU_WORKER_HOSTNAMES = "TPU_WORKER_HOSTNAMES"  # comma-separated FQDNs
ENV_TPU_ACCELERATOR_TYPE = "TPU_ACCELERATOR_TYPE"
ENV_TPU_TOPOLOGY = "TPU_TOPOLOGY"
ENV_TPU_CHIPS_PER_HOST = "TPU_CHIPS_PER_HOST"
ENV_COORDINATOR_ADDRESS = "TPUJOB_COORDINATOR_ADDRESS"  # host:port of worker-0
ENV_NUM_PROCESSES = "TPUJOB_NUM_PROCESSES"
ENV_PROCESS_ID = "TPUJOB_PROCESS_ID"
ENV_JOB_NAME = "TPUJOB_NAME"
ENV_JOB_NAMESPACE = "TPUJOB_NAMESPACE"
ENV_NUM_SLICES = "TPUJOB_NUM_SLICES"
ENV_SLICE_ID = "TPUJOB_SLICE_ID"

# Chaos-injected per-worker slowdown factor (chaos SlowWorker fault →
# LocalPodRunner child env → cmd/train.py step clock): the trainer
# stretches every step's wall time by this factor, modelling a slow
# host without touching the optimization math.  Unset/1.0 = no-op.
ENV_STEP_SLOWDOWN = "TPUJOB_CHAOS_STEP_SLOWDOWN"

# Chaos-injected per-window HBM leak (chaos MemoryLeak fault →
# LocalPodRunner child env → utils/devstats.py sampler): the victim's
# *reported* bytes-in-use grows by this many bytes every telemetry
# window, driving the real MemoryPressure detector path without
# allocating anything.  Unset/0 = no-op.
ENV_MEM_LEAK_BYTES = "TPU_MEM_LEAK_BYTES"

# Chaos-injected torn checkpoint commit (chaos TornWriteChaos fault →
# LocalPodRunner child env → utils/checkpoint.AsyncCheckpointManager):
# the victim's next checkpoint write lands its step data but dies before
# the commit marker — the on-disk state a writer killed mid-commit
# leaves behind.  One-shot (the runner pops it after one injection);
# unset/0 = no-op.
ENV_TORN_WRITE = "TPUJOB_CHAOS_TORN_WRITE"

# Grace budget (seconds) the preempted final save may spend draining an
# in-flight async checkpoint write before giving up — kept under the
# pod's terminationGracePeriodSeconds so SIGKILL never lands mid-commit.
ENV_CHECKPOINT_GRACE = "TPUJOB_CHECKPOINT_GRACE_S"

# Cross-process trace propagation (W3C traceparent analog): the controller
# stamps the reconcile's (trace id, span id) into every pod it builds, and
# launcher/train adopt it on startup, so operator, launcher, and worker
# spans share one trace id end to end (utils/trace.TraceContext).
ENV_TRACE_CONTEXT = "TPU_TRACE_CONTEXT"

# Multislice (DCN) rendezvous: when numSlices > 1, libtpu's megascale
# runtime forms the cross-slice transport from these variables — the same
# contract GKE's JobSet TPU integration sets for its pods. Slice 0's host
# 0 is the megascale coordinator (distinct from the jax.distributed
# coordinator only in port); ICI stays within a slice, DCN carries the
# cross-slice collectives.
ENV_MEGASCALE_COORDINATOR_ADDRESS = "MEGASCALE_COORDINATOR_ADDRESS"
ENV_MEGASCALE_NUM_SLICES = "MEGASCALE_NUM_SLICES"
ENV_MEGASCALE_SLICE_ID = "MEGASCALE_SLICE_ID"
ENV_MEGASCALE_PORT = "MEGASCALE_PORT"

# Rendezvous defaults.
DEFAULT_COORDINATOR_PORT = 8476  # jax.distributed's conventional port
DEFAULT_MEGASCALE_PORT = 8080  # libtpu megascale's conventional port
DEFAULT_CLEAN_POD_POLICY = "None"

# Elastic restart/rejoin (BASELINE.md milestone 5): every worker pod is
# stamped with the world size its rendezvous env was rendered for.  A
# resize makes the stamp stale; unlike Elastic Horovod (which re-execs
# discover_hosts.sh without restarting, proposals/elastic-horovod.md),
# jax.distributed cannot change world size in place, so the controller
# restarts stale pods with fresh env — honest restart-and-rejoin.
WORLD_SIZE_ANNOTATION = "tpujob.kubeflow.org/world-size"

# Per-worker step heartbeat (utils/telemetry.py window records), patched
# onto the worker's own Pod by the kubelet sim (runtime/podrunner.py
# tails the pod log for ``step_heartbeat`` JSONL lines) — the kube-native
# transport the step-skew observatory (utils/stepstats.py) consumes via
# the ordinary pod informer watch.  Value: one JSON object.
STEP_HEARTBEAT_ANNOTATION = "tpujob.kubeflow.org/step-heartbeat"

# Per-worker device-memory sample (utils/devstats.py window records),
# patched onto the worker's own Pod by the kubelet sim exactly like the
# step heartbeat above — the transport the device-memory observatory
# (utils/devstats.MemoryMatrix) consumes via the pod informer watch.
# Value: one JSON object.
DEVICE_MEMORY_ANNOTATION = "tpujob.kubeflow.org/device-memory"

# Hot-spare bookkeeping.  STANDBY_ANNOTATION marks a parked spare pod
# ("true"): the scheduler's chip gauges tally standby capacity
# separately and prefer standby gangs as preemption victims.
# PROMOTED_FROM_ANNOTATION on a worker records the spare pod whose warm
# slot it took — the pod is created pre-bound to that spare's node, so
# it skips the scheduler entirely and restart downtime collapses to
# rejoin time.
STANDBY_ANNOTATION = "tpujob.kubeflow.org/standby"
PROMOTED_FROM_ANNOTATION = "tpujob.kubeflow.org/promoted-from"

# ConfigMap keys (hostfile/discover_hosts.sh analogs,
# mpi_job_controller.go:1106-1145).
CONFIG_SUFFIX = "-config"
HOSTNAMES_KEY = "hostnames"
DISCOVER_HOSTS_KEY = "discover_hosts.sh"
