"""Structural OpenAPI v3 schema for the TPUJob CRD.

controller-gen analog: the reference generates its CRD schema from Go
types (`make crd` → v2/crd/kubeflow.org_mpijobs.yaml, Makefile:148-150),
including the full core/v1 PodTemplateSpec schema (~488 KB). Here the
schema is built from these Python dicts: `hack/gen_manifests.py` wraps
them in the CRD envelope, and the in-memory apiserver enforces them at
admission (api/schema.py) the way a real apiserver enforces the CRD —
malformed pod templates are rejected at create time, not at pod-creation
time.

The pod template schema is a *trimmed but structural* subset of core/v1:
every field the operator's builders consume plus the common pod surface
(containers, env, resources, volumes, scheduling). Exotic subtrees
(probes, securityContext, affinity, volume sources) stay open via
``x-kubernetes-preserve-unknown-fields`` — present and typed as objects,
contents unvalidated, exactly how a trimmed controller-gen schema would
mark them.
"""

from __future__ import annotations

from . import types

# DNS-1123 label (container/port/volume names).
DNS1123 = r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?$"


def _str(desc: str = "", **kw) -> dict:
    d = {"type": "string"}
    if desc:
        d["description"] = desc
    d.update(kw)
    return d


def _int(desc: str = "", minimum=None, maximum=None) -> dict:
    d: dict = {"type": "integer", "format": "int32"}
    if desc:
        d["description"] = desc
    if minimum is not None:
        d["minimum"] = minimum
    if maximum is not None:
        d["maximum"] = maximum
    return d


def _bool(desc: str = "") -> dict:
    d = {"type": "boolean"}
    if desc:
        d["description"] = desc
    return d


def _str_array() -> dict:
    return {"type": "array", "items": {"type": "string"}}


def _open_object(desc: str = "") -> dict:
    d = {"type": "object", "x-kubernetes-preserve-unknown-fields": True}
    if desc:
        d["description"] = desc
    return d


def _string_map(desc: str = "") -> dict:
    d = {"type": "object", "additionalProperties": {"type": "string"}}
    if desc:
        d["description"] = desc
    return d


def quantity_map(desc: str = "") -> dict:
    """resources.limits / resources.requests: quantities are int-or-string
    (\"2\", \"500m\", \"1Gi\", 4)."""
    d = {
        "type": "object",
        "additionalProperties": {"x-kubernetes-int-or-string": True},
    }
    if desc:
        d["description"] = desc
    return d


def container_schema() -> dict:
    return {
        "type": "object",
        "required": ["name"],
        "properties": {
            "name": _str("Container name (DNS label).", pattern=DNS1123),
            "image": _str("Container image."),
            "command": _str_array(),
            "args": _str_array(),
            "workingDir": _str(),
            "imagePullPolicy": _str(
                enum=["Always", "IfNotPresent", "Never"]
            ),
            "env": {
                "type": "array",
                "items": {
                    "type": "object",
                    "required": ["name"],
                    "properties": {
                        "name": _str("Environment variable name."),
                        "value": _str(),
                        "valueFrom": _open_object(
                            "fieldRef / secretKeyRef / configMapKeyRef source."
                        ),
                    },
                },
            },
            "envFrom": {
                "type": "array",
                "items": _open_object("configMapRef / secretRef bulk import."),
            },
            "ports": {
                "type": "array",
                "items": {
                    "type": "object",
                    "required": ["containerPort"],
                    "properties": {
                        "name": _str(pattern=DNS1123),
                        "containerPort": _int(minimum=1, maximum=65535),
                        "hostPort": _int(minimum=1, maximum=65535),
                        "protocol": _str(enum=["TCP", "UDP", "SCTP"]),
                    },
                },
            },
            "resources": {
                "type": "object",
                "description": (
                    "Compute resources; google.com/tpu limits are injected "
                    "by the operator when absent."
                ),
                "properties": {
                    "limits": quantity_map(),
                    "requests": quantity_map(),
                },
            },
            "volumeMounts": {
                "type": "array",
                "items": {
                    "type": "object",
                    "required": ["name", "mountPath"],
                    "properties": {
                        "name": _str(),
                        "mountPath": _str(),
                        "subPath": _str(),
                        "readOnly": _bool(),
                    },
                },
            },
            "securityContext": _open_object(),
            "lifecycle": _open_object(),
            "livenessProbe": _open_object(),
            "readinessProbe": _open_object(),
            "startupProbe": _open_object(),
            "terminationMessagePath": _str(),
            "terminationMessagePolicy": _str(
                enum=["File", "FallbackToLogsOnError"]
            ),
            "stdin": _bool(),
            "tty": _bool(),
        },
    }


def pod_template_schema() -> dict:
    """Trimmed core/v1 PodTemplateSpec (reference embeds the full schema,
    v2/crd/kubeflow.org_mpijobs.yaml; this keeps the fields that matter
    structural and leaves exotic subtrees open)."""
    return {
        "type": "object",
        "description": "core/v1 PodTemplateSpec for the replica pods.",
        "properties": {
            "metadata": {
                "type": "object",
                "properties": {
                    "labels": _string_map(),
                    "annotations": _string_map(),
                    "name": _str(),
                    "namespace": _str(),
                },
            },
            "spec": {
                "type": "object",
                "required": ["containers"],
                "properties": {
                    "containers": {
                        "type": "array",
                        "minItems": 1,
                        "items": container_schema(),
                    },
                    "initContainers": {
                        "type": "array",
                        "items": container_schema(),
                    },
                    "volumes": {
                        "type": "array",
                        "items": {
                            # name is structural; the volume *source* union
                            # (30+ types in core/v1) stays open.
                            "type": "object",
                            "required": ["name"],
                            "properties": {"name": _str(pattern=DNS1123)},
                            "x-kubernetes-preserve-unknown-fields": True,
                        },
                    },
                    "nodeSelector": _string_map(),
                    "tolerations": {
                        "type": "array",
                        "items": {
                            "type": "object",
                            "properties": {
                                "key": _str(),
                                "operator": _str(enum=["Exists", "Equal"]),
                                "value": _str(),
                                "effect": _str(
                                    enum=[
                                        "NoSchedule",
                                        "PreferNoSchedule",
                                        "NoExecute",
                                    ]
                                ),
                                "tolerationSeconds": {
                                    "type": "integer",
                                    "format": "int64",
                                },
                            },
                        },
                    },
                    "affinity": _open_object(),
                    "topologySpreadConstraints": {
                        "type": "array",
                        "items": _open_object(),
                    },
                    "schedulerName": _str(),
                    "priorityClassName": _str(),
                    "serviceAccountName": _str(),
                    "automountServiceAccountToken": _bool(),
                    "restartPolicy": _str(
                        "Pod-level restart policy; the operator derives it "
                        "from the ReplicaSpec when unset.",
                        enum=["Always", "OnFailure", "Never"],
                    ),
                    "terminationGracePeriodSeconds": {
                        "type": "integer",
                        "format": "int64",
                        "minimum": 0,
                    },
                    "activeDeadlineSeconds": {
                        "type": "integer",
                        "format": "int64",
                        "minimum": 1,
                    },
                    "hostNetwork": _bool(),
                    "hostPID": _bool(),
                    "hostIPC": _bool(),
                    "dnsPolicy": _str(
                        enum=[
                            "ClusterFirst",
                            "ClusterFirstWithHostNet",
                            "Default",
                            "None",
                        ]
                    ),
                    "securityContext": _open_object(),
                    "imagePullSecrets": {
                        "type": "array",
                        "items": {
                            "type": "object",
                            "properties": {"name": _str()},
                        },
                    },
                    "subdomain": _str(),
                    "hostname": _str(),
                },
            },
        },
    }


def replica_spec_schema(role: str) -> dict:
    return {
        "type": "object",
        "description": f"{role} replica group.",
        "properties": {
            "replicas": _int(
                "Number of replicas. For Worker this is normally derived "
                "from spec.tpu and may be omitted.",
                minimum=0,
            ),
            "restartPolicy": _str(
                "Restart policy for replica pods.",
                enum=[types.RESTART_POLICY_NEVER, types.RESTART_POLICY_ON_FAILURE],
            ),
            "template": pod_template_schema(),
        },
    }


def job_spec_schema() -> dict:
    return {
        "type": "object",
        "required": ["tpuReplicaSpecs"],
        "properties": {
            "tpu": {
                "type": "object",
                "description": (
                    "The TPU slice shape this job trains on. Worker count and "
                    "chips-per-pod are derived from acceleratorType/topology."
                ),
                "properties": {
                    "acceleratorType": _str(
                        "TPU slice type, <generation>-<chips>, e.g. v5e-16.",
                        pattern=r"^v[0-9]+[a-z]*-[0-9]+$",
                    ),
                    "topology": _str(
                        "Optional explicit chip topology, e.g. 4x4 or 2x2x4.",
                        pattern=r"^[0-9]+(x[0-9]+)*$",
                    ),
                    "numSlices": _int(
                        "Number of pod slices (>1 = multislice over DCN).",
                        minimum=1,
                    ),
                    "runtimeVersion": _str("TPU VM runtime version label."),
                },
            },
            "jaxDistribution": {
                "type": "object",
                "description": (
                    "Rendezvous wiring for jax.distributed.initialize. "
                    "Replaces the reference operator's SSH bootstrap: the only "
                    "shared state is worker-0's coordinator address."
                ),
                "properties": {
                    "coordinatorPort": _int(
                        "Coordinator port on worker 0.", minimum=1, maximum=65535
                    ),
                    "heartbeatTimeoutSeconds": _int(
                        "jax.distributed heartbeat timeout.", minimum=1
                    ),
                },
            },
            "runPolicy": {
                "type": "object",
                "description": "Policies for job lifetime and cleanup.",
                "properties": {
                    "cleanPodPolicy": _str(
                        "Which worker pods to delete once the job finishes.",
                        enum=[
                            types.CLEAN_POD_POLICY_NONE,
                            types.CLEAN_POD_POLICY_RUNNING,
                            types.CLEAN_POD_POLICY_ALL,
                        ],
                    ),
                    "ttlSecondsAfterFinished": _int(minimum=0),
                    "activeDeadlineSeconds": _int(minimum=0),
                    "backoffLimit": _int(minimum=0),
                    "suspend": {
                        "type": "boolean",
                        "description": "Suspend gates worker/launcher creation.",
                    },
                    "schedulingPolicy": {
                        "type": "object",
                        "properties": {
                            "minAvailable": _int(minimum=0),
                            "queue": _str(),
                            "priorityClass": _str(),
                        },
                    },
                },
            },
            "tpuReplicaSpecs": {
                "type": "object",
                "required": [types.REPLICA_TYPE_WORKER],
                "properties": {
                    types.REPLICA_TYPE_LAUNCHER: replica_spec_schema("Launcher"),
                    types.REPLICA_TYPE_WORKER: replica_spec_schema("Worker"),
                },
            },
        },
    }


def job_status_schema() -> dict:
    return {
        "type": "object",
        "properties": {
            "conditions": {
                "type": "array",
                "items": {
                    "type": "object",
                    "required": ["type", "status"],
                    "properties": {
                        "type": _str(
                            enum=[
                                types.JOB_CREATED,
                                types.JOB_RUNNING,
                                types.JOB_RESTARTING,
                                types.JOB_SUSPENDED,
                                types.JOB_SUCCEEDED,
                                types.JOB_FAILED,
                            ]
                        ),
                        "status": _str(enum=["True", "False", "Unknown"]),
                        "reason": _str(),
                        "message": _str(),
                        "lastUpdateTime": {"type": "number"},
                        "lastTransitionTime": {"type": "number"},
                    },
                },
            },
            "replicaStatuses": {
                "type": "object",
                "additionalProperties": {
                    "type": "object",
                    "properties": {
                        "active": _int(minimum=0),
                        "succeeded": _int(minimum=0),
                        "failed": _int(minimum=0),
                        "restarts": _int(minimum=0),
                    },
                },
            },
            "startTime": {"type": "number"},
            "completionTime": {"type": "number"},
            "lastReconcileTime": {"type": "number"},
        },
    }


def tpujob_schema() -> dict:
    """The complete openAPIV3Schema for the TPUJob CRD version entry."""
    return {
        "type": "object",
        "properties": {
            "apiVersion": _str(),
            "kind": _str(),
            "metadata": {"type": "object"},
            "spec": job_spec_schema(),
            "status": job_status_schema(),
        },
    }
