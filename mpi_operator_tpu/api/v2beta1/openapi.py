"""Structural OpenAPI v3 schema for the TPUJob CRD.

controller-gen analog: the reference generates its CRD schema from Go
types (`make crd` → v2/crd/kubeflow.org_mpijobs.yaml, Makefile:148-150),
including the full core/v1 PodTemplateSpec schema (~488 KB). Here the
schema is built from these Python dicts: `hack/gen_manifests.py` wraps
them in the CRD envelope, and the in-memory apiserver enforces them at
admission (api/schema.py) the way a real apiserver enforces the CRD —
malformed pod templates are rejected at create time, not at pod-creation
time.

The pod template schema is a *structural* subset of core/v1: every
field the operator's builders consume plus the common pod surface —
containers (env valueFrom/envFrom, probes, lifecycle, securityContext),
volumes with their typed source union, affinity/topology-spread
scheduling. Only genuinely unbounded maps (volumeAttributes,
nodeSelector, labels) stay as additionalProperties string maps; nothing
under ``containers`` is ``x-kubernetes-preserve-unknown-fields``
anymore — malformed probes and volume sources are rejected at
admission, matching the reference's full controller-gen schema
(v2/crd/kubeflow.org_mpijobs.yaml).
"""

from __future__ import annotations

from . import types

# DNS-1123 label (container/port/volume names).
DNS1123 = r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?$"


def _str(desc: str = "", **kw) -> dict:
    d = {"type": "string"}
    if desc:
        d["description"] = desc
    d.update(kw)
    return d


def _int(desc: str = "", minimum=None, maximum=None) -> dict:
    d: dict = {"type": "integer", "format": "int32"}
    if desc:
        d["description"] = desc
    if minimum is not None:
        d["minimum"] = minimum
    if maximum is not None:
        d["maximum"] = maximum
    return d


def _bool(desc: str = "") -> dict:
    d = {"type": "boolean"}
    if desc:
        d["description"] = desc
    return d


def _str_array() -> dict:
    return {"type": "array", "items": {"type": "string"}}


def _string_map(desc: str = "") -> dict:
    d = {"type": "object", "additionalProperties": {"type": "string"}}
    if desc:
        d["description"] = desc
    return d


def quantity_map(desc: str = "") -> dict:
    """resources.limits / resources.requests: quantities are int-or-string
    (\"2\", \"500m\", \"1Gi\", 4)."""
    d = {
        "type": "object",
        "additionalProperties": {"x-kubernetes-int-or-string": True},
    }
    if desc:
        d["description"] = desc
    return d


def _int_or_string(desc: str = "") -> dict:
    d: dict = {"x-kubernetes-int-or-string": True}
    if desc:
        d["description"] = desc
    return d


def _int64(desc: str = "", minimum=None) -> dict:
    d: dict = {"type": "integer", "format": "int64"}
    if desc:
        d["description"] = desc
    if minimum is not None:
        d["minimum"] = minimum
    return d


def _name_optional_ref(desc: str = "") -> dict:
    """LocalObjectReference + optional (configMapRef/secretRef shape)."""
    d = {
        "type": "object",
        "properties": {"name": _str(), "optional": _bool()},
    }
    if desc:
        d["description"] = desc
    return d


def _key_selector(desc: str) -> dict:
    """configMapKeyRef / secretKeyRef: one key of a named object."""
    return {
        "type": "object",
        "description": desc,
        "required": ["key"],
        "properties": {
            "key": _str(),
            "name": _str(),
            "optional": _bool(),
        },
    }


def env_value_from_schema() -> dict:
    return {
        "type": "object",
        "description": "Source for the env var's value (exactly one).",
        "properties": {
            "fieldRef": {
                "type": "object",
                "required": ["fieldPath"],
                "properties": {
                    "apiVersion": _str(),
                    "fieldPath": _str("Pod field path, e.g. status.podIP."),
                },
            },
            "resourceFieldRef": {
                "type": "object",
                "required": ["resource"],
                "properties": {
                    "containerName": _str(),
                    "divisor": _int_or_string(),
                    "resource": _str(),
                },
            },
            "configMapKeyRef": _key_selector("A key of a ConfigMap."),
            "secretKeyRef": _key_selector("A key of a Secret."),
        },
    }


def env_from_source_schema() -> dict:
    return {
        "type": "object",
        "description": "Bulk env import from a ConfigMap or Secret.",
        "properties": {
            "prefix": _str("Prepended to every imported key."),
            "configMapRef": _name_optional_ref(),
            "secretRef": _name_optional_ref(),
        },
    }


def _probe_handler_properties() -> dict:
    """The action union shared by probes and lifecycle hooks."""
    return {
        "exec": {
            "type": "object",
            "properties": {"command": _str_array()},
        },
        "httpGet": {
            "type": "object",
            "required": ["port"],
            "properties": {
                "host": _str(),
                "httpHeaders": {
                    "type": "array",
                    "items": {
                        "type": "object",
                        "required": ["name", "value"],
                        "properties": {
                            "name": _str(),
                            "value": _str(),
                        },
                    },
                },
                "path": _str(),
                "port": _int_or_string(),
                "scheme": _str(enum=["HTTP", "HTTPS"]),
            },
        },
        "tcpSocket": {
            "type": "object",
            "required": ["port"],
            "properties": {
                "host": _str(),
                "port": _int_or_string(),
            },
        },
    }


def probe_schema(desc: str) -> dict:
    return {
        "type": "object",
        "description": desc,
        "properties": {
            **_probe_handler_properties(),
            "grpc": {
                "type": "object",
                "required": ["port"],
                "properties": {
                    "port": _int(minimum=1, maximum=65535),
                    "service": _str(),
                },
            },
            "initialDelaySeconds": _int(),
            "periodSeconds": _int(),
            "timeoutSeconds": _int(),
            "successThreshold": _int(),
            "failureThreshold": _int(),
            "terminationGracePeriodSeconds": _int64(minimum=1),
        },
    }


def lifecycle_schema() -> dict:
    handler = {
        "type": "object",
        "properties": {
            **_probe_handler_properties(),
            "sleep": {
                "type": "object",
                "required": ["seconds"],
                "properties": {"seconds": _int64()},
            },
        },
    }
    return {
        "type": "object",
        "description": "postStart/preStop hooks.",
        "properties": {"postStart": handler, "preStop": handler},
    }


def _se_linux_options() -> dict:
    return {
        "type": "object",
        "properties": {
            "level": _str(), "role": _str(),
            "type": _str(), "user": _str(),
        },
    }


def _typed_profile() -> dict:
    """seccompProfile and appArmorProfile share this exact shape."""
    return {
        "type": "object",
        "required": ["type"],
        "properties": {
            "localhostProfile": _str(),
            "type": _str(enum=["Localhost", "RuntimeDefault",
                               "Unconfined"]),
        },
    }


_seccomp_profile = _typed_profile
_app_armor_profile = _typed_profile


def _windows_options() -> dict:
    return {
        "type": "object",
        "properties": {
            "gmsaCredentialSpec": _str(),
            "gmsaCredentialSpecName": _str(),
            "hostProcess": _bool(),
            "runAsUserName": _str(),
        },
    }


def container_security_context_schema() -> dict:
    return {
        "type": "object",
        "description": "Container-level security attributes.",
        "properties": {
            "allowPrivilegeEscalation": _bool(),
            "appArmorProfile": _app_armor_profile(),
            "capabilities": {
                "type": "object",
                "properties": {
                    "add": _str_array(),
                    "drop": _str_array(),
                },
            },
            "privileged": _bool(),
            "procMount": _str(),
            "readOnlyRootFilesystem": _bool(),
            "runAsGroup": _int64(),
            "runAsNonRoot": _bool(),
            "runAsUser": _int64(),
            "seLinuxOptions": _se_linux_options(),
            "seccompProfile": _seccomp_profile(),
            "windowsOptions": _windows_options(),
        },
    }


def pod_security_context_schema() -> dict:
    return {
        "type": "object",
        "description": "Pod-level security attributes.",
        "properties": {
            "appArmorProfile": _app_armor_profile(),
            "fsGroup": _int64(),
            "fsGroupChangePolicy": _str(
                enum=["Always", "OnRootMismatch"]
            ),
            "runAsGroup": _int64(),
            "runAsNonRoot": _bool(),
            "runAsUser": _int64(),
            "seLinuxOptions": _se_linux_options(),
            "seccompProfile": _seccomp_profile(),
            "supplementalGroups": {
                "type": "array",
                "items": _int64(),
            },
            "sysctls": {
                "type": "array",
                "items": {
                    "type": "object",
                    "required": ["name", "value"],
                    "properties": {"name": _str(), "value": _str()},
                },
            },
            "windowsOptions": _windows_options(),
        },
    }


def label_selector_schema() -> dict:
    return {
        "type": "object",
        "properties": {
            "matchLabels": _string_map(),
            "matchExpressions": {
                "type": "array",
                "items": {
                    "type": "object",
                    "required": ["key", "operator"],
                    "properties": {
                        "key": _str(),
                        "operator": _str(
                            enum=["In", "NotIn", "Exists", "DoesNotExist"]
                        ),
                        "values": _str_array(),
                    },
                },
            },
        },
    }


def _node_selector_term() -> dict:
    requirement = {
        "type": "object",
        "required": ["key", "operator"],
        "properties": {
            "key": _str(),
            "operator": _str(
                enum=["In", "NotIn", "Exists", "DoesNotExist", "Gt", "Lt"]
            ),
            "values": _str_array(),
        },
    }
    return {
        "type": "object",
        "properties": {
            "matchExpressions": {"type": "array", "items": requirement},
            "matchFields": {"type": "array", "items": requirement},
        },
    }


def _pod_affinity_term() -> dict:
    return {
        "type": "object",
        "required": ["topologyKey"],
        "properties": {
            "labelSelector": label_selector_schema(),
            "matchLabelKeys": _str_array(),
            "mismatchLabelKeys": _str_array(),
            "namespaceSelector": label_selector_schema(),
            "namespaces": _str_array(),
            "topologyKey": _str(),
        },
    }


def _pod_affinity_group() -> dict:
    """podAffinity / podAntiAffinity share this shape."""
    return {
        "type": "object",
        "properties": {
            "requiredDuringSchedulingIgnoredDuringExecution": {
                "type": "array",
                "items": _pod_affinity_term(),
            },
            "preferredDuringSchedulingIgnoredDuringExecution": {
                "type": "array",
                "items": {
                    "type": "object",
                    "required": ["podAffinityTerm", "weight"],
                    "properties": {
                        "podAffinityTerm": _pod_affinity_term(),
                        "weight": _int(minimum=1, maximum=100),
                    },
                },
            },
        },
    }


def affinity_schema() -> dict:
    return {
        "type": "object",
        "description": "node/pod (anti-)affinity scheduling constraints.",
        "properties": {
            "nodeAffinity": {
                "type": "object",
                "properties": {
                    "requiredDuringSchedulingIgnoredDuringExecution": {
                        "type": "object",
                        "required": ["nodeSelectorTerms"],
                        "properties": {
                            "nodeSelectorTerms": {
                                "type": "array",
                                "items": _node_selector_term(),
                            },
                        },
                    },
                    "preferredDuringSchedulingIgnoredDuringExecution": {
                        "type": "array",
                        "items": {
                            "type": "object",
                            "required": ["preference", "weight"],
                            "properties": {
                                "preference": _node_selector_term(),
                                "weight": _int(minimum=1, maximum=100),
                            },
                        },
                    },
                },
            },
            "podAffinity": _pod_affinity_group(),
            "podAntiAffinity": _pod_affinity_group(),
        },
    }


def topology_spread_constraint_schema() -> dict:
    return {
        "type": "object",
        "required": ["maxSkew", "topologyKey", "whenUnsatisfiable"],
        "properties": {
            "labelSelector": label_selector_schema(),
            "matchLabelKeys": _str_array(),
            "maxSkew": _int(minimum=1),
            "minDomains": _int(minimum=0),
            "nodeAffinityPolicy": _str(enum=["Honor", "Ignore"]),
            "nodeTaintsPolicy": _str(enum=["Honor", "Ignore"]),
            "topologyKey": _str(),
            "whenUnsatisfiable": _str(
                enum=["DoNotSchedule", "ScheduleAnyway"]
            ),
        },
    }


def _key_path_items() -> dict:
    """configMap/secret volume item projections."""
    return {
        "type": "array",
        "items": {
            "type": "object",
            "required": ["key", "path"],
            "properties": {
                "key": _str(),
                "mode": _int(),
                "path": _str(),
            },
        },
    }


def _downward_api_items() -> dict:
    return {
        "type": "array",
        "items": {
            "type": "object",
            "required": ["path"],
            "properties": {
                "fieldRef": {
                    "type": "object",
                    "required": ["fieldPath"],
                    "properties": {
                        "apiVersion": _str(),
                        "fieldPath": _str(),
                    },
                },
                "mode": _int(),
                "path": _str(),
                "resourceFieldRef": {
                    "type": "object",
                    "required": ["resource"],
                    "properties": {
                        "containerName": _str(),
                        "divisor": _int_or_string(),
                        "resource": _str(),
                    },
                },
            },
        },
    }


def _obj(required=None, **props) -> dict:
    """Compact object-schema builder for the legacy volume sources."""
    d: dict = {"type": "object", "properties": props}
    if required:
        d["required"] = list(required)
    return d


def _secret_ref() -> dict:
    return _obj(name=_str())


def _legacy_volume_sources() -> dict:
    """The remaining core/v1 volume sources. Mostly superseded by CSI,
    but prune semantics mean an OMITTED source would be silently
    stripped from stored objects (not rejected) — so every core/v1
    member must stay representable, like the reference's full
    controller-gen schema."""
    return {
        "awsElasticBlockStore": _obj(
            ["volumeID"], fsType=_str(), partition=_int(),
            readOnly=_bool(), volumeID=_str(),
        ),
        "azureDisk": _obj(
            ["diskName", "diskURI"], cachingMode=_str(), diskName=_str(),
            diskURI=_str(), fsType=_str(), kind=_str(), readOnly=_bool(),
        ),
        "azureFile": _obj(
            ["secretName", "shareName"], readOnly=_bool(),
            secretName=_str(), shareName=_str(),
        ),
        "cephfs": _obj(
            ["monitors"], monitors=_str_array(), path=_str(),
            readOnly=_bool(), secretFile=_str(), secretRef=_secret_ref(),
            user=_str(),
        ),
        "cinder": _obj(
            ["volumeID"], fsType=_str(), readOnly=_bool(),
            secretRef=_secret_ref(), volumeID=_str(),
        ),
        "fc": _obj(
            None, fsType=_str(), lun=_int(), readOnly=_bool(),
            targetWWNs=_str_array(), wwids=_str_array(),
        ),
        "flexVolume": _obj(
            ["driver"], driver=_str(), fsType=_str(),
            options=_string_map(), readOnly=_bool(),
            secretRef=_secret_ref(),
        ),
        "flocker": _obj(None, datasetName=_str(), datasetUUID=_str()),
        "gcePersistentDisk": _obj(
            ["pdName"], fsType=_str(), partition=_int(), pdName=_str(),
            readOnly=_bool(),
        ),
        "gitRepo": _obj(
            ["repository"], directory=_str(), repository=_str(),
            revision=_str(),
        ),
        "glusterfs": _obj(
            ["endpoints", "path"], endpoints=_str(), path=_str(),
            readOnly=_bool(),
        ),
        "image": _obj(None, pullPolicy=_str(), reference=_str()),
        "iscsi": _obj(
            ["iqn", "lun", "targetPortal"], chapAuthDiscovery=_bool(),
            chapAuthSession=_bool(), fsType=_str(), initiatorName=_str(),
            iqn=_str(), iscsiInterface=_str(), lun=_int(),
            portals=_str_array(), readOnly=_bool(),
            secretRef=_secret_ref(), targetPortal=_str(),
        ),
        "photonPersistentDisk": _obj(["pdID"], fsType=_str(), pdID=_str()),
        "portworxVolume": _obj(
            ["volumeID"], fsType=_str(), readOnly=_bool(), volumeID=_str(),
        ),
        "quobyte": _obj(
            ["registry", "volume"], group=_str(), readOnly=_bool(),
            registry=_str(), tenant=_str(), user=_str(), volume=_str(),
        ),
        "rbd": _obj(
            ["image", "monitors"], fsType=_str(), image=_str(),
            keyring=_str(), monitors=_str_array(), pool=_str(),
            readOnly=_bool(), secretRef=_secret_ref(), user=_str(),
        ),
        "scaleIO": _obj(
            ["gateway", "secretRef", "system"], fsType=_str(),
            gateway=_str(), protectionDomain=_str(), readOnly=_bool(),
            secretRef=_secret_ref(), sslEnabled=_bool(),
            storageMode=_str(), storagePool=_str(), system=_str(),
            volumeName=_str(),
        ),
        "storageos": _obj(
            None, fsType=_str(), readOnly=_bool(), secretRef=_secret_ref(),
            volumeName=_str(), volumeNamespace=_str(),
        ),
        "vsphereVolume": _obj(
            ["volumePath"], fsType=_str(), storagePolicyID=_str(),
            storagePolicyName=_str(), volumePath=_str(),
        ),
    }


def volume_schema() -> dict:
    """The complete core/v1 volume-source union, typed. The common TPU
    sources (datasets, checkpoints, tokens, scratch) are spelled out
    first; the legacy pre-CSI sources follow so that nothing a user's
    template legally carries gets pruned away."""
    return {
        "type": "object",
        "required": ["name"],
        "properties": {
            "name": _str(pattern=DNS1123),
            "configMap": {
                "type": "object",
                "properties": {
                    "defaultMode": _int(),
                    "items": _key_path_items(),
                    "name": _str(),
                    "optional": _bool(),
                },
            },
            "secret": {
                "type": "object",
                "properties": {
                    "defaultMode": _int(),
                    "items": _key_path_items(),
                    "optional": _bool(),
                    "secretName": _str(),
                },
            },
            "emptyDir": {
                "type": "object",
                "properties": {
                    "medium": _str(),
                    "sizeLimit": _int_or_string(),
                },
            },
            "hostPath": {
                "type": "object",
                "required": ["path"],
                "properties": {
                    "path": _str(),
                    "type": _str(),
                },
            },
            "persistentVolumeClaim": {
                "type": "object",
                "required": ["claimName"],
                "properties": {
                    "claimName": _str(),
                    "readOnly": _bool(),
                },
            },
            "nfs": {
                "type": "object",
                "required": ["path", "server"],
                "properties": {
                    "path": _str(),
                    "readOnly": _bool(),
                    "server": _str(),
                },
            },
            "csi": {
                "type": "object",
                "required": ["driver"],
                "properties": {
                    "driver": _str(),
                    "fsType": _str(),
                    "nodePublishSecretRef": {
                        "type": "object",
                        "properties": {"name": _str()},
                    },
                    "readOnly": _bool(),
                    # Driver-defined: a genuinely unbounded string map.
                    "volumeAttributes": _string_map(),
                },
            },
            "downwardAPI": {
                "type": "object",
                "properties": {
                    "defaultMode": _int(),
                    "items": _downward_api_items(),
                },
            },
            "projected": {
                "type": "object",
                "properties": {
                    "defaultMode": _int(),
                    "sources": {
                        "type": "array",
                        "items": {
                            "type": "object",
                            "properties": {
                                "configMap": {
                                    "type": "object",
                                    "properties": {
                                        "items": _key_path_items(),
                                        "name": _str(),
                                        "optional": _bool(),
                                    },
                                },
                                "downwardAPI": {
                                    "type": "object",
                                    "properties": {
                                        "items": _downward_api_items(),
                                    },
                                },
                                "secret": {
                                    "type": "object",
                                    "properties": {
                                        "items": _key_path_items(),
                                        "name": _str(),
                                        "optional": _bool(),
                                    },
                                },
                                "serviceAccountToken": {
                                    "type": "object",
                                    "required": ["path"],
                                    "properties": {
                                        "audience": _str(),
                                        "expirationSeconds": _int64(
                                            minimum=600
                                        ),
                                        "path": _str(),
                                    },
                                },
                            },
                        },
                    },
                },
            },
            "ephemeral": {
                "type": "object",
                "properties": {
                    "volumeClaimTemplate": {
                        "type": "object",
                        "required": ["spec"],
                        "properties": {
                            "metadata": {
                                "type": "object",
                                "properties": {
                                    "labels": _string_map(),
                                    "annotations": _string_map(),
                                },
                            },
                            "spec": {
                                "type": "object",
                                "properties": {
                                    "accessModes": _str_array(),
                                    "dataSource": _obj(
                                        ["kind", "name"],
                                        apiGroup=_str(), kind=_str(),
                                        name=_str(),
                                    ),
                                    "dataSourceRef": _obj(
                                        ["kind", "name"],
                                        apiGroup=_str(), kind=_str(),
                                        name=_str(), namespace=_str(),
                                    ),
                                    "resources": {
                                        "type": "object",
                                        "properties": {
                                            "limits": quantity_map(),
                                            "requests": quantity_map(),
                                        },
                                    },
                                    "selector": label_selector_schema(),
                                    "storageClassName": _str(),
                                    "volumeAttributesClassName": _str(),
                                    "volumeMode": _str(
                                        enum=["Block", "Filesystem"]
                                    ),
                                    "volumeName": _str(),
                                },
                            },
                        },
                    },
                },
            },
            **_legacy_volume_sources(),
        },
    }


def container_schema() -> dict:
    return {
        "type": "object",
        "required": ["name"],
        "properties": {
            "name": _str("Container name (DNS label).", pattern=DNS1123),
            "image": _str("Container image."),
            "command": _str_array(),
            "args": _str_array(),
            "workingDir": _str(),
            "imagePullPolicy": _str(
                enum=["Always", "IfNotPresent", "Never"]
            ),
            "env": {
                "type": "array",
                "items": {
                    "type": "object",
                    "required": ["name"],
                    "properties": {
                        "name": _str("Environment variable name."),
                        "value": _str(),
                        "valueFrom": env_value_from_schema(),
                    },
                },
            },
            "envFrom": {
                "type": "array",
                "items": env_from_source_schema(),
            },
            "ports": {
                "type": "array",
                "items": {
                    "type": "object",
                    "required": ["containerPort"],
                    "properties": {
                        "name": _str(pattern=DNS1123),
                        "containerPort": _int(minimum=1, maximum=65535),
                        "hostPort": _int(minimum=1, maximum=65535),
                        "protocol": _str(enum=["TCP", "UDP", "SCTP"]),
                    },
                },
            },
            "resources": {
                "type": "object",
                "description": (
                    "Compute resources; google.com/tpu limits are injected "
                    "by the operator when absent."
                ),
                "properties": {
                    "limits": quantity_map(),
                    "requests": quantity_map(),
                },
            },
            "volumeMounts": {
                "type": "array",
                "items": {
                    "type": "object",
                    "required": ["name", "mountPath"],
                    "properties": {
                        "name": _str(),
                        "mountPath": _str(),
                        "subPath": _str(),
                        "readOnly": _bool(),
                    },
                },
            },
            "securityContext": container_security_context_schema(),
            "lifecycle": lifecycle_schema(),
            "livenessProbe": probe_schema("Container liveness probe."),
            "readinessProbe": probe_schema("Container readiness probe."),
            "startupProbe": probe_schema("Container startup probe."),
            "terminationMessagePath": _str(),
            "terminationMessagePolicy": _str(
                enum=["File", "FallbackToLogsOnError"]
            ),
            "stdin": _bool(),
            "tty": _bool(),
        },
    }


def pod_template_schema() -> dict:
    """Trimmed core/v1 PodTemplateSpec (reference embeds the full schema,
    v2/crd/kubeflow.org_mpijobs.yaml; this keeps the fields that matter
    structural and leaves exotic subtrees open)."""
    return {
        "type": "object",
        "description": "core/v1 PodTemplateSpec for the replica pods.",
        "properties": {
            "metadata": {
                "type": "object",
                "properties": {
                    "labels": _string_map(),
                    "annotations": _string_map(),
                    "name": _str(),
                    "namespace": _str(),
                },
            },
            "spec": {
                "type": "object",
                "required": ["containers"],
                "properties": {
                    "containers": {
                        "type": "array",
                        "minItems": 1,
                        "items": container_schema(),
                    },
                    "initContainers": {
                        "type": "array",
                        "items": container_schema(),
                    },
                    "volumes": {
                        "type": "array",
                        "items": volume_schema(),
                    },
                    "nodeSelector": _string_map(),
                    "tolerations": {
                        "type": "array",
                        "items": {
                            "type": "object",
                            "properties": {
                                "key": _str(),
                                "operator": _str(enum=["Exists", "Equal"]),
                                "value": _str(),
                                "effect": _str(
                                    enum=[
                                        "NoSchedule",
                                        "PreferNoSchedule",
                                        "NoExecute",
                                    ]
                                ),
                                "tolerationSeconds": {
                                    "type": "integer",
                                    "format": "int64",
                                },
                            },
                        },
                    },
                    "affinity": affinity_schema(),
                    "topologySpreadConstraints": {
                        "type": "array",
                        "items": topology_spread_constraint_schema(),
                    },
                    "schedulerName": _str(),
                    "priorityClassName": _str(),
                    "serviceAccountName": _str(),
                    "automountServiceAccountToken": _bool(),
                    "restartPolicy": _str(
                        "Pod-level restart policy; the operator derives it "
                        "from the ReplicaSpec when unset.",
                        enum=["Always", "OnFailure", "Never"],
                    ),
                    "terminationGracePeriodSeconds": {
                        "type": "integer",
                        "format": "int64",
                        "minimum": 0,
                    },
                    "activeDeadlineSeconds": {
                        "type": "integer",
                        "format": "int64",
                        "minimum": 1,
                    },
                    "hostNetwork": _bool(),
                    "hostPID": _bool(),
                    "hostIPC": _bool(),
                    "dnsPolicy": _str(
                        enum=[
                            "ClusterFirst",
                            "ClusterFirstWithHostNet",
                            "Default",
                            "None",
                        ]
                    ),
                    "securityContext": pod_security_context_schema(),
                    "imagePullSecrets": {
                        "type": "array",
                        "items": {
                            "type": "object",
                            "properties": {"name": _str()},
                        },
                    },
                    "subdomain": _str(),
                    "hostname": _str(),
                },
            },
        },
    }


def replica_spec_schema(role: str) -> dict:
    return {
        "type": "object",
        "description": f"{role} replica group.",
        "properties": {
            "replicas": _int(
                "Number of replicas. For Worker this is normally derived "
                "from spec.tpu and may be omitted.",
                minimum=0,
            ),
            "restartPolicy": _str(
                "Restart policy for replica pods.",
                enum=[types.RESTART_POLICY_NEVER, types.RESTART_POLICY_ON_FAILURE],
            ),
            "template": pod_template_schema(),
        },
    }


def pod_failure_policy_schema() -> dict:
    """batch/v1 PodFailurePolicy analog: ordered rules classifying worker
    failures by container exit code or pod condition/reason."""
    return {
        "type": "object",
        "required": ["rules"],
        "description": (
            "Ordered failure-classification rules; the first rule matching "
            "a failed worker pod decides its fate. Ignore replaces the pod "
            "without charging backoffLimit, Restart replaces and charges, "
            "FailJob fails the job with reason PodFailurePolicy."
        ),
        "properties": {
            "rules": {
                "type": "array",
                "items": {
                    "type": "object",
                    "required": ["action"],
                    "properties": {
                        "action": _str(
                            "What to do with a matching failed pod.",
                            enum=[
                                types.POD_FAILURE_POLICY_ACTION_IGNORE,
                                types.POD_FAILURE_POLICY_ACTION_RESTART,
                                types.POD_FAILURE_POLICY_ACTION_FAIL_JOB,
                            ],
                        ),
                        "onExitCodes": {
                            "type": "object",
                            "required": ["operator", "values"],
                            "properties": {
                                "containerName": _str(
                                    "Restrict matching to this container."
                                ),
                                "operator": _str(
                                    enum=[
                                        types.POD_FAILURE_POLICY_OP_IN,
                                        types.POD_FAILURE_POLICY_OP_NOT_IN,
                                    ]
                                ),
                                "values": {
                                    "type": "array",
                                    "items": _int(minimum=0, maximum=255),
                                },
                            },
                        },
                        "onPodConditions": {
                            "type": "array",
                            "items": {
                                "type": "object",
                                "properties": {
                                    "type": _str("Pod condition type to match."),
                                    "status": _str(
                                        enum=["True", "False", "Unknown"]
                                    ),
                                    "reason": _str(
                                        "Match pod status.reason (e.g. Evicted, "
                                        "NodeLost) — TPU extension."
                                    ),
                                },
                            },
                        },
                    },
                },
            },
        },
    }


def job_spec_schema() -> dict:
    return {
        "type": "object",
        "required": ["tpuReplicaSpecs"],
        "properties": {
            "tpu": {
                "type": "object",
                "description": (
                    "The TPU slice shape this job trains on. Worker count and "
                    "chips-per-pod are derived from acceleratorType/topology."
                ),
                "properties": {
                    "acceleratorType": _str(
                        "TPU slice type, <generation>-<chips>, e.g. v5e-16.",
                        pattern=r"^v[0-9]+[a-z]*-[0-9]+$",
                    ),
                    "topology": _str(
                        "Optional explicit chip topology, e.g. 4x4 or 2x2x4.",
                        pattern=r"^[0-9]+(x[0-9]+)*$",
                    ),
                    "numSlices": _int(
                        "Number of pod slices (>1 = multislice over DCN).",
                        minimum=1,
                    ),
                    "runtimeVersion": _str("TPU VM runtime version label."),
                    "hotSpares": _int(
                        "Standby workers kept warm (scheduled, "
                        "bootstrapped, parked before the barrier) for "
                        "fast promotion when a worker death is "
                        "restart-eligible.",
                        minimum=0,
                    ),
                },
            },
            "jaxDistribution": {
                "type": "object",
                "description": (
                    "Rendezvous wiring for jax.distributed.initialize. "
                    "Replaces the reference operator's SSH bootstrap: the only "
                    "shared state is worker-0's coordinator address."
                ),
                "properties": {
                    "coordinatorPort": _int(
                        "Coordinator port on worker 0.", minimum=1, maximum=65535
                    ),
                    "heartbeatTimeoutSeconds": _int(
                        "jax.distributed heartbeat timeout.", minimum=1
                    ),
                },
            },
            "runPolicy": {
                "type": "object",
                "description": "Policies for job lifetime and cleanup.",
                "properties": {
                    "cleanPodPolicy": _str(
                        "Which worker pods to delete once the job finishes.",
                        enum=[
                            types.CLEAN_POD_POLICY_NONE,
                            types.CLEAN_POD_POLICY_RUNNING,
                            types.CLEAN_POD_POLICY_ALL,
                        ],
                    ),
                    "ttlSecondsAfterFinished": _int(minimum=0),
                    "activeDeadlineSeconds": _int(minimum=0),
                    "backoffLimit": _int(minimum=0),
                    "suspend": {
                        "type": "boolean",
                        "description": "Suspend gates worker/launcher creation.",
                    },
                    "schedulingPolicy": {
                        "type": "object",
                        "properties": {
                            "minAvailable": _int(minimum=0),
                            "queue": _str(),
                            "priorityClass": _str(),
                        },
                    },
                    "podFailurePolicy": pod_failure_policy_schema(),
                },
            },
            "tpuReplicaSpecs": {
                "type": "object",
                "required": [types.REPLICA_TYPE_WORKER],
                "properties": {
                    types.REPLICA_TYPE_LAUNCHER: replica_spec_schema("Launcher"),
                    types.REPLICA_TYPE_WORKER: replica_spec_schema("Worker"),
                },
            },
        },
    }


def job_status_schema() -> dict:
    return {
        "type": "object",
        "properties": {
            "conditions": {
                "type": "array",
                "items": {
                    "type": "object",
                    "required": ["type", "status"],
                    "properties": {
                        "type": _str(
                            enum=[
                                types.JOB_CREATED,
                                types.JOB_SCHEDULED,
                                types.JOB_RUNNING,
                                types.JOB_RESTARTING,
                                types.JOB_SUSPENDED,
                                types.JOB_SUCCEEDED,
                                types.JOB_FAILED,
                                types.JOB_QUOTA_RESERVED,
                                types.JOB_QUEUE_NOT_FOUND,
                            ]
                        ),
                        "status": _str(enum=["True", "False", "Unknown"]),
                        "reason": _str(),
                        "message": _str(),
                        "lastUpdateTime": {"type": "number"},
                        "lastTransitionTime": {"type": "number"},
                    },
                },
            },
            "replicaStatuses": {
                "type": "object",
                "additionalProperties": {
                    "type": "object",
                    "properties": {
                        "active": _int(minimum=0),
                        "succeeded": _int(minimum=0),
                        "failed": _int(minimum=0),
                        "restarts": _int(minimum=0),
                    },
                },
            },
            "startTime": {"type": "number"},
            "completionTime": {"type": "number"},
            "lastReconcileTime": {"type": "number"},
        },
    }


def tpujob_schema() -> dict:
    """The complete openAPIV3Schema for the TPUJob CRD version entry."""
    return {
        "type": "object",
        "properties": {
            "apiVersion": _str(),
            "kind": _str(),
            "metadata": {"type": "object"},
            "spec": job_spec_schema(),
            "status": job_status_schema(),
        },
    }


def clusterqueue_schema() -> dict:
    """openAPIV3Schema for the ClusterQueue CRD (Kueue analog, chip-only)."""
    return {
        "type": "object",
        "properties": {
            "apiVersion": _str(),
            "kind": _str(),
            "metadata": {"type": "object"},
            "spec": {
                "type": "object",
                "required": ["quotas"],
                "properties": {
                    "cohort": _str(
                        "Cohort name; member queues lend unused quota to "
                        "each other.",
                        pattern=DNS1123,
                    ),
                    "quotas": {
                        "type": "array",
                        "minItems": 1,
                        "items": {
                            "type": "object",
                            "required": ["generation", "nominalQuota"],
                            "properties": {
                                "generation": _str(
                                    "TPU generation, e.g. v5e, v5p, v4.",
                                    pattern=r"^v[0-9]+[a-z]*$",
                                ),
                                "nominalQuota": _int(
                                    "Chips this queue owns outright.",
                                    minimum=0,
                                ),
                                "borrowingLimit": _int(
                                    "Max chips borrowable from the cohort "
                                    "on top of nominalQuota (unset = "
                                    "unbounded).",
                                    minimum=0,
                                ),
                            },
                        },
                    },
                    "preemption": {
                        "type": "object",
                        "properties": {
                            "reclaimWithinCohort": _str(
                                "Whether lent quota is reclaimed by "
                                "evicting cohort borrowers.",
                                enum=["Never", "Any"],
                            ),
                        },
                    },
                },
            },
            "status": {
                "type": "object",
                "properties": {
                    "pendingWorkloads": _int(minimum=0),
                    "admittedWorkloads": _int(minimum=0),
                    "usage": {
                        "type": "object",
                        "description": "generation -> admitted chips.",
                        "additionalProperties": {
                            "type": "integer",
                            "format": "int32",
                        },
                    },
                },
            },
        },
    }


def localqueue_schema() -> dict:
    """openAPIV3Schema for the LocalQueue CRD (namespace -> ClusterQueue)."""
    return {
        "type": "object",
        "properties": {
            "apiVersion": _str(),
            "kind": _str(),
            "metadata": {"type": "object"},
            "spec": {
                "type": "object",
                "required": ["clusterQueue"],
                "properties": {
                    "clusterQueue": _str(
                        "Name of the ClusterQueue this LocalQueue admits "
                        "into.",
                        pattern=DNS1123,
                    ),
                },
            },
        },
    }
