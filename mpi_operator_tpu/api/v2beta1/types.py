"""TPUJob API types (group ``kubeflow.org``, version ``v2beta1``).

A TPUJob declares a gang of TPU worker pods forming one (or more) pod
slices, plus an optional CPU-only launcher Job for orchestration duties.

Redesign of the reference MPIJob API
(/root/reference/v2/pkg/apis/kubeflow/v2beta1/types.go:25-81) for TPU:

- ``slotsPerWorker`` + ``mpiImplementation``  →  ``tpu:`` block
  (acceleratorType/topology), from which worker count and chips-per-pod
  are *derived* (see api/topology.py).
- ``sshAuthMountPath`` (the SSH rendezvous) →  ``jaxDistribution:`` block:
  workers rendezvous via ``jax.distributed.initialize`` against worker-0's
  coordinator port, so there is no per-job SSH Secret at all.
- Launcher is *optional* (TPU jobs are SPMD: every worker runs the same
  program); the reference required it because only `mpirun` knew how to
  start ranks.  Worker is *required* — the inverse of the reference's
  validation (validation.go:117-136).

Status reuses the kubeflow-common shape: conditions
(Created/Running/Restarting/Succeeded/Failed), per-replica-type counts, and
start/completion timestamps (kubeflow/common JobStatus, consumed at
/root/reference/v2/pkg/controller/mpi_job_controller_status.go:38-142).
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Any, Optional

from ...runtime.objects import ObjectMeta

GROUP_NAME = "kubeflow.org"
GROUP_VERSION = "v2beta1"
API_VERSION = f"{GROUP_NAME}/{GROUP_VERSION}"
KIND = "TPUJob"
PLURAL = "tpujobs"

# Replica types.
REPLICA_TYPE_LAUNCHER = "Launcher"
REPLICA_TYPE_WORKER = "Worker"

# Restart policies (subset of core/v1 allowed for replica specs,
# reference analog: validation.go:40-44).
RESTART_POLICY_NEVER = "Never"
RESTART_POLICY_ON_FAILURE = "OnFailure"

# CleanPodPolicy values (kubeflow-common analog).
CLEAN_POD_POLICY_NONE = "None"
CLEAN_POD_POLICY_RUNNING = "Running"
CLEAN_POD_POLICY_ALL = "All"

# Job condition types (kubeflow-common analog, consumed by
# mpi_job_controller_status.go).
JOB_CREATED = "Created"
JOB_SCHEDULED = "Scheduled"
JOB_RUNNING = "Running"
JOB_RESTARTING = "Restarting"
JOB_SUSPENDED = "Suspended"
JOB_SUCCEEDED = "Succeeded"
JOB_FAILED = "Failed"
# Admission-queue condition types (Kueue Workload-condition analogs,
# written by queue/manager.py when --enable-queue is on).
JOB_QUOTA_RESERVED = "QuotaReserved"
JOB_QUEUE_NOT_FOUND = "QueueNotFound"
# Step-skew observatory verdict (utils/stepstats.py): True while the
# gang has a detected straggler, flipped False on recovery.  Orthogonal
# to the lifecycle conditions — a Straggling job is still Running.
JOB_STRAGGLING = "Straggling"
# Device-memory observatory verdict (utils/devstats.py): True while the
# fleet HBM watermark trend projects exhaustion within the pressure
# horizon, flipped False on recovery.  Same orthogonality as Straggling.
JOB_MEMORY_PRESSURE = "MemoryPressure"

# podFailurePolicy actions (batch/v1 PodFailurePolicyAction analog, with
# ``Restart`` standing in for batch's ``Count`` — the TPU operator
# replaces failed workers rather than tallying them).
POD_FAILURE_POLICY_ACTION_IGNORE = "Ignore"
POD_FAILURE_POLICY_ACTION_RESTART = "Restart"
POD_FAILURE_POLICY_ACTION_FAIL_JOB = "FailJob"
# onExitCodes operators (batch/v1 PodFailurePolicyOnExitCodesOperator).
POD_FAILURE_POLICY_OP_IN = "In"
POD_FAILURE_POLICY_OP_NOT_IN = "NotIn"
# Condition reason when a FailJob rule terminates the job.
JOB_POD_FAILURE_POLICY_REASON = "PodFailurePolicy"


@dataclass
class PodFailurePolicyOnExitCodes:
    """Exit-code requirement (batch/v1 PodFailurePolicyOnExitCodesRequirement).

    Matches when any terminated container (optionally restricted to
    ``container_name``) exited non-zero with a code In/NotIn ``values``.
    Exit code 0 never matches — success is not a failure class.
    """

    operator: str = POD_FAILURE_POLICY_OP_IN
    values: list[int] = field(default_factory=list)
    container_name: str = ""

    def matches(self, pod: dict) -> bool:
        codes = _terminated_exit_codes(pod, self.container_name)
        if self.operator == POD_FAILURE_POLICY_OP_NOT_IN:
            return any(c != 0 and c not in self.values for c in codes)
        return any(c != 0 and c in self.values for c in codes)

    def to_dict(self) -> dict:
        d: dict[str, Any] = {"operator": self.operator, "values": list(self.values)}
        if self.container_name:
            d["containerName"] = self.container_name
        return d

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "PodFailurePolicyOnExitCodes":
        d = d or {}
        return cls(
            operator=d.get("operator", POD_FAILURE_POLICY_OP_IN),
            values=[int(v) for v in d.get("values") or []],
            container_name=d.get("containerName", ""),
        )


@dataclass
class PodFailurePolicyOnPodCondition:
    """Pod-condition requirement (batch/v1 ...OnPodConditionsPattern).

    ``reason`` is a TPU extension: the in-process kubelet reports failure
    classes (Evicted, NodeLost, Error) through ``status.reason`` rather
    than synthetic conditions, so rules may match on it directly.
    """

    type: str = ""
    status: str = "True"
    reason: str = ""

    def matches(self, pod: dict) -> bool:
        status = pod.get("status") or {}
        if self.reason and status.get("reason") != self.reason:
            return False
        if self.type:
            for cond in status.get("conditions") or []:
                if cond.get("type") == self.type and cond.get("status") == self.status:
                    break
            else:
                return False
        return bool(self.reason or self.type)

    def to_dict(self) -> dict:
        d: dict[str, Any] = {}
        if self.type:
            d["type"] = self.type
            d["status"] = self.status
        if self.reason:
            d["reason"] = self.reason
        return d

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "PodFailurePolicyOnPodCondition":
        d = d or {}
        return cls(
            type=d.get("type", ""),
            status=d.get("status", "True"),
            reason=d.get("reason", ""),
        )


@dataclass
class PodFailurePolicyRule:
    """One ordered rule: first match wins (batch/v1 PodFailurePolicyRule).

    Exactly one of ``on_exit_codes`` / ``on_pod_conditions`` must be set
    (validation enforces this); a rule with conditions matches when *any*
    listed pattern matches.
    """

    action: str = ""
    on_exit_codes: Optional[PodFailurePolicyOnExitCodes] = None
    on_pod_conditions: list[PodFailurePolicyOnPodCondition] = field(
        default_factory=list
    )

    def matches(self, pod: dict) -> bool:
        if self.on_exit_codes is not None:
            return self.on_exit_codes.matches(pod)
        if self.on_pod_conditions:
            return any(p.matches(pod) for p in self.on_pod_conditions)
        return False

    def to_dict(self) -> dict:
        d: dict[str, Any] = {"action": self.action}
        if self.on_exit_codes is not None:
            d["onExitCodes"] = self.on_exit_codes.to_dict()
        if self.on_pod_conditions:
            d["onPodConditions"] = [p.to_dict() for p in self.on_pod_conditions]
        return d

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "PodFailurePolicyRule":
        d = d or {}
        return cls(
            action=d.get("action", ""),
            on_exit_codes=(
                PodFailurePolicyOnExitCodes.from_dict(d["onExitCodes"])
                if "onExitCodes" in d
                else None
            ),
            on_pod_conditions=[
                PodFailurePolicyOnPodCondition.from_dict(p)
                for p in d.get("onPodConditions") or []
            ],
        )


@dataclass
class PodFailurePolicy:
    """Ordered failure-classification rules (batch/v1 PodFailurePolicy).

    The controller consults :meth:`match` when a worker pod fails:
    ``Ignore`` replaces the pod without charging ``backoffLimit`` (TPU
    preemptions are not the job's fault), ``Restart`` replaces it and
    charges the budget, ``FailJob`` fails the whole job immediately with
    condition reason ``PodFailurePolicy`` (assertion-style exit codes
    should not burn through retries).
    """

    rules: list[PodFailurePolicyRule] = field(default_factory=list)

    def match(self, pod: dict) -> Optional[PodFailurePolicyRule]:
        for rule in self.rules:
            if rule.matches(pod):
                return rule
        return None

    def to_dict(self) -> dict:
        return {"rules": [r.to_dict() for r in self.rules]}

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "PodFailurePolicy":
        d = d or {}
        return cls(
            rules=[PodFailurePolicyRule.from_dict(r) for r in d.get("rules") or []]
        )


def _terminated_exit_codes(pod: dict, container_name: str = "") -> list[int]:
    """Exit codes of terminated containers, from containerStatuses."""
    codes: list[int] = []
    status = pod.get("status") or {}
    for cs in status.get("containerStatuses") or []:
        if container_name and cs.get("name") != container_name:
            continue
        terminated = (cs.get("state") or {}).get("terminated") or {}
        code = terminated.get("exitCode")
        if code is not None:
            codes.append(int(code))
    return codes


@dataclass
class SchedulingPolicy:
    """Gang-scheduling knobs (kubeflow-common SchedulingPolicy analog)."""

    min_available: Optional[int] = None
    queue: str = ""
    priority_class: str = ""

    def to_dict(self) -> dict:
        d: dict[str, Any] = {}
        if self.min_available is not None:
            d["minAvailable"] = self.min_available
        if self.queue:
            d["queue"] = self.queue
        if self.priority_class:
            d["priorityClass"] = self.priority_class
        return d

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "SchedulingPolicy":
        d = d or {}
        return cls(
            min_available=d.get("minAvailable"),
            queue=d.get("queue", ""),
            priority_class=d.get("priorityClass", ""),
        )


@dataclass
class RunPolicy:
    """Runtime policies (kubeflow-common RunPolicy analog).

    ``ttl_seconds_after_finished`` / ``active_deadline_seconds`` /
    ``backoff_limit`` pass through to the launcher batch Job exactly like
    the reference does (mpi_job_controller.go:1318-1323); for launcher-less
    jobs the controller enforces them itself.
    """

    clean_pod_policy: Optional[str] = None
    ttl_seconds_after_finished: Optional[int] = None
    active_deadline_seconds: Optional[int] = None
    backoff_limit: Optional[int] = None
    scheduling_policy: Optional[SchedulingPolicy] = None
    suspend: Optional[bool] = None
    pod_failure_policy: Optional[PodFailurePolicy] = None

    def to_dict(self) -> dict:
        d: dict[str, Any] = {}
        if self.clean_pod_policy is not None:
            d["cleanPodPolicy"] = self.clean_pod_policy
        if self.ttl_seconds_after_finished is not None:
            d["ttlSecondsAfterFinished"] = self.ttl_seconds_after_finished
        if self.active_deadline_seconds is not None:
            d["activeDeadlineSeconds"] = self.active_deadline_seconds
        if self.backoff_limit is not None:
            d["backoffLimit"] = self.backoff_limit
        if self.scheduling_policy is not None:
            d["schedulingPolicy"] = self.scheduling_policy.to_dict()
        if self.suspend is not None:
            d["suspend"] = self.suspend
        if self.pod_failure_policy is not None:
            d["podFailurePolicy"] = self.pod_failure_policy.to_dict()
        return d

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "RunPolicy":
        d = d or {}
        return cls(
            clean_pod_policy=d.get("cleanPodPolicy"),
            ttl_seconds_after_finished=d.get("ttlSecondsAfterFinished"),
            active_deadline_seconds=d.get("activeDeadlineSeconds"),
            backoff_limit=d.get("backoffLimit"),
            scheduling_policy=(
                SchedulingPolicy.from_dict(d["schedulingPolicy"])
                if "schedulingPolicy" in d
                else None
            ),
            suspend=d.get("suspend"),
            pod_failure_policy=(
                PodFailurePolicy.from_dict(d["podFailurePolicy"])
                if "podFailurePolicy" in d
                else None
            ),
        )


@dataclass
class TPUSpec:
    """The TPU slice this job trains on.

    ``accelerator_type`` is ``<generation>-<chips>`` (e.g. ``v5e-16``);
    ``topology`` optionally pins the slice shape (``4x4``); ``num_slices``
    > 1 asks for a multislice job (data-parallel over DCN);
    ``hot_spares`` > 0 over-provisions that many standby workers kept
    warm (scheduled, bootstrapped, parked before the barrier) so a
    restart-eligible worker death is repaired by promotion instead of
    the full schedule→pending→bootstrap pipeline.
    """

    accelerator_type: str = ""
    topology: str = ""
    num_slices: int = 1
    runtime_version: str = ""
    hot_spares: int = 0

    def to_dict(self) -> dict:
        d: dict[str, Any] = {}
        if self.accelerator_type:
            d["acceleratorType"] = self.accelerator_type
        if self.topology:
            d["topology"] = self.topology
        if self.num_slices != 1:
            d["numSlices"] = self.num_slices
        if self.runtime_version:
            d["runtimeVersion"] = self.runtime_version
        if self.hot_spares != 0:
            d["hotSpares"] = self.hot_spares
        return d

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "TPUSpec":
        d = d or {}
        num_slices = d.get("numSlices")
        hot_spares = d.get("hotSpares")
        return cls(
            accelerator_type=d.get("acceleratorType", ""),
            topology=d.get("topology", ""),
            # An explicit invalid value (0, negative) is preserved so
            # validation can reject it; only absence defaults to 1.
            num_slices=1 if num_slices is None else int(num_slices),
            runtime_version=d.get("runtimeVersion", ""),
            # Same preservation contract: absence defaults to 0, an
            # explicit negative survives for validation to reject.
            hot_spares=0 if hot_spares is None else int(hot_spares),
        )


@dataclass
class JAXDistributionSpec:
    """Rendezvous wiring for ``jax.distributed.initialize``.

    Replaces the reference's SSH bootstrap block (``sshAuthMountPath`` +
    generated Secret, mpi_job_controller.go:1178-1213): the only shared
    state TPU workers need is the coordinator address, which is always
    worker-0's stable DNS name plus this port.
    """

    coordinator_port: int = 0
    heartbeat_timeout_seconds: Optional[int] = None

    def to_dict(self) -> dict:
        d: dict[str, Any] = {}
        if self.coordinator_port:
            d["coordinatorPort"] = self.coordinator_port
        if self.heartbeat_timeout_seconds is not None:
            d["heartbeatTimeoutSeconds"] = self.heartbeat_timeout_seconds
        return d

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "JAXDistributionSpec":
        d = d or {}
        return cls(
            coordinator_port=int(d.get("coordinatorPort", 0) or 0),
            heartbeat_timeout_seconds=d.get("heartbeatTimeoutSeconds"),
        )


@dataclass
class ReplicaSpec:
    """One replica group (kubeflow-common ReplicaSpec analog).

    ``template`` is a PodTemplateSpec kept in plain dict form (the operator
    treats it as opaque except for the fields it decorates).
    """

    replicas: Optional[int] = None
    restart_policy: str = ""
    template: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        d: dict[str, Any] = {}
        if self.replicas is not None:
            d["replicas"] = self.replicas
        if self.restart_policy:
            d["restartPolicy"] = self.restart_policy
        if self.template:
            d["template"] = copy.deepcopy(self.template)
        return d

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "ReplicaSpec":
        d = d or {}
        return cls(
            replicas=d.get("replicas"),
            restart_policy=d.get("restartPolicy", ""),
            template=copy.deepcopy(d.get("template") or {}),
        )


@dataclass
class TPUJobSpec:
    tpu: TPUSpec = field(default_factory=TPUSpec)
    jax_distribution: JAXDistributionSpec = field(default_factory=JAXDistributionSpec)
    run_policy: RunPolicy = field(default_factory=RunPolicy)
    replica_specs: dict[str, ReplicaSpec] = field(default_factory=dict)

    def to_dict(self) -> dict:
        d: dict[str, Any] = {}
        tpu = self.tpu.to_dict()
        if tpu:
            d["tpu"] = tpu
        jd = self.jax_distribution.to_dict()
        if jd:
            d["jaxDistribution"] = jd
        rp = self.run_policy.to_dict()
        if rp:
            d["runPolicy"] = rp
        if self.replica_specs:
            d["tpuReplicaSpecs"] = {
                k: v.to_dict() for k, v in self.replica_specs.items()
            }
        return d

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "TPUJobSpec":
        d = d or {}
        return cls(
            tpu=TPUSpec.from_dict(d.get("tpu")),
            jax_distribution=JAXDistributionSpec.from_dict(d.get("jaxDistribution")),
            run_policy=RunPolicy.from_dict(d.get("runPolicy")),
            replica_specs={
                k: ReplicaSpec.from_dict(v)
                for k, v in (d.get("tpuReplicaSpecs") or {}).items()
            },
        )


@dataclass
class JobCondition:
    type: str = ""
    status: str = ""  # "True" | "False" | "Unknown"
    reason: str = ""
    message: str = ""
    last_update_time: Optional[float] = None
    last_transition_time: Optional[float] = None

    def to_dict(self) -> dict:
        d: dict[str, Any] = {"type": self.type, "status": self.status}
        if self.reason:
            d["reason"] = self.reason
        if self.message:
            d["message"] = self.message
        if self.last_update_time is not None:
            d["lastUpdateTime"] = self.last_update_time
        if self.last_transition_time is not None:
            d["lastTransitionTime"] = self.last_transition_time
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "JobCondition":
        return cls(
            type=d.get("type", ""),
            status=d.get("status", ""),
            reason=d.get("reason", ""),
            message=d.get("message", ""),
            last_update_time=d.get("lastUpdateTime"),
            last_transition_time=d.get("lastTransitionTime"),
        )


@dataclass
class ReplicaStatus:
    active: int = 0
    succeeded: int = 0
    failed: int = 0
    # Cumulative failure-replacements for launcher-less elastic jobs (the
    # analog of a batch Job's retry count: runPolicy.backoffLimit bounds
    # it). Unlike active/succeeded/failed this survives pod replacement.
    restarts: int = 0

    def to_dict(self) -> dict:
        d: dict[str, Any] = {}
        if self.active:
            d["active"] = self.active
        if self.succeeded:
            d["succeeded"] = self.succeeded
        if self.failed:
            d["failed"] = self.failed
        if self.restarts:
            d["restarts"] = self.restarts
        return d

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "ReplicaStatus":
        d = d or {}
        return cls(
            active=int(d.get("active", 0) or 0),
            succeeded=int(d.get("succeeded", 0) or 0),
            failed=int(d.get("failed", 0) or 0),
            restarts=int(d.get("restarts", 0) or 0),
        )


@dataclass
class JobStatus:
    conditions: list[JobCondition] = field(default_factory=list)
    replica_statuses: dict[str, ReplicaStatus] = field(default_factory=dict)
    start_time: Optional[float] = None
    completion_time: Optional[float] = None
    last_reconcile_time: Optional[float] = None

    def to_dict(self) -> dict:
        d: dict[str, Any] = {}
        if self.conditions:
            d["conditions"] = [c.to_dict() for c in self.conditions]
        if self.replica_statuses:
            d["replicaStatuses"] = {
                k: v.to_dict() for k, v in self.replica_statuses.items()
            }
        if self.start_time is not None:
            d["startTime"] = self.start_time
        if self.completion_time is not None:
            d["completionTime"] = self.completion_time
        if self.last_reconcile_time is not None:
            d["lastReconcileTime"] = self.last_reconcile_time
        return d

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "JobStatus":
        d = d or {}
        return cls(
            conditions=[JobCondition.from_dict(c) for c in d.get("conditions") or []],
            replica_statuses={
                k: ReplicaStatus.from_dict(v)
                for k, v in (d.get("replicaStatuses") or {}).items()
            },
            start_time=d.get("startTime"),
            completion_time=d.get("completionTime"),
            last_reconcile_time=d.get("lastReconcileTime"),
        )


@dataclass
class TPUJob:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: TPUJobSpec = field(default_factory=TPUJobSpec)
    status: JobStatus = field(default_factory=JobStatus)

    api_version: str = API_VERSION
    kind: str = KIND

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace

    def to_dict(self) -> dict:
        d: dict[str, Any] = {
            "apiVersion": self.api_version,
            "kind": self.kind,
            "metadata": self.metadata.to_dict(),
            "spec": self.spec.to_dict(),
        }
        status = self.status.to_dict()
        if status:
            d["status"] = status
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "TPUJob":
        return cls(
            api_version=d.get("apiVersion", API_VERSION),
            kind=d.get("kind", KIND),
            metadata=ObjectMeta.from_dict(d.get("metadata")),
            spec=TPUJobSpec.from_dict(d.get("spec")),
            status=JobStatus.from_dict(d.get("status")),
        )

    def deep_copy(self) -> "TPUJob":
        return TPUJob.from_dict(self.to_dict())
