"""kubeflow.org/v2beta1 TPUJob API group.

Reference analog: /root/reference/v2/pkg/apis/kubeflow/v2beta1 (scheme
registration register.go:24-45 collapses to these re-exports).
"""

from .constants import *  # noqa: F401,F403
from .defaults import set_defaults_tpujob  # noqa: F401
from .queue_types import (  # noqa: F401
    CLUSTER_QUEUE_KIND,
    CLUSTER_QUEUE_PLURAL,
    LOCAL_QUEUE_KIND,
    LOCAL_QUEUE_PLURAL,
    RECLAIM_ANY,
    RECLAIM_NEVER,
    ClusterQueue,
    ClusterQueueSpec,
    ClusterQueueStatus,
    GenerationQuota,
    LocalQueue,
    LocalQueueSpec,
    PreemptionPolicy,
)
from .types import (  # noqa: F401
    API_VERSION,
    GROUP_NAME,
    GROUP_VERSION,
    JOB_CREATED,
    JOB_FAILED,
    JOB_QUEUE_NOT_FOUND,
    JOB_QUOTA_RESERVED,
    JOB_RESTARTING,
    JOB_RUNNING,
    JOB_SUCCEEDED,
    JOB_SUSPENDED,
    KIND,
    PLURAL,
    REPLICA_TYPE_LAUNCHER,
    REPLICA_TYPE_WORKER,
    JAXDistributionSpec,
    JobCondition,
    JobStatus,
    ReplicaSpec,
    ReplicaStatus,
    RunPolicy,
    SchedulingPolicy,
    TPUJob,
    TPUJobSpec,
    TPUSpec,
)
