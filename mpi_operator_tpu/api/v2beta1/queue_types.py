"""ClusterQueue / LocalQueue API types (group ``kubeflow.org``, version
``v2beta1``).

Kueue analog (sigs.k8s.io/kueue): the reference operator's production
story gates MPIJobs behind Kueue, which admits suspended jobs against
per-queue quotas.  This in-repo counterpart keeps the same two-level
shape, collapsed to the one resource TPU fleets actually ration — chips:

- ``ClusterQueue`` (cluster-scoped) owns a nominal chip quota per TPU
  generation, may join a *cohort* whose members lend each other unused
  quota (bounded by ``borrowingLimit``), and declares whether it reclaims
  lent quota by evicting borrowers (``preemption.reclaimWithinCohort``).
- ``LocalQueue`` (namespaced) is the submission point: a TPUJob names a
  LocalQueue via ``spec.runPolicy.schedulingPolicy.queue``, and the
  LocalQueue binds that namespace to one ClusterQueue.

Both follow the TPUJob dataclass idiom (types.py): camelCase wire form,
empty/None fields omitted from ``to_dict``.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

from ...runtime.objects import ObjectMeta
from .types import API_VERSION

CLUSTER_QUEUE_KIND = "ClusterQueue"
CLUSTER_QUEUE_PLURAL = "clusterqueues"
LOCAL_QUEUE_KIND = "LocalQueue"
LOCAL_QUEUE_PLURAL = "localqueues"

# preemption.reclaimWithinCohort values (Kueue vocabulary): Never = lent
# quota comes back only as borrowers finish; Any = evict the youngest
# borrowing workloads when an owner needs its nominal quota back.
RECLAIM_NEVER = "Never"
RECLAIM_ANY = "Any"


@dataclass
class GenerationQuota:
    """Chip quota of one ClusterQueue for one TPU generation.

    ``nominal_quota`` is the chip count this queue owns outright;
    ``borrowing_limit`` caps how many chips it may borrow on top from
    cohort peers (None = unbounded, Kueue's default)."""

    generation: str = ""
    nominal_quota: int = 0
    borrowing_limit: Optional[int] = None

    def to_dict(self) -> dict:
        d: dict[str, Any] = {}
        if self.generation:
            d["generation"] = self.generation
        d["nominalQuota"] = self.nominal_quota
        if self.borrowing_limit is not None:
            d["borrowingLimit"] = self.borrowing_limit
        return d

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "GenerationQuota":
        d = d or {}
        return cls(
            generation=d.get("generation", ""),
            nominal_quota=int(d.get("nominalQuota", 0) or 0),
            borrowing_limit=d.get("borrowingLimit"),
        )


@dataclass
class PreemptionPolicy:
    reclaim_within_cohort: str = RECLAIM_NEVER

    def to_dict(self) -> dict:
        d: dict[str, Any] = {}
        if self.reclaim_within_cohort:
            d["reclaimWithinCohort"] = self.reclaim_within_cohort
        return d

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "PreemptionPolicy":
        d = d or {}
        return cls(
            reclaim_within_cohort=d.get("reclaimWithinCohort", RECLAIM_NEVER)
        )


@dataclass
class ClusterQueueSpec:
    cohort: str = ""
    quotas: list[GenerationQuota] = field(default_factory=list)
    preemption: PreemptionPolicy = field(default_factory=PreemptionPolicy)

    def to_dict(self) -> dict:
        d: dict[str, Any] = {}
        if self.cohort:
            d["cohort"] = self.cohort
        if self.quotas:
            d["quotas"] = [q.to_dict() for q in self.quotas]
        preemption = self.preemption.to_dict()
        if preemption:
            d["preemption"] = preemption
        return d

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "ClusterQueueSpec":
        d = d or {}
        return cls(
            cohort=d.get("cohort", ""),
            quotas=[GenerationQuota.from_dict(q) for q in d.get("quotas") or []],
            preemption=PreemptionPolicy.from_dict(d.get("preemption")),
        )


@dataclass
class ClusterQueueStatus:
    """Mirrored by the QueueManager: how the queue currently stands."""

    pending_workloads: int = 0
    admitted_workloads: int = 0
    # generation -> chips currently admitted against this queue.
    usage: dict[str, int] = field(default_factory=dict)

    def to_dict(self) -> dict:
        d: dict[str, Any] = {}
        if self.pending_workloads:
            d["pendingWorkloads"] = self.pending_workloads
        if self.admitted_workloads:
            d["admittedWorkloads"] = self.admitted_workloads
        if self.usage:
            d["usage"] = dict(self.usage)
        return d

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "ClusterQueueStatus":
        d = d or {}
        return cls(
            pending_workloads=int(d.get("pendingWorkloads", 0) or 0),
            admitted_workloads=int(d.get("admittedWorkloads", 0) or 0),
            usage={k: int(v) for k, v in (d.get("usage") or {}).items()},
        )


@dataclass
class ClusterQueue:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ClusterQueueSpec = field(default_factory=ClusterQueueSpec)
    status: ClusterQueueStatus = field(default_factory=ClusterQueueStatus)

    api_version: str = API_VERSION
    kind: str = CLUSTER_QUEUE_KIND

    @property
    def name(self) -> str:
        return self.metadata.name

    def quota_for(self, generation: str) -> Optional[GenerationQuota]:
        for quota in self.spec.quotas:
            if quota.generation == generation:
                return quota
        return None

    def to_dict(self) -> dict:
        d: dict[str, Any] = {
            "apiVersion": self.api_version,
            "kind": self.kind,
            "metadata": self.metadata.to_dict(),
            "spec": self.spec.to_dict(),
        }
        status = self.status.to_dict()
        if status:
            d["status"] = status
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "ClusterQueue":
        return cls(
            api_version=d.get("apiVersion", API_VERSION),
            kind=d.get("kind", CLUSTER_QUEUE_KIND),
            metadata=ObjectMeta.from_dict(d.get("metadata")),
            spec=ClusterQueueSpec.from_dict(d.get("spec")),
            status=ClusterQueueStatus.from_dict(d.get("status")),
        )

    def deep_copy(self) -> "ClusterQueue":
        return ClusterQueue.from_dict(self.to_dict())


@dataclass
class LocalQueueSpec:
    cluster_queue: str = ""

    def to_dict(self) -> dict:
        d: dict[str, Any] = {}
        if self.cluster_queue:
            d["clusterQueue"] = self.cluster_queue
        return d

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "LocalQueueSpec":
        d = d or {}
        return cls(cluster_queue=d.get("clusterQueue", ""))


@dataclass
class LocalQueue:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: LocalQueueSpec = field(default_factory=LocalQueueSpec)

    api_version: str = API_VERSION
    kind: str = LOCAL_QUEUE_KIND

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace

    def to_dict(self) -> dict:
        return {
            "apiVersion": self.api_version,
            "kind": self.kind,
            "metadata": self.metadata.to_dict(),
            "spec": self.spec.to_dict(),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "LocalQueue":
        return cls(
            api_version=d.get("apiVersion", API_VERSION),
            kind=d.get("kind", LOCAL_QUEUE_KIND),
            metadata=ObjectMeta.from_dict(d.get("metadata")),
            spec=LocalQueueSpec.from_dict(d.get("spec")),
        )
