"""Defaulting for TPUJob.

Reference analog: SetDefaults_MPIJob and friends,
/root/reference/v2/pkg/apis/kubeflow/v2beta1/default.go:26-77.

Differences, by design:
- Worker replicas default from the slice topology (one pod per TPU host)
  rather than to 0 — a TPUJob's worker count is a property of the slice.
- There is no SSH mount path or MPI implementation to default; instead the
  coordinator port defaults to 8476.
- A Launcher spec is defaulted only if present (it is optional).
"""

from __future__ import annotations

from .. import topology
from . import constants
from .types import (
    REPLICA_TYPE_LAUNCHER,
    REPLICA_TYPE_WORKER,
    ReplicaSpec,
    TPUJob,
)


def _set_defaults_launcher(spec: ReplicaSpec | None) -> None:
    # default.go:27-38 analog.
    if spec is None:
        return
    if not spec.restart_policy:
        spec.restart_policy = constants.DEFAULT_LAUNCHER_RESTART_POLICY
    if spec.replicas is None:
        spec.replicas = 1


def _set_defaults_worker(
    spec: ReplicaSpec | None, accelerator_type: str, topo: str, num_slices: int
) -> None:
    # default.go:41-50 analog, except replicas default from topology.
    if spec is None:
        return
    if not spec.restart_policy:
        spec.restart_policy = constants.DEFAULT_RESTART_POLICY
    if spec.replicas is None and accelerator_type and num_slices >= 1:
        try:
            spec.replicas = (
                topology.resolve(accelerator_type, topo).num_hosts * num_slices
            )
        except topology.TopologyError:
            pass  # left for validation to report
    if spec.replicas is None:
        spec.replicas = 0


def set_defaults_tpujob(job: TPUJob) -> None:
    # default.go:53-59 analog.
    if job.spec.run_policy.clean_pod_policy is None:
        job.spec.run_policy.clean_pod_policy = constants.DEFAULT_CLEAN_POD_POLICY
    # Remaining run-policy fields pass through to the batch Job API, which
    # does its own defaulting (default.go:57-58 analog).

    if not job.spec.jax_distribution.coordinator_port:
        job.spec.jax_distribution.coordinator_port = constants.DEFAULT_COORDINATOR_PORT

    # Fill in the standard topology so everything downstream (env wiring,
    # mesh construction) sees an explicit shape.
    tpu = job.spec.tpu
    if tpu.accelerator_type and not tpu.topology:
        try:
            tpu.topology = topology.default_topology(
                *topology.parse_accelerator_type(tpu.accelerator_type)
            )
        except topology.TopologyError:
            pass  # left for validation to report

    _set_defaults_launcher(job.spec.replica_specs.get(REPLICA_TYPE_LAUNCHER))
    _set_defaults_worker(
        job.spec.replica_specs.get(REPLICA_TYPE_WORKER),
        tpu.accelerator_type,
        tpu.topology,
        tpu.num_slices,
    )
