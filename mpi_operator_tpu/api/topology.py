"""TPU slice topology math.

This replaces the reference's ``slotsPerWorker`` notion
(/root/reference/v2/pkg/apis/kubeflow/v2beta1/types.go:43-45): where an
MPIJob declares "N slots per worker" and the operator writes it into MPI env
(/root/reference/v2/pkg/controller/mpi_job_controller.go:1363-1377), a TPUJob
declares a *slice* (``acceleratorType`` + optional ``topology``), and the
operator derives from it:

- how many worker pods the slice needs (one per TPU host),
- how many chips each pod must request (``google.com/tpu`` resource),
- the env wiring each worker needs to find its peers
  (``TPU_WORKER_ID``/``TPU_WORKER_HOSTNAMES``).

Conventions (documented deviation from Cloud naming): ``acceleratorType`` is
``<generation>-<chips>`` where ``<chips>`` always counts *chips* (Cloud's
v2/v3/v5p names count TensorCores; we do not reproduce that inconsistency).
Topologies are ``AxB`` (2D generations) or ``AxBxC`` (3D generations).

A host owns a 2x2 block of a 2D slice or a 2x2x1 block of a 3D slice
(4 chips/host), except small single-host slices which own all chips
(up to 8 for the 2D generations, e.g. v5e ``2x4``).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import reduce

CHIPS_PER_HOST = 4
MAX_SINGLE_HOST_CHIPS_2D = 8

# generation name -> number of topology dimensions
GENERATIONS: dict[str, int] = {
    "v4": 3,
    "v5e": 2,
    "v5p": 3,
    "v6e": 2,
}

# Standard topologies per (generation dims, chips). 2D entries follow the
# published v5e/v6e shapes; 3D entries are near-cubes with even factors.
_DEFAULT_2D: dict[int, str] = {
    1: "1x1",
    4: "2x2",
    8: "2x4",
    16: "4x4",
    32: "4x8",
    64: "8x8",
    128: "8x16",
    256: "16x16",
}
_DEFAULT_3D: dict[int, str] = {
    8: "2x2x2",
    16: "2x2x4",
    32: "2x4x4",
    64: "4x4x4",
    128: "4x4x8",
    256: "4x8x8",
    512: "8x8x8",
    1024: "8x8x16",
    2048: "8x16x16",
    4096: "16x16x16",
}


class TopologyError(ValueError):
    pass


@dataclass(frozen=True)
class SliceShape:
    """Resolved shape of one TPU slice."""

    generation: str
    chips: int
    topology: str  # "AxB" or "AxBxC"
    num_hosts: int
    chips_per_host: int

    @property
    def accelerator_type(self) -> str:
        return f"{self.generation}-{self.chips}"

    def dims(self) -> tuple[int, ...]:
        return parse_topology(self.topology)


def parse_accelerator_type(accelerator_type: str) -> tuple[str, int]:
    """``"v5e-16"`` -> ``("v5e", 16)``."""
    parts = accelerator_type.rsplit("-", 1)
    if len(parts) != 2 or parts[0] not in GENERATIONS:
        raise TopologyError(
            f"invalid acceleratorType {accelerator_type!r}: want "
            f"<generation>-<chips> with generation in {sorted(GENERATIONS)}"
        )
    try:
        chips = int(parts[1])
    except ValueError:
        raise TopologyError(
            f"invalid acceleratorType {accelerator_type!r}: chip count "
            f"{parts[1]!r} is not an integer"
        ) from None
    if chips <= 0:
        raise TopologyError(
            f"invalid acceleratorType {accelerator_type!r}: chip count must be positive"
        )
    return parts[0], chips


def parse_topology(topology: str) -> tuple[int, ...]:
    """``"4x4"`` -> ``(4, 4)``."""
    try:
        dims = tuple(int(p) for p in topology.split("x"))
    except ValueError:
        raise TopologyError(f"invalid topology {topology!r}") from None
    if len(dims) not in (2, 3) or any(d <= 0 for d in dims):
        raise TopologyError(
            f"invalid topology {topology!r}: want AxB or AxBxC with positive dims"
        )
    return dims


def default_topology(generation: str, chips: int) -> str:
    ndims = GENERATIONS.get(generation)
    if ndims is None:
        raise TopologyError(f"unknown TPU generation {generation!r}")
    table = _DEFAULT_2D if ndims == 2 else _DEFAULT_3D
    topo = table.get(chips)
    if topo is None:
        raise TopologyError(
            f"no standard topology for {generation}-{chips}; pass "
            f"spec.tpu.topology explicitly (standard sizes: {sorted(table)})"
        )
    return topo


def resolve(accelerator_type: str, topology: str = "") -> SliceShape:
    """Resolve acceleratorType (+ optional explicit topology) to a SliceShape.

    Raises TopologyError on inconsistency (topology product != chip count,
    wrong dimensionality for the generation, non-integral host count).
    """
    generation, chips = parse_accelerator_type(accelerator_type)
    ndims = GENERATIONS[generation]
    if not topology:
        topology = default_topology(generation, chips)
    dims = parse_topology(topology)
    if len(dims) != ndims:
        raise TopologyError(
            f"topology {topology!r} has {len(dims)} dims but generation "
            f"{generation} slices are {ndims}-dimensional"
        )
    product = reduce(lambda a, b: a * b, dims, 1)
    if product != chips:
        raise TopologyError(
            f"topology {topology!r} has {product} chips but acceleratorType "
            f"{accelerator_type!r} declares {chips}"
        )

    if chips <= CHIPS_PER_HOST:
        num_hosts, chips_per_host = 1, chips
    elif ndims == 2 and chips <= MAX_SINGLE_HOST_CHIPS_2D:
        # e.g. v5e 2x4: one 8-chip host machine.
        num_hosts, chips_per_host = 1, chips
    else:
        if chips % CHIPS_PER_HOST != 0:
            raise TopologyError(
                f"{accelerator_type!r}: multi-host slices must have a chip "
                f"count divisible by {CHIPS_PER_HOST}"
            )
        # A host owns a 2x2(x1) block, which must tile the slice: at least
        # two topology dims must be even (chip divisibility alone does not
        # guarantee this — e.g. 1x16 has 16 chips but no 2x2 tiling).
        if sum(1 for d in dims if d % 2 == 0) < 2:
            raise TopologyError(
                f"topology {topology!r} cannot be tiled by 2x2 host blocks; "
                f"multi-host slices need at least two even dimensions"
            )
        num_hosts, chips_per_host = chips // CHIPS_PER_HOST, CHIPS_PER_HOST
    return SliceShape(
        generation=generation,
        chips=chips,
        topology=topology,
        num_hosts=num_hosts,
        chips_per_host=chips_per_host,
    )


def host_block_dims(dims: tuple[int, ...]) -> tuple[int, ...]:
    """Extents of one host's chip block within a multi-host slice.

    A host owns a 2x2 block; in 3D the two "2" extents lie along the
    first two *even* dimensions (2x2x1 canonically, but e.g. a 2x3x2
    slice tiles as 2x1x2 blocks — chip divisibility alone does not pin
    the orientation).
    """
    evens = [i for i, d in enumerate(dims) if d % 2 == 0][:2]
    return tuple(2 if i in evens else 1 for i in range(len(dims)))


def host_grid(shape: SliceShape) -> list[tuple[int, ...]]:
    """Chip-space origin of every host's block, indexed by host id.

    Host ids walk the block grid in row-major order, so consecutive ids
    are physically adjacent along the innermost dimension — the property
    the scheduler's topology-aware scoring relies on when it packs a
    gang onto contiguous hosts of one slice.
    """
    dims = shape.dims()
    if shape.num_hosts == 1:
        return [tuple(0 for _ in dims)]
    block = host_block_dims(dims)
    counts = tuple(d // b for d, b in zip(dims, block))
    coords: list[tuple[int, ...]] = []
    for idx in range(shape.num_hosts):
        rem, pos = idx, []
        for c in reversed(counts):
            pos.append(rem % c)
            rem //= c
        coords.append(tuple(p * b for p, b in zip(reversed(pos), block)))
    return coords


def resolve_shape_or_none(accelerator_type: str, topology: str = ""):
    """``resolve`` that returns None instead of raising — the scheduler
    consumes inventory/pod hints best-effort and must not crash on a
    malformed one."""
    try:
        return resolve(accelerator_type, topology)
    except TopologyError:
        return None
