"""klog-analog structured logger (no external dependency).

Reference analog: the klog.Infof/klog.Errorf call sites threaded through
/root/reference/v2/pkg/controller/mpi_job_controller.go (e.g. :262-267,
:475, :1565) plus klog's severity-prefixed line format.  Like klog, this
is a process-global sink configured once from flags (``--log-level`` /
``--log-format`` on cmd/operator.py) and consumed through cheap per-
component logger handles.

Two output formats:

- ``text`` — klog-style single line, severity char + timestamp +
  component: ``I0805 14:03:22.123456 controller] synced job key="a/b"``;
- ``json`` — one JSON object per line (``ts``, ``level``, ``component``,
  ``msg``, plus structured fields), the machine-scrapeable form.

Every record automatically carries ``trace_id`` when a span is open on
the calling thread (or the process adopted a cross-process context, see
utils/trace.TraceContext) — the join key between logs and
``/debug/trace``.

:func:`emit_json` is the raw line emitter underneath the JSON format,
exported for call sites whose *output itself* is the product (training
telemetry JSONL, healthcheck result on stdout): they keep their exact
stream contract while sharing the single-line, single-write discipline.
"""

from __future__ import annotations

import json
import sys
import threading
import time
from typing import Optional, TextIO

from . import trace

DEBUG = 10
INFO = 20
WARNING = 30
ERROR = 40

_LEVELS = {"debug": DEBUG, "info": INFO, "warning": WARNING, "error": ERROR}
_SEVERITY_NAME = {DEBUG: "debug", INFO: "info", WARNING: "warning", ERROR: "error"}
_SEVERITY_CHAR = {DEBUG: "D", INFO: "I", WARNING: "W", ERROR: "E"}

FORMAT_TEXT = "text"
FORMAT_JSON = "json"


class _Config:
    def __init__(self):
        self.level = INFO
        self.format = FORMAT_TEXT
        self.stream: TextIO = sys.stderr
        self.clock = time.time
        self.lock = threading.Lock()


_config = _Config()


def parse_level(level) -> int:
    if isinstance(level, int):
        return level
    try:
        return _LEVELS[str(level).lower()]
    except KeyError:
        raise ValueError(
            f"unknown log level {level!r} (want one of {sorted(_LEVELS)})"
        ) from None


def configure(
    level=None,
    format: Optional[str] = None,
    stream: Optional[TextIO] = None,
    clock=None,
) -> dict:
    """Set the process-global sink; only passed settings change.  Returns
    the previous values of the settings that changed, as kwargs suitable
    for a restoring ``configure(**prev)`` (test hygiene)."""
    prev: dict = {}
    if level is not None:
        prev["level"] = _config.level
        _config.level = parse_level(level)
    if format is not None:
        if format not in (FORMAT_TEXT, FORMAT_JSON):
            raise ValueError(f"unknown log format {format!r}")
        prev["format"] = _config.format
        _config.format = format
    if stream is not None:
        prev["stream"] = _config.stream
        _config.stream = stream
    if clock is not None:
        prev["clock"] = _config.clock
        _config.clock = clock
    return prev


def emit_json(record: dict, stream: Optional[TextIO] = None) -> None:
    """Write one JSON object as a single sorted-keys line (atomic under
    the sink lock).  The emitter behind the ``json`` format, and the
    sanctioned path for machine-readable line protocols (telemetry JSONL,
    healthcheck stdout result)."""
    out = _config.stream if stream is None else stream
    line = json.dumps(record, sort_keys=True)
    with _config.lock:
        out.write(line + "\n")
        try:
            out.flush()
        except (ValueError, OSError):
            pass  # closed/pipeless stream: the write already landed or never will


def _format_field(value) -> str:
    if isinstance(value, str):
        return json.dumps(value)
    if isinstance(value, float):
        return f"{value:.6g}"
    return str(value)


class Logger:
    """A component-bound handle onto the process-global sink.  Immutable;
    ``with_fields`` returns a child carrying extra structured fields
    (job namespace/name, typically) on every record."""

    __slots__ = ("component", "_fields")

    def __init__(self, component: str, fields: Optional[dict] = None):
        self.component = component
        self._fields = dict(fields or {})

    def with_fields(self, **fields) -> "Logger":
        merged = dict(self._fields)
        merged.update(fields)
        return Logger(self.component, merged)

    def for_job(self, namespace: str, name: str) -> "Logger":
        """The klog job-identity convention: every record about a job
        carries its namespace/name as structured fields."""
        return self.with_fields(namespace=namespace, tpujob=name)

    def enabled_for(self, level) -> bool:
        return parse_level(level) >= _config.level

    # -- severity methods (printf-style, klog.Infof analog) --------------

    def debug(self, msg: str, *args, **fields) -> None:
        self._emit(DEBUG, msg, args, fields)

    def info(self, msg: str, *args, **fields) -> None:
        self._emit(INFO, msg, args, fields)

    def warning(self, msg: str, *args, **fields) -> None:
        self._emit(WARNING, msg, args, fields)

    def error(self, msg: str, *args, **fields) -> None:
        self._emit(ERROR, msg, args, fields)

    def _emit(self, severity: int, msg: str, args: tuple, fields: dict) -> None:
        cfg = _config
        if severity < cfg.level:
            return
        if args:
            msg = msg % args
        merged = dict(self._fields)
        merged.update(fields)
        ctx = trace.current_context()
        if ctx is not None and "trace_id" not in merged:
            merged["trace_id"] = ctx.trace_id
        now = cfg.clock()
        if cfg.format == FORMAT_JSON:
            record = {
                "ts": round(now, 6),
                "level": _SEVERITY_NAME[severity],
                "component": self.component,
                "msg": msg,
            }
            for k, v in merged.items():
                record.setdefault(k, v)
            emit_json(record, stream=cfg.stream)
            return
        # klog-style text: I0805 14:03:22.123456 component] msg k="v"
        lt = time.localtime(now)
        stamp = (
            f"{_SEVERITY_CHAR[severity]}{lt.tm_mon:02d}{lt.tm_mday:02d} "
            f"{lt.tm_hour:02d}:{lt.tm_min:02d}:{lt.tm_sec:02d}"
            f".{int((now % 1) * 1e6):06d}"
        )
        parts = [f"{stamp} {self.component}] {msg}"]
        parts.extend(f"{k}={_format_field(v)}" for k, v in merged.items())
        line = " ".join(parts)
        with cfg.lock:
            cfg.stream.write(line + "\n")
            try:
                cfg.stream.flush()
            except (ValueError, OSError):
                pass


def get_logger(component: str, **fields) -> Logger:
    """The one sanctioned logger constructor (enforced by
    tests/test_lint.py): ``log = get_logger("controller")``."""
    return Logger(component, fields or None)
