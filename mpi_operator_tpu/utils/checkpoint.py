"""Checkpoint/resume for elastic TPU training (orbax wrapper).

The reference operator deliberately owns no checkpointing — it guarantees
restart/rejoin and leaves state to user code (SURVEY.md §5,
proposals/elastic-horovod.md premise). Our framework keeps that
separation but ships the workload-side half: a thin orbax
CheckpointManager wrapper the trainer (cmd/train.py) uses so a gang that
was elastically restarted (launcher.barrier + the controller's
world-size restamping) resumes from the last step instead of step 0.

Orbax is multi-host aware: every process must call save/restore
collectively; only process 0 writes metadata. Sharded jax.Arrays are
saved/restored with their shardings, so a resume onto a *different* mesh
shape (elastic resize!) works by passing ``restore_args`` built from the
new mesh — see ``restore_latest(..., like=state)``.

Durable-commit contract: every completed save publishes a *commit
marker* (``<directory>/.commits/<step>``, written temp → fsync → atomic
rename) after the step data is on disk.  ``restore_latest`` skips any
step without a marker — the on-disk state a writer killed mid-commit
leaves behind — exactly like the torn-checkpoint fallback below, so a
torn write costs one save interval, never the whole resume.  The
``AsyncCheckpointManager`` subclass moves the write off the training
step path entirely: ``save`` blocks only on the device→host snapshot,
a background thread lands the orbax write plus the marker, and the
SIGTERM path drains the in-flight write inside the termination grace
window (``drain_final_save``).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Optional

from ..api.v2beta1 import constants as api_constants
from . import metrics
from .logging import get_logger
from .telemetry import FinalOnce

log = get_logger("checkpoint")

# Subdirectory holding one marker file per durably-committed step.  It is
# not a step directory, so orbax's step listing ignores it.
COMMITS_DIRNAME = ".commits"

# Default grace budget for the preempted final save: under the 30s
# kube default terminationGracePeriodSeconds with headroom for the
# process to exit before SIGKILL.
DEFAULT_FINAL_GRACE_S = 25.0

# The checkpoint observatory (sole writer of the
# tpu_operator_job_checkpoint* family — analysis rule TPU114).
checkpoint_snapshot_seconds = metrics.new_histogram(
    "tpu_operator_job_checkpoint_snapshot_seconds",
    "Device-to-host state snapshot time per async checkpoint save — the "
    "only checkpoint cost on the training step path.",
)
checkpoint_write_seconds = metrics.new_histogram(
    "tpu_operator_job_checkpoint_write_seconds",
    "Durable checkpoint write time (orbax write + commit-marker "
    "publish), off the step path for the async manager.",
    buckets=(0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
             120.0, 300.0),
)
checkpoint_commits_total = metrics.new_counter(
    "tpu_operator_job_checkpoint_commits_total",
    "Checkpoint steps durably committed (commit marker published).",
)


def _write_commit_marker(directory: str, step: int) -> None:
    """Publish ``step`` torn-write-safely: write a temp file, fsync it,
    then atomically rename into place.  A reader never sees a partial
    marker — either the rename happened (step is durable) or the marker
    does not exist (step is skipped on restore)."""
    commits = os.path.join(directory, COMMITS_DIRNAME)
    os.makedirs(commits, exist_ok=True)
    tmp = os.path.join(commits, f".{step}.tmp")
    with open(tmp, "w", encoding="utf-8") as f:
        f.write(str(step))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, os.path.join(commits, str(step)))
    # Make the rename itself durable where the platform allows it.
    try:
        dir_fd = os.open(commits, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(dir_fd)
    except OSError:
        pass
    finally:
        os.close(dir_fd)


def committed_steps(directory: str) -> Optional[set[int]]:
    """The set of durably-committed steps, or ``None`` when the layout
    predates commit markers (no ``.commits`` directory) — legacy
    checkpoints stay restorable without markers."""
    commits = os.path.join(directory, COMMITS_DIRNAME)
    try:
        names = os.listdir(commits)
    except FileNotFoundError:
        return None
    out: set[int] = set()
    for name in names:
        try:
            out.add(int(name))
        except ValueError:
            continue  # in-flight temp files
    return out


def _shapes_by_path(meta_tree: Any) -> dict[tuple, tuple]:
    """Flatten an orbax metadata tree (dicts/lists after the namedtuple
    erasure) into {path-of-names: stored shape}."""
    out: dict[tuple, tuple] = {}

    def rec(node, path):
        if isinstance(node, dict):
            for k, v in node.items():
                rec(v, path + (str(k),))
        elif isinstance(node, (list, tuple)):
            for i, v in enumerate(node):
                rec(v, path + (str(i),))
        elif node is not None:
            shape = getattr(node, "shape", None)
            if shape is not None:
                out[path] = tuple(shape)

    rec(meta_tree, ())
    return out


def _map_with_path(fn, tree: Any, path: tuple = ()) -> Any:
    """Rebuild ``tree`` with ``fn(leaf, path)`` at each leaf, naming
    paths the way orbax metadata does: dict keys as-is, namedtuple
    FIELD NAMES (not indices), sequence indices as strings."""
    if isinstance(tree, dict):
        return type(tree)(
            (k, _map_with_path(fn, v, path + (str(k),)))
            for k, v in tree.items()
        )
    if isinstance(tree, tuple) and hasattr(tree, "_fields"):  # namedtuple
        return type(tree)(*(
            _map_with_path(fn, v, path + (f,))
            for f, v in zip(tree._fields, tree)
        ))
    if isinstance(tree, (list, tuple)):
        mapped = [
            _map_with_path(fn, v, path + (str(i),))
            for i, v in enumerate(tree)
        ]
        return mapped if isinstance(tree, list) else tuple(mapped)
    if tree is None:
        return None
    return fn(tree, path)


class CheckpointManager:
    """save-every-N / keep-K / resume-latest, orbax-backed."""

    def __init__(
        self,
        directory: str,
        *,
        save_interval_steps: int = 100,
        max_to_keep: int = 3,
    ):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.directory = directory
        self._mgr = ocp.CheckpointManager(
            directory,
            # Registering the handler up front lets a FRESH manager read
            # item_metadata (stored shapes) before any restore — the
            # restack-on-resume path inspects shapes first.
            item_handlers=ocp.StandardCheckpointHandler(),
            options=ocp.CheckpointManagerOptions(
                save_interval_steps=save_interval_steps,
                max_to_keep=max_to_keep,
                create=True,
            ),
        )
        # One-shot latch for the preempted final save: however many
        # paths race to save-on-SIGTERM (signal handler, loop epilogue),
        # exactly one drains and records (see drain_final_save).
        self.final_latch = FinalOnce()

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def save(self, step: int, state: Any, *, force: bool = False) -> bool:
        """Save if the interval policy says so (or ``force``). A step that
        already exists is never re-saved (orbax raises on overwrite)."""
        if step in (self._mgr.all_steps() or ()):
            return False
        saved = self._mgr.save(
            step, args=self._ocp.args.StandardSave(state), force=force
        )
        if saved:
            # Synchronous contract: the save is durable when this call
            # returns, so the commit marker is published inline (after
            # any internal orbax async write has landed).
            self._mgr.wait_until_finished()
            _write_commit_marker(self.directory, step)
            checkpoint_commits_total.inc()
            log.info("checkpoint saved at step %d -> %s", step, self.directory)
        return saved

    def restore_latest(self, like: Any) -> tuple[Optional[int], Any]:
        """Restore the newest checkpoint shaped/sharded like ``like``
        (the freshly-initialized state on the *current* mesh — this is
        what makes resume-after-elastic-resize work). Returns
        ``(step, state)`` or ``(None, like)`` when no checkpoint exists.

        Pipelined-elastic resume: when a stored leaf differs from
        ``like`` only by a re-split of its two leading dims — the
        stage-stacked ``[P, L/P, ...]`` layout of models/llama_pp.py
        saved at a different pp size (layer order is pp-invariant) —
        the leaf is restored at its stored shape and reshaped onto the
        new stage split, then placed with ``like``'s sharding. A
        preempted slice rarely comes back the same shape; without this
        a resume onto a resized pipeline died on a shape mismatch.

        Torn-write tolerance: a step directory truncated mid-save (the
        writer was preempted before orbax committed) must not brick the
        resume — an unreadable step is skipped with a warning and the
        next-newest step is tried, down to a cold start when nothing is
        readable.  A step with no commit marker (the writer died between
        the data write and the marker publish) is skipped the same way
        before any read is attempted; checkpoints predating the marker
        layout (no ``.commits`` directory) restore as before.
        """
        steps = sorted(self._mgr.all_steps() or (), reverse=True)
        committed = committed_steps(self.directory)
        for step in steps:
            if committed is not None and step not in committed:
                log.warning(
                    "checkpoint at step %d has no commit marker (torn "
                    "write); falling back to an older step", step,
                )
                continue
            try:
                return self._restore_step(step, like)
            except Exception as e:
                log.warning(
                    "checkpoint at step %d is unreadable (%s: %s); "
                    "falling back to an older step",
                    step, type(e).__name__, e,
                )
        if steps:
            log.warning("no readable checkpoint among steps %s; starting "
                        "cold", steps)
        return None, like

    def _restore_step(self, step: int, like: Any) -> tuple[int, Any]:
        try:
            template, n_restacked = self._restack_template(step, like)
        except Exception as e:  # exotic container types: restore strict
            log.warning("restack template build failed (%s); restoring "
                        "shape-strict — a pp-resized resume will fail on "
                        "a shape mismatch", e)
            template, n_restacked = like, 0
        state = self._mgr.restore(
            step, args=self._ocp.args.StandardRestore(template)
        )
        if n_restacked:
            state = self._reshape_like(state, like)
            log.info("restacked %d pipeline leaves onto the new pp split",
                     n_restacked)
        log.info("resumed from checkpoint step %d (%s)", step, self.directory)
        return step, state

    def _restack_template(self, step: int, like: Any) -> tuple[Any, int]:
        """Build the restore template: ``like``, except leaves whose
        stored shape is a re-split of the leading (stage, layer) dims
        become abstract arrays at the STORED shape (replicated — they
        are re-split and re-sharded after the read).

        The stored shapes come from orbax item metadata, which
        represents namedtuples (optax states) as plain dicts keyed by
        field name — so matching walks both trees by PATH NAME, not by
        pytree structure."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec

        try:
            meta = self._mgr.item_metadata(step)
            meta = getattr(meta, "tree", meta)
            stored_shapes = _shapes_by_path(meta)
        except Exception as e:  # metadata layout varies across versions
            log.warning("no item metadata for step %d (%s); restoring "
                        "shape-strict — a pp-resized resume will fail on "
                        "a shape mismatch", step, e)
            return like, 0
        if not stored_shapes:
            return like, 0

        restacked = [0]

        def plan(leaf, path):
            stored = stored_shapes.get(path)
            want = tuple(getattr(leaf, "shape", None) or ())
            if stored is None or stored == want:
                return leaf
            # Block leaves are always ndim >= 3 ([P, L/P, d, ...]); a 2-D
            # leaf with an equal element count is a refactor (e.g. a
            # transposed kernel), which must keep failing loudly.
            if (len(stored) == len(want) and len(stored) >= 3
                    and stored[0] * stored[1] == want[0] * want[1]
                    and stored[2:] == want[2:]):
                restacked[0] += 1
                sharding = None
                sh = getattr(leaf, "sharding", None)
                if isinstance(sh, NamedSharding):
                    # Keep the read sharded: trailing (weight) dims are
                    # pp-invariant, so ``like``'s spec from dim 2 on
                    # (e.g. the ZeRO-3 fsdp split) applies to the stored
                    # shape too; only the re-split leading dims restore
                    # unsharded.
                    tail = tuple(sh.spec)[2:]
                    sharding = NamedSharding(
                        sh.mesh, PartitionSpec(None, None, *tail)
                    )
                return jax.ShapeDtypeStruct(
                    stored, leaf.dtype, sharding=sharding
                )
            return leaf  # genuine mismatch: let orbax raise its error

        return _map_with_path(plan, like), restacked[0]

    @staticmethod
    def _reshape_like(state: Any, like: Any) -> Any:
        """Re-split restored ``[P', L/P', ...]`` leaves onto ``like``'s
        ``[P, L/P, ...]`` stage split (a pure reshape — layer order does
        not depend on the pp size) and place them with ``like``'s
        sharding."""
        import jax
        import jax.numpy as jnp

        def fix(s, l):
            if tuple(s.shape) == tuple(l.shape):
                return s
            s = jnp.reshape(s, l.shape)
            sharding = getattr(l, "sharding", None)
            return jax.device_put(s, sharding) if sharding is not None else s

        return jax.tree_util.tree_map(fix, state, like)

    def read_latest(self) -> tuple[Optional[int], Any]:
        """Inspection/tooling path: read the newest checkpoint as plain
        host numpy arrays, with no sharding template. NOT for training
        resume (no shardings, whole state on every host) — use
        :meth:`restore_latest` there.

        Restores explicitly as numpy: a bare ``restore(step)`` replays
        the *stored* shardings, which fails whenever the reading
        topology differs from the writing one — exactly the
        cmd.generate / cmd.eval case (checkpoint written on one slice
        shape, read on another, or on CPU)."""
        import jax
        import numpy as np

        step = self._mgr.latest_step()
        if step is None:
            return None, None
        meta = self._mgr.item_metadata(step)
        # Metadata layout varies across orbax versions (the
        # restore_latest path guards the same call): unwrap the tree
        # attribute when present.
        meta = getattr(meta, "tree", meta)
        # numpy-leaf template → StandardCheckpointHandler restores each
        # leaf as host numpy (np.zeros is calloc-lazy, so the template
        # costs address space, not resident memory).
        template = jax.tree_util.tree_map(
            lambda m: np.zeros(m.shape, m.dtype), meta
        )
        return step, self._mgr.restore(
            step, args=self._ocp.args.StandardRestore(template)
        )

    def wait_until_finished(self) -> None:
        self._mgr.wait_until_finished()

    def drain(self, timeout_s: Optional[float] = None) -> bool:
        """Wait for any in-flight write to land; True when nothing is
        left in flight.  The synchronous manager has no background
        writer, so this is ``wait_until_finished`` with a trivially-true
        result — the async subclass overrides it with a bounded join."""
        self._mgr.wait_until_finished()
        return True

    def close(self) -> None:
        self._mgr.close()


class AsyncCheckpointManager(CheckpointManager):
    """Checkpointing off the training step path.

    ``save`` blocks only on the device→host snapshot (``jax.device_get``
    — timed into ``checkpoint_snapshot_seconds``); a background thread
    lands the orbax write and then publishes the commit marker (timed
    into ``checkpoint_write_seconds``).  At most one write is in flight:
    a save arriving while the writer is busy is *skipped*, which is what
    keeps the step-path checkpoint cost flat no matter how aggressive
    the save interval is.  Restore-side safety is the commit-marker
    contract on the base class: a step whose writer died mid-commit has
    no marker and is skipped on resume.

    Chaos hook: ``TPUJOB_CHAOS_TORN_WRITE`` in the environment tears the
    next commit — the step data is written but the marker is withheld,
    the exact on-disk state a writer killed between data write and
    marker publish leaves behind (chaos/podchaos.TornWriteInjector).
    """

    def __init__(
        self,
        directory: str,
        *,
        save_interval_steps: int = 100,
        max_to_keep: int = 3,
    ):
        super().__init__(
            directory,
            save_interval_steps=save_interval_steps,
            max_to_keep=max_to_keep,
        )
        self._interval = max(1, int(save_interval_steps))
        self._writer: Optional[threading.Thread] = None
        self._tear_next = os.environ.get(
            api_constants.ENV_TORN_WRITE, ""
        ) not in ("", "0")
        self.torn_writes = 0  # commits torn by the chaos hook

    def save(self, step: int, state: Any, *, force: bool = False) -> bool:
        """Snapshot to host and hand the write to the background thread.
        Blocking cost: the device→host copy only."""
        if not force and step % self._interval != 0:
            return False
        if step in (self._mgr.all_steps() or ()):
            return False
        if self._writer is not None and self._writer.is_alive():
            if not force:
                # One write in flight at a time: skipping (rather than
                # queueing) bounds the step-path cost and the host
                # memory footprint regardless of save frequency.
                log.info(
                    "checkpoint write still in flight; skipping save at "
                    "step %d", step,
                )
                return False
            self.drain(None)
        import jax

        t0 = time.perf_counter()
        host_state = jax.device_get(state)
        checkpoint_snapshot_seconds.observe(time.perf_counter() - t0)
        writer = threading.Thread(
            target=self._write,
            args=(step, host_state),
            name=f"ckpt-write-{step}",
            daemon=True,
        )
        self._writer = writer
        writer.start()
        return True

    def _write(self, step: int, host_state: Any) -> None:
        t0 = time.perf_counter()
        try:
            self._mgr.save(
                step,
                args=self._ocp.args.StandardSave(host_state),
                force=True,
            )
            self._mgr.wait_until_finished()
            if self._tear_next:
                # Chaos: die "mid-commit" — data on disk, no marker.
                self._tear_next = False
                self.torn_writes += 1
                log.warning(
                    "chaos: tore checkpoint commit at step %d (step data "
                    "written, commit marker withheld)", step,
                )
                return
            _write_commit_marker(self.directory, step)
            checkpoint_commits_total.inc()
            log.info(
                "checkpoint committed at step %d -> %s", step,
                self.directory,
            )
        except Exception as e:
            # The writer thread must never take the trainer down: a
            # failed background save costs one interval, nothing more.
            log.warning(
                "background checkpoint write at step %d failed (%s: %s)",
                step, type(e).__name__, e,
            )
        finally:
            checkpoint_write_seconds.observe(time.perf_counter() - t0)

    def drain(self, timeout_s: Optional[float] = None) -> bool:
        """Join the in-flight write (bounded when ``timeout_s`` is set);
        True when nothing is left in flight afterwards."""
        writer = self._writer
        if writer is None or not writer.is_alive():
            return True
        writer.join(timeout_s)
        return not writer.is_alive()

    def wait_until_finished(self) -> None:
        self.drain(None)
        super().wait_until_finished()


def drain_final_save(
    ckpt: CheckpointManager,
    step: int,
    state: Any,
    telem=None,
    *,
    grace_s: float = DEFAULT_FINAL_GRACE_S,
    clock=time.perf_counter,
) -> bool:
    """The preempted final save: force-save ``state`` and drain the
    write inside the termination grace budget.

    Guarded by the manager's ``final_latch`` (``FinalOnce``): however
    many paths race here on SIGTERM, exactly one performs the save —
    later calls are no-ops returning False, so telemetry never records
    the final checkpoint twice.  The drain budget is ``grace_s`` minus
    whatever the save itself spent (measured on ``clock`` so tests can
    drive it on a fake clock).  Returns True when the checkpoint fully
    drained within the budget; the wall time spent is recorded into
    ``telem`` (``record_checkpoint``) either way.
    """
    if not ckpt.final_latch.claim():
        return False
    t0 = clock()
    drained = False
    try:
        ckpt.save(step, state, force=True)
        remaining = max(0.0, grace_s - (clock() - t0))
        drained = ckpt.drain(remaining)
        if not drained:
            log.warning(
                "final checkpoint at step %d still in flight after the "
                "%.1fs grace budget; exiting without it", step, grace_s,
            )
    except Exception as e:
        log.warning(
            "final checkpoint save at step %d failed (%s: %s)",
            step, type(e).__name__, e,
        )
    finally:
        if telem is not None:
            telem.record_checkpoint(max(0.0, clock() - t0))
    return drained


def read_llama_params(checkpoint_dir: str, cfg, model_name: str):
    """Shared cmd.generate / cmd.eval checkpoint loader: newest step's
    ``params`` as host arrays, with pp-mesh stage-stacked layouts
    unstacked into the ``layer_i`` form the plain model walks. Raises
    ``SystemExit`` with operator-facing messages (these are CLI tools).
    Returns ``(step, params)``."""
    ckpt = CheckpointManager(checkpoint_dir)
    step, state = ckpt.read_latest()
    if step is None:
        raise SystemExit(f"no checkpoint found under {checkpoint_dir}")
    if "params" not in state:
        raise SystemExit(
            f"checkpoint at step {step} has no 'params' entry — was it "
            f"written by cmd.train?"
        )
    params = state["params"]
    if "blocks" in params:
        from ..models.llama_pp import unstack_block_params

        blocks = unstack_block_params(params["blocks"])
        if len(blocks) != cfg.n_layers:
            raise SystemExit(
                f"pipelined checkpoint holds {len(blocks)} layers but "
                f"{model_name} has {cfg.n_layers} — wrong --model?"
            )
        params = {k: v for k, v in params.items() if k != "blocks"}
        params.update(blocks)
    return step, params
