"""Checkpoint/resume for elastic TPU training (orbax wrapper).

The reference operator deliberately owns no checkpointing — it guarantees
restart/rejoin and leaves state to user code (SURVEY.md §5,
proposals/elastic-horovod.md premise). Our framework keeps that
separation but ships the workload-side half: a thin orbax
CheckpointManager wrapper the trainer (cmd/train.py) uses so a gang that
was elastically restarted (launcher.barrier + the controller's
world-size restamping) resumes from the last step instead of step 0.

Orbax is multi-host aware: every process must call save/restore
collectively; only process 0 writes metadata. Sharded jax.Arrays are
saved/restored with their shardings, so a resume onto a *different* mesh
shape (elastic resize!) works by passing ``restore_args`` built from the
new mesh — see ``restore_latest(..., like=state)``.
"""

from __future__ import annotations

import logging
from typing import Any, Optional

log = logging.getLogger(__name__)


class CheckpointManager:
    """save-every-N / keep-K / resume-latest, orbax-backed."""

    def __init__(
        self,
        directory: str,
        *,
        save_interval_steps: int = 100,
        max_to_keep: int = 3,
    ):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.directory = directory
        self._mgr = ocp.CheckpointManager(
            directory,
            options=ocp.CheckpointManagerOptions(
                save_interval_steps=save_interval_steps,
                max_to_keep=max_to_keep,
                create=True,
            ),
        )

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def save(self, step: int, state: Any, *, force: bool = False) -> bool:
        """Save if the interval policy says so (or ``force``). A step that
        already exists is never re-saved (orbax raises on overwrite)."""
        if step in (self._mgr.all_steps() or ()):
            return False
        saved = self._mgr.save(
            step, args=self._ocp.args.StandardSave(state), force=force
        )
        if saved:
            log.info("checkpoint saved at step %d -> %s", step, self.directory)
        return saved

    def restore_latest(self, like: Any) -> tuple[Optional[int], Any]:
        """Restore the newest checkpoint shaped/sharded like ``like``
        (the freshly-initialized state on the *current* mesh — this is
        what makes resume-after-elastic-resize work). Returns
        ``(step, state)`` or ``(None, like)`` when no checkpoint exists."""
        step = self._mgr.latest_step()
        if step is None:
            return None, like
        state = self._mgr.restore(
            step, args=self._ocp.args.StandardRestore(like)
        )
        log.info("resumed from checkpoint step %d (%s)", step, self.directory)
        return step, state

    def read_latest(self) -> tuple[Optional[int], Any]:
        """Inspection/tooling path: read the newest checkpoint as plain
        fully-replicated host arrays, with no sharding template. NOT for
        training resume (no shardings, whole state on every host) — use
        :meth:`restore_latest` there."""
        step = self._mgr.latest_step()
        if step is None:
            return None, None
        return step, self._mgr.restore(step)

    def wait_until_finished(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.close()
