"""Runtime jit-recompile / host-transfer tracer (locktrace's analog for
the training stack).

The TPU5xx static rules (``analysis/jaxcheck.py``) prove at the AST
level that the step path cannot recompile or sync; this module proves
it at *runtime*.  When armed it hooks the two chokepoints the bug
classes share:

- **compiles** — ``jax.monitoring``'s backend-compile duration event
  fires once per XLA compilation (i.e. per jit cache miss).  Any
  compile after :func:`note_warmup_complete` is a
  *recompile-after-warmup*: a shape/dtype/static-arg leak that the
  warmup steps did not cover, costing a full compile mid-training.

- **device-to-host transfers** — every implicit materialization
  (``float(arr)``, ``np.asarray(arr)``, ``.item()``, ``print(arr)``)
  funnels through the array's ``_value`` property; the patch counts
  bytes and attributes them to the first non-jax caller frame.  Only
  reads that actually move bytes count: a second ``float()`` of the
  same array hits the numpy cache, and on the CPU backend
  ``np.asarray`` is zero-copy shared memory — neither is a transfer.

Zero cost when off: hooks are installed once, on first
:func:`enable`, and check one module global before doing any work —
un-armed processes never even install them.  Arm with the
``TPU_JAX_TRACE=1`` environment variable (picked up by ``cmd/train.py``
and ``bench.py``), the bench harness's ``--jax-trace`` flag, or
``jaxtrace.enable()`` in tests.

The report rides in bench/train result blocks as ``"jax_trace"`` the
same way locktrace's rides as ``"lock_trace"``::

    {"compiles": {"total": 3, "seconds": 1.82, "after_warmup": 0,
                  "sites": []},
     "transfers": {"count": 2, "bytes": 8, "after_warmup_count": 0,
                   "after_warmup_bytes": 0, "top_sites": {...}},
     "steps_after_warmup": 64,
     "transfer_bytes_per_step": 0.0}
"""

from __future__ import annotations

import os
import threading
import traceback
from collections import Counter
from typing import Optional

ENV_FLAG = "TPU_JAX_TRACE"

# Frames of caller stack kept per compile-after-warmup sample.
_STACK_DEPTH = 8
# Distinct transfer sites kept in the report.
_TOP_SITES = 8

_COMPILE_EVENT_SUFFIX = "backend_compile_duration"

_SELF_FILE = os.path.abspath(__file__)


class RecompileError(AssertionError):
    """Raised by ``JaxTracer.assert_no_recompiles_after_warmup`` with
    the offending compile sites in the message."""


def _caller_site() -> str:
    """file:line of the nearest frame outside jax and this module —
    the user code that forced the transfer/compile."""
    for frame in reversed(traceback.extract_stack()):
        fn = frame.filename.replace(os.sep, "/")
        if ("/jax/" in fn or "/jaxlib/" in fn
                or os.path.abspath(frame.filename) == _SELF_FILE):
            continue
        return f"{frame.filename}:{frame.lineno}"
    return "<unknown>"


def _caller_stack() -> list[str]:
    frames = [
        f"{f.filename}:{f.lineno}:{f.name}"
        for f in traceback.extract_stack()
        if "/jax/" not in f.filename.replace(os.sep, "/")
        and "/jaxlib/" not in f.filename.replace(os.sep, "/")
        and os.path.abspath(f.filename) != _SELF_FILE
    ]
    return frames[-_STACK_DEPTH:]


class JaxTracer:
    """Counts compiles and device-to-host transfers, split at the
    warmup boundary.  The monitoring listener can fire from compile
    threads, so all state is lock-guarded (the lock is internal —
    never visible to locktrace)."""

    def __init__(self, capture_stacks: bool = True):
        self.capture_stacks = capture_stacks
        self._mu = threading.Lock()
        self._warmup_done = False
        self._steps_after_warmup = 0
        self._compiles = 0
        self._compile_seconds = 0.0
        self._compiles_after_warmup = 0
        self._compile_sites: list[dict] = []
        self._transfers = 0
        self._transfer_bytes = 0
        self._transfers_after_warmup = 0
        self._transfer_bytes_after_warmup = 0
        self._transfer_sites: Counter = Counter()

    # -- hook callbacks --------------------------------------------------

    def on_compile(self, duration_secs: float) -> None:
        with self._mu:
            self._compiles += 1
            self._compile_seconds += duration_secs
            if self._warmup_done:
                self._compiles_after_warmup += 1
                site = {
                    "seconds": round(duration_secs, 6),
                    "stack": _caller_stack() if self.capture_stacks else [],
                }
                self._compile_sites.append(site)

    def on_transfer(self, nbytes: int) -> None:
        site = _caller_site() if self.capture_stacks else "<off>"
        with self._mu:
            self._transfers += 1
            self._transfer_bytes += nbytes
            self._transfer_sites[site] += 1
            if self._warmup_done:
                self._transfers_after_warmup += 1
                self._transfer_bytes_after_warmup += nbytes

    # -- step-loop annotations ------------------------------------------

    def note_warmup_complete(self) -> None:
        """The step loop finished warmup (and synced): compiles and
        transfers from here on are hot-path regressions."""
        with self._mu:
            self._warmup_done = True

    def note_step(self) -> None:
        with self._mu:
            if self._warmup_done:
                self._steps_after_warmup += 1

    # -- reporting -------------------------------------------------------

    def report(self) -> dict:
        """JSON-friendly summary, attached to bench/train result blocks
        as ``"jax_trace"``."""
        with self._mu:
            steps = self._steps_after_warmup
            per_step = (
                self._transfer_bytes_after_warmup / steps if steps else 0.0
            )
            return {
                "compiles": {
                    "total": self._compiles,
                    "seconds": round(self._compile_seconds, 6),
                    "after_warmup": self._compiles_after_warmup,
                    "sites": [dict(s) for s in self._compile_sites],
                },
                "transfers": {
                    "count": self._transfers,
                    "bytes": self._transfer_bytes,
                    "after_warmup_count": self._transfers_after_warmup,
                    "after_warmup_bytes": self._transfer_bytes_after_warmup,
                    "top_sites": dict(
                        self._transfer_sites.most_common(_TOP_SITES)
                    ),
                },
                "steps_after_warmup": steps,
                "transfer_bytes_per_step": round(per_step, 3),
            }

    def assert_no_recompiles_after_warmup(self) -> None:
        with self._mu:
            count = self._compiles_after_warmup
            sites = list(self._compile_sites)
        if count:
            lines = [f"{count} recompile(s) after warmup:"]
            for site in sites:
                lines.append(f"  compile took {site['seconds']}s")
                for frame in site["stack"][-4:]:
                    lines.append(f"    {frame}")
            raise RecompileError("\n".join(lines))


# ----------------------------------------------------------------------
# Process-global switch + hook installation
# ----------------------------------------------------------------------

_tracer: Optional[JaxTracer] = None
_hooks_installed = False


def enabled() -> bool:
    return _tracer is not None


def tracer() -> Optional[JaxTracer]:
    """The active tracer, or None when tracing is off."""
    return _tracer


def enable(active: Optional[JaxTracer] = None) -> JaxTracer:
    """Arm tracing; returns the tracer.  Installs the process-wide
    hooks on first use — call before the steps under test (compiles
    that already happened are not back-counted)."""
    global _tracer
    _tracer = active if active is not None else JaxTracer()
    _install_hooks()
    return _tracer


def disable() -> None:
    global _tracer
    _tracer = None


def note_warmup_complete() -> None:
    """Module-level convenience: no-op when tracing is off."""
    t = _tracer
    if t is not None:
        t.note_warmup_complete()


def note_step() -> None:
    t = _tracer
    if t is not None:
        t.note_step()


def _on_compile_event(event: str, duration_secs: float, **kw) -> None:
    t = _tracer
    if t is not None and event.endswith(_COMPILE_EVENT_SUFFIX):
        t.on_compile(duration_secs)


def _install_hooks() -> None:
    """Register the compile listener and patch the device-to-host
    chokepoint.  Idempotent; both hooks gate on the module global, so a
    disabled tracer costs one attribute read per event."""
    global _hooks_installed
    if _hooks_installed:
        return
    _hooks_installed = True

    try:
        import jax

        jax.monitoring.register_event_duration_secs_listener(
            _on_compile_event
        )
    except Exception:  # pragma: no cover - jax too old / absent
        pass

    try:
        from jax._src.array import ArrayImpl

        orig = ArrayImpl._value
        orig_fget = orig.fget

        def _traced_value(self):
            t = _tracer
            # _npy_value None means this read actually moves bytes;
            # a cached re-read is free and must not count.
            if t is not None and getattr(self, "_npy_value", 1) is None:
                try:
                    nbytes = int(self.nbytes)
                except Exception:  # pragma: no cover - exotic dtypes
                    nbytes = 0
                t.on_transfer(nbytes)
            return orig_fget(self)

        ArrayImpl._value = property(_traced_value)
    except Exception:  # pragma: no cover - jax internals moved
        pass


def _env_enabled() -> bool:
    return os.environ.get(ENV_FLAG, "").strip().lower() not in (
        "", "0", "false", "off", "no",
    )


if _env_enabled():  # pragma: no cover - exercised via subprocess tests
    enable()
