"""Training-loop telemetry: step wall time, throughput, and goodput.

The scaling decisions in the source papers (arxiv 2011.03641, 1909.09756)
all start from the same three numbers per run: how long a step takes, how
many tokens/examples per second that buys, and what fraction of total wall
time was *productive* step time (goodput) — the rest being compile,
restart, checkpoint, and input stalls.  This module owns that bookkeeping
for ``cmd.train``:

- each step's wall time feeds a ``tpu_operator_train_step_duration_seconds``
  histogram plus tokens/examples counters in a metrics registry (the same
  registry shape the operator scrapes, so a sidecar exporter can serve it);
- a compact JSONL record is emitted every ``interval`` steps (and on
  ``close()``) to a file and/or stderr, one object per line, so progress is
  greppable from pod logs without parsing the human log lines;
- every record is stamped with this worker's identity (``TPU_WORKER_ID``
  and hostname, read once at construction), so per-worker JSONL streams
  are joinable without path-name archaeology;
- with ``heartbeat_interval`` set, a windowed ``step_heartbeat`` record
  (step-wall p50/max, barrier/collective-wait share) is emitted every N
  post-warmup steps and handed to an optional publisher — the raw input
  of the operator-side step-skew observatory (utils/stepstats.py), which
  joins heartbeats across workers to find stragglers;
- with a ``devstats_sampler`` wired (utils/devstats.DeviceMemorySampler),
  each closed heartbeat window also emits one ``device_memory`` record
  (HBM in-use/peak/limit watermarks) — the raw input of the operator-side
  device-memory observatory (utils/devstats.MemoryMatrix).

The SIGTERM contract — emit ``final: true`` exactly once per process,
across the telemetry record, the heartbeat flush, and the devstats
sample — is owned by one shared ``FinalOnce`` latch, so a double
delivery of SIGTERM (kubelet retry, supervisor impatience) can never
double-emit the final records.

Step durations are dispatch-to-dispatch wall times: JAX dispatch is async,
so an individual step's number can lag its true device time, but the
backpressure of a steady-state loop makes the sequence converge to real
step time without forcing a device sync per step.
"""

from __future__ import annotations

import os
import socket
import sys
import time
from typing import Callable, Optional, TextIO

from ..api.v2beta1 import constants
from . import metrics
from .logging import emit_json

# Train steps range from ~1ms (tiny CPU models in tests) to minutes
# (large pods): wider buckets than the server-latency defaults.
STEP_BUCKETS = (
    0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
    60.0, 120.0,
)


class FinalOnce:
    """One-shot latch for the "emit ``final: true`` exactly once" SIGTERM
    contract.

    Every shutdown path that wants to stamp a final record claims the
    latch first; only the first claim wins.  Shared by the final
    telemetry record, the final heartbeat flush, and the final devstats
    sample, so the guard lives in one place instead of being duplicated
    per record family.
    """

    def __init__(self):
        import threading

        self._lock = threading.Lock()
        self._claimed = False

    def claim(self) -> bool:
        """True exactly once; every later claim returns False."""
        with self._lock:
            if self._claimed:
                return False
            self._claimed = True
            return True

    @property
    def claimed(self) -> bool:
        with self._lock:
            return self._claimed


class TrainingTelemetry:
    """Accumulates per-step timings and derives throughput/goodput.

    ``record_step`` is called once per optimizer step with that step's
    wall time and whether it was warmup (warmup time counts toward total
    wall time but not toward productive time, so compile cost lands in
    the goodput denominator exactly once).
    """

    def __init__(
        self,
        *,
        tokens_per_step: int = 0,
        examples_per_step: int = 0,
        registry: Optional[metrics.Registry] = None,
        interval: int = 0,
        jsonl_path: str = "",
        stream: Optional[TextIO] = None,
        clock: Callable[[], float] = time.perf_counter,
        heartbeat_interval: int = 0,
        heartbeat_publisher: Optional[Callable[[dict], None]] = None,
        devstats_sampler: Optional[Callable[[int], Optional[dict]]] = None,
    ):
        self.tokens_per_step = tokens_per_step
        self.examples_per_step = examples_per_step
        self.interval = interval
        self._clock = clock
        self._stream = stream if stream is not None else sys.stderr
        self._file: Optional[TextIO] = None
        if jsonl_path:
            self._file = open(jsonl_path, "a", buffering=1)

        # Worker identity, read ONCE at construction (the pod env never
        # changes mid-process): joins the per-worker JSONL streams.
        worker = os.environ.get(constants.ENV_TPU_WORKER_ID, "").strip()
        self.worker_id: Optional[int] = int(worker) if worker.isdigit() else None
        self.hostname = os.environ.get("HOSTNAME") or socket.gethostname()

        # Windowed step heartbeats (the step-skew observatory's input):
        # every ``heartbeat_interval`` post-warmup steps, one compact
        # record with the window's step-wall p50/max and wait share.
        self.heartbeat_interval = max(heartbeat_interval, 0)
        self.heartbeat_publisher = heartbeat_publisher
        self._hb_durations: list[float] = []
        self._hb_wait_s = 0.0
        self._hb_window = 0

        # Device-memory observatory input: one HBM watermark sample per
        # closed heartbeat window (utils/devstats.DeviceMemorySampler).
        self.devstats_sampler = devstats_sampler
        self._final_once = FinalOnce()

        registry = registry or metrics.DEFAULT_REGISTRY
        self.registry = registry
        self.step_duration = metrics.new_histogram(
            "tpu_operator_train_step_duration_seconds",
            "Wall time per optimizer step (dispatch-to-dispatch)",
            registry=registry,
            buckets=STEP_BUCKETS,
        )
        self.steps_total = metrics.new_counter(
            "tpu_operator_train_steps_total",
            "Optimizer steps completed, by phase",
            ("phase",),
            registry,
        )
        self.tokens_total = metrics.new_counter(
            "tpu_operator_train_tokens_total",
            "Tokens processed by post-warmup steps",
            registry=registry,
        )
        self.examples_total = metrics.new_counter(
            "tpu_operator_train_examples_total",
            "Examples processed by post-warmup steps",
            registry=registry,
        )
        self.goodput = metrics.new_gauge(
            "tpu_operator_train_goodput_ratio",
            "Productive step time over total wall time (compiles, restarts, "
            "checkpoints included in the denominator)",
            registry=registry,
        )
        self.throughput = metrics.new_gauge(
            "tpu_operator_train_tokens_per_second",
            "Recent tokens/second (examples/second for token-free models)",
            registry=registry,
        )

        self._origin: Optional[float] = None
        self._productive_s = 0.0
        self._checkpoint_s = 0.0
        self._last_emit_step = 0
        self._last_emit_time: Optional[float] = None
        self._last_emit_productive = 0.0

    def start(self, prior_wall_s: float = 0.0) -> None:
        """Open the wall clock. ``prior_wall_s`` charges time spent before
        this process's loop (e.g. restart/bootstrap cost carried across a
        preemption) to the goodput denominator."""
        self._origin = self._clock() - prior_wall_s
        self._last_emit_time = self._clock()

    def record_step(
        self,
        step: int,
        duration_s: float,
        *,
        warmup: bool = False,
        wait_s: float = 0.0,
    ) -> None:
        """``wait_s`` is the slice of this step spent blocked on the gang
        (barrier/collective wait) when the workload can tell it apart —
        it feeds the heartbeat's wait share, never the goodput split."""
        if self._origin is None:
            self.start()
        self.step_duration.observe(duration_s)
        self.steps_total.inc(1, "warmup" if warmup else "train")
        if not warmup:
            self._productive_s += duration_s
            if self.tokens_per_step:
                self.tokens_total.inc(self.tokens_per_step)
            if self.examples_per_step:
                self.examples_total.inc(self.examples_per_step)
            if self.heartbeat_interval:
                # Warmup (compile) steps stay out of the window: their
                # wall times would read as fake skew to the detector.
                self._hb_durations.append(duration_s)
                self._hb_wait_s += max(0.0, min(wait_s, duration_s))
                if len(self._hb_durations) >= self.heartbeat_interval:
                    self.emit_heartbeat(step)
        if self.interval and step % self.interval == 0:
            self.emit(step)

    def _stamp_identity(self, rec: dict) -> dict:
        """Every emitted record carries the worker's identity so the
        per-pod JSONL files (and the tailed pod logs) join by content."""
        if self.worker_id is not None:
            rec["worker_id"] = self.worker_id
        rec["hostname"] = self.hostname
        return rec

    def emit_heartbeat(self, step: int) -> Optional[dict]:
        """Close the current heartbeat window: emit one ``step_heartbeat``
        JSONL record and hand it to the publisher (in the pods the
        kubelet sim tails, that record becomes a pod annotation patch).
        Returns None when the window is empty."""
        durations = sorted(self._hb_durations)
        if not durations:
            return None
        n = len(durations)
        mid = n // 2
        p50 = (
            durations[mid]
            if n % 2
            else (durations[mid - 1] + durations[mid]) / 2.0
        )
        total = sum(durations)
        rec = self._stamp_identity({
            "event": "step_heartbeat",
            "window": self._hb_window,
            "step": step,
            "steps": n,
            "step_wall_p50_ms": round(p50 * 1000, 3),
            "step_wall_max_ms": round(durations[-1] * 1000, 3),
            "wait_share": round(self._hb_wait_s / total, 4) if total > 0 else 0.0,
            "window_s": round(total, 6),
        })
        self._hb_window += 1
        self._hb_durations = []
        self._hb_wait_s = 0.0
        emit_json(rec, stream=self._file if self._file is not None else self._stream)
        if self.heartbeat_publisher is not None:
            try:
                self.heartbeat_publisher(rec)
            except Exception:
                # A broken publisher (apiserver away, annotation conflict
                # storm) must never take the training loop down with it.
                pass
        # The device-memory observatory samples at the same cadence: one
        # HBM watermark record per closed heartbeat window.
        self.emit_device_memory(rec["window"])
        return rec

    def emit_device_memory(
        self, window: int, *, final: bool = False
    ) -> Optional[dict]:
        """Emit one ``device_memory`` JSONL record for ``window`` via the
        wired sampler (None without one).  Sampler breakage is swallowed:
        memory telemetry must never take the training loop down."""
        if self.devstats_sampler is None:
            return None
        try:
            rec = self.devstats_sampler(window)
        except Exception:
            return None
        if not rec:
            return None
        rec = self._stamp_identity(dict(rec))
        if final:
            rec["final"] = True
        emit_json(
            rec, stream=self._file if self._file is not None else self._stream
        )
        return rec

    def record_checkpoint(self, duration_s: float) -> None:
        """Charge durable-save wall time.  Checkpoint seconds stay in the
        goodput denominator (they are not productive step time) but are
        reported separately so the operator-side goodput ledger can carve
        them out of the job's productive phase."""
        self._checkpoint_s += max(0.0, duration_s)

    # -- derived numbers -------------------------------------------------

    def wall_s(self) -> float:
        if self._origin is None:
            return 0.0
        return max(self._clock() - self._origin, 1e-9)

    def goodput_ratio(self) -> float:
        wall = self.wall_s()
        return min(self._productive_s / wall, 1.0) if wall > 0 else 0.0

    def snapshot(self, step: int) -> dict:
        """One JSONL record: cumulative ratios + rates over the window
        since the previous emit (rates over the whole run would smear
        every transient slowdown into invisibility)."""
        now = self._clock()
        window_s = (
            now - self._last_emit_time
            if self._last_emit_time is not None
            else self.wall_s()
        )
        window_steps = step - self._last_emit_step
        window_productive = self._productive_s - self._last_emit_productive
        per_step = window_productive / window_steps if window_steps > 0 else 0.0
        rate = window_steps / window_s if window_s > 0 else 0.0
        goodput = self.goodput_ratio()
        rec = self._stamp_identity({
            "event": "train_telemetry",
            "step": step,
            "step_ms": round(per_step * 1000, 3),
            "steps_per_sec": round(rate, 3),
            "goodput": round(goodput, 4),
            "wall_s": round(self.wall_s(), 3),
        })
        if self.tokens_per_step:
            rec["tokens_per_sec"] = round(rate * self.tokens_per_step, 1)
        if self.examples_per_step:
            rec["examples_per_sec"] = round(rate * self.examples_per_step, 1)
        if self._checkpoint_s > 0:
            rec["checkpoint_s"] = round(self._checkpoint_s, 3)
        self.goodput.set(round(goodput, 6))
        self.throughput.set(
            round(rate * (self.tokens_per_step or self.examples_per_step), 3)
        )
        self._last_emit_step = step
        self._last_emit_time = now
        self._last_emit_productive = self._productive_s
        return rec

    def emit(self, step: int, *, final: bool = False) -> dict:
        rec = self.snapshot(step)
        if final:
            rec["final"] = True
        # Shared structured-log writer: same sorted-keys one-object-per-line
        # shape as before, with flush + write locking for free.
        emit_json(rec, stream=self._file if self._file is not None else self._stream)
        return rec

    def close(self, step: int, *, final: bool = False) -> Optional[dict]:
        """Final emit, then file close.  Plain shutdown emits only when
        periodic records are enabled and a step landed since the last
        one; ``final=True`` (the preemption/SIGTERM path) always emits,
        so a killed worker's partial goodput and step count are never
        lost with the process.

        The FinalOnce latch makes ``final`` idempotent: a second SIGTERM
        delivery degrades to a plain close instead of double-emitting the
        final records."""
        if final:
            final = self._final_once.claim()
        rec = None
        if self.heartbeat_interval and self._hb_durations:
            # Flush the partial window: a preempted worker's last steps
            # still reach the operator-side step matrix.
            self.emit_heartbeat(step)
        if final:
            # The dying worker's last HBM watermark: the OOM-forensics
            # snapshot the operator freezes must be as fresh as possible.
            self.emit_device_memory(self._hb_window, final=True)
        if final or (self.interval and step > self._last_emit_step):
            rec = self.emit(step, final=final)
        if self._file is not None:
            self._file.close()
            self._file = None
        return rec
