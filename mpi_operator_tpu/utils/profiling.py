"""Phase-level profiling for the control plane.

PR 1/3 gave the operator whole-reconcile histograms (how long did
``sync_handler`` take) but nothing below that granularity: a slow
reconcile could be cache scans, desired-state rendering, apiserver
writes, or status-update conflict retries and the metrics could not say
which.  This module is the attribution layer:

- ``PhaseProfiler.phase(name)``: a context manager (and ``profiled``
  decorator) that times a named phase of work.  Timing is *exclusive*:
  entering a nested phase pauses the parent, so phases tile the pass
  they belong to and their shares sum to ~100% (the remainder is
  reported as ``unattributed`` glue code, never double-counted).
- cache-scan accounting (``record_scan``): objects touched per pass.
  ``utils/statemetrics.py`` and ``queue/manager.py`` rescan full caches
  today; these counters make that visible (and let tests assert when an
  index removes a scan).
- watch-to-reconcile propagation latency: the apiserver stamps every
  ``WatchEvent`` at emission (``WatchEvent.emitted_at``); the informer
  pump observes the ``delivered`` stage and the controller observes the
  ``reconcile`` stage when it dequeues the key the event produced.

Phase names are a closed vocabulary: ``PHASES`` below is the canonical
enum and ``tests/test_lint.py`` rejects any ``.phase("...")`` call site
using a string not registered here (and any non-literal argument), so
the taxonomy cannot drift into free-form labels.

Clock discipline: every stamp and observation goes through the
module-level ``clock`` chokepoint (the ``retry.sleep`` idiom) so
deterministic tests can monkeypatch ``profiling.clock`` and inject
exact latencies with no wall-clock waits.

One profiler per registry: components that share a ``metrics.Registry``
(controller + queue manager in the operator process) must also share a
profiler, or the second one would re-register the same metric names.
``profiler_for(registry)`` memoizes on the registry identity.
"""

from __future__ import annotations

import functools
import threading
import time
import weakref
from typing import Callable, Optional

from ..runtime import locktrace
from . import metrics

# ----------------------------------------------------------------------
# Canonical phase taxonomy (the closed vocabulary tests/test_lint.py
# enforces at every .phase(...) call site).
# ----------------------------------------------------------------------

PHASE_CACHE_READ = "cache_read"          # informer cache get/list
PHASE_RENDER = "render"                  # desired-state object building
PHASE_APISERVER_WRITE = "apiserver_write"  # create/update/delete calls
PHASE_STATUS_UPDATE = "status_update"    # job status diff + write (retries)
PHASE_SCHED_SNAPSHOT = "sched_snapshot"  # scheduler cluster snapshot/reconcile
PHASE_SCHED_RESERVE = "sched_reserve"    # gang fit + chip reservation
PHASE_SCHED_BIND = "sched_bind"          # pod binding writes
PHASE_QUEUE_ADMISSION = "queue_admission"  # quota admission pass

# Phases that tile a controller reconcile pass: their exclusive times
# plus ``unattributed`` sum to the whole-pass duration.
RECONCILE_PHASES = (
    PHASE_CACHE_READ,
    PHASE_RENDER,
    PHASE_APISERVER_WRITE,
    PHASE_STATUS_UPDATE,
)
SCHEDULER_PHASES = (PHASE_SCHED_SNAPSHOT, PHASE_SCHED_RESERVE, PHASE_SCHED_BIND)
QUEUE_PHASES = (PHASE_QUEUE_ADMISSION,)

PHASES = RECONCILE_PHASES + SCHEDULER_PHASES + QUEUE_PHASES

# Derived label for reconcile time outside any phase; not a phase name
# (passing it to .phase() is rejected).
UNATTRIBUTED = "unattributed"

# Watch propagation stages.
STAGE_DELIVERED = "delivered"   # apiserver emission -> informer handler
STAGE_RECONCILE = "reconcile"   # apiserver emission -> controller dequeue
PROPAGATION_STAGES = (STAGE_DELIVERED, STAGE_RECONCILE)

# Propagation/phase latencies span from microseconds (in-process pump)
# to tens of seconds (chaos-delayed watches), wider than DEFAULT_BUCKETS.
LATENCY_BUCKETS = (
    1e-6, 1e-5, 1e-4, 1e-3, 0.01, 0.1, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)

# Module-level clock chokepoint (the ``retry.sleep`` idiom): every stamp
# and every observation reads this, so tests monkeypatch
# ``profiling.clock`` once and emission/delivery/dequeue all agree.
clock: Callable[[], float] = time.monotonic

# Thread-local stamp of the watch event currently being dispatched by an
# informer pump: set around handler dispatch, read by the controller's
# enqueue hook so the emitted_at timestamp survives the object->key
# mapping (pod event -> owner job key) without threading it through
# every handler signature.
_tls = threading.local()


def set_current_event_stamp(emitted_at: Optional[float]) -> None:
    _tls.event_stamp = emitted_at


def current_event_stamp() -> Optional[float]:
    return getattr(_tls, "event_stamp", None)


def clear_current_event_stamp() -> None:
    _tls.event_stamp = None


def histogram_quantile(
    hist: metrics.Histogram, q: float, *labels: str,
    counts: Optional[list[int]] = None,
) -> float:
    """PromQL ``histogram_quantile`` analog: linear interpolation within
    the bucket containing the rank.  Observations in the +Inf bucket
    report the largest finite bound (same clamping Prometheus applies).

    Pass ``counts`` (a ``cumulative_counts`` result) to compute several
    quantiles from one atomic read of the histogram instead of a fresh
    — possibly shifted — read per quantile.
    """
    if counts is None:
        counts = hist.cumulative_counts(*labels)
    total = counts[-1] if counts else 0
    if total == 0:
        return 0.0
    rank = q * total
    bounds = hist.buckets
    prev_count, prev_bound = 0, 0.0
    for bound, count in zip(bounds, counts):
        if count >= rank:
            if count == prev_count:
                return bound
            return prev_bound + (bound - prev_bound) * (
                (rank - prev_count) / (count - prev_count)
            )
        prev_count, prev_bound = count, bound
    return bounds[-1]


class _PhaseSpan:
    """One active phase on the thread's stack.  Exclusive timing: when a
    child phase enters it pauses this span (accumulating elapsed time up
    to the child's start); when the child exits this span resumes."""

    __slots__ = ("_profiler", "name", "_elapsed", "_resumed_at")

    def __init__(self, profiler: "PhaseProfiler", name: str):
        self._profiler = profiler
        self.name = name
        self._elapsed = 0.0
        self._resumed_at = 0.0

    def __enter__(self) -> "_PhaseSpan":
        now = clock()
        stack = self._profiler._stack()
        if stack:
            stack[-1]._pause(now)
        self._resumed_at = now
        stack.append(self)
        return self

    def __exit__(self, *exc) -> None:
        now = clock()
        stack = self._profiler._stack()
        stack.pop()
        self._elapsed += now - self._resumed_at
        self._profiler._observe_phase(self.name, self._elapsed)
        if stack:
            stack[-1]._resumed_at = now

    def _pause(self, now: float) -> None:
        self._elapsed += now - self._resumed_at
        self._resumed_at = now


class PhaseProfiler:
    """Phase timers, scan accounting, and watch-propagation latency,
    all feeding one ``metrics.Registry``.  Construct via
    ``profiler_for(registry)`` so components sharing a registry share
    the profiler (duplicate registration would corrupt /metrics)."""

    def __init__(self, registry: metrics.Registry):
        self.phase_duration = metrics.new_histogram(
            "tpu_operator_profile_phase_duration_seconds",
            "Exclusive time spent per named control-plane phase",
            ("phase",),
            registry,
            buckets=LATENCY_BUCKETS,
        )
        self.scan_objects = metrics.new_counter(
            "tpu_operator_profile_cache_scan_objects_total",
            "Objects touched by full cache/store scans, by scan scope",
            ("scope",),
            registry,
        )
        self.scan_passes = metrics.new_counter(
            "tpu_operator_profile_cache_scan_passes_total",
            "Full cache/store scan passes, by scan scope",
            ("scope",),
            registry,
        )
        self.watch_propagation = metrics.new_histogram(
            "tpu_operator_profile_watch_propagation_seconds",
            "Latency from apiserver event emission to each pipeline stage",
            ("stage",),
            registry,
            buckets=LATENCY_BUCKETS,
        )
        self._lock = locktrace.lock("profiler")
        self._local = threading.local()
        self._pass_count = 0
        self._pass_seconds = 0.0
        self._scan_scopes: set[str] = set()
        # key -> earliest emitted_at of the events that dirtied it, popped
        # when the controller dequeues the key.
        self._pending_events: dict[str, float] = {}

    # -- phase timing ---------------------------------------------------

    def _stack(self) -> list:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def phase(self, name: str) -> _PhaseSpan:
        """``with profiler.phase(profiling.PHASE_RENDER): ...``"""
        if name not in PHASES:
            raise ValueError(
                f"unknown profiling phase {name!r}; register it in "
                "profiling.PHASES"
            )
        return _PhaseSpan(self, name)

    def profiled(self, name: str) -> Callable:
        """Decorator form of ``phase``."""

        def decorate(fn: Callable) -> Callable:
            @functools.wraps(fn)
            def wrapper(*args, **kwargs):
                with self.phase(name):
                    return fn(*args, **kwargs)

            return wrapper

        return decorate

    def _observe_phase(self, name: str, seconds: float) -> None:
        self.phase_duration.observe(max(seconds, 0.0), name)

    def observe_pass(self, seconds: float) -> None:
        """Record one whole reconcile pass; the denominator for phase
        shares in ``snapshot()``."""
        with self._lock:
            self._pass_count += 1
            self._pass_seconds += max(seconds, 0.0)

    # -- cache scan accounting ------------------------------------------

    def record_scan(self, scope: str, objects: int) -> None:
        """One full scan over ``objects`` objects under ``scope`` (a
        resource or component name, not a phase)."""
        self.scan_passes.inc(1.0, scope)
        self.scan_objects.inc(float(objects), scope)
        with self._lock:
            self._scan_scopes.add(scope)

    # -- watch-to-reconcile latency -------------------------------------

    def observe_delivery(self, emitted_at: Optional[float]) -> None:
        """Informer pump delivered an event stamped at ``emitted_at``."""
        if emitted_at is None:
            return
        self.watch_propagation.observe(
            max(clock() - emitted_at, 0.0), STAGE_DELIVERED
        )

    def note_event(self, key: str, emitted_at: Optional[float]) -> None:
        """An event stamped at ``emitted_at`` enqueued ``key``.  Keeps
        the earliest stamp per key: a burst of events coalesced by the
        workqueue is attributed to the first event that went unserved."""
        if emitted_at is None:
            return
        with self._lock:
            prior = self._pending_events.get(key)
            if prior is None or emitted_at < prior:
                self._pending_events[key] = emitted_at

    def observe_dequeue(self, key: str) -> None:
        """The controller dequeued ``key``; close out the propagation
        measurement for the event(s) that produced it."""
        with self._lock:
            emitted_at = self._pending_events.pop(key, None)
        if emitted_at is not None:
            self.watch_propagation.observe(
                max(clock() - emitted_at, 0.0), STAGE_RECONCILE
            )

    # -- snapshot (the /debug/profile payload) --------------------------

    def snapshot(self) -> dict:
        """JSON-friendly summary: per-phase exclusive seconds and counts,
        reconcile-phase shares (summing to ~1.0 with ``unattributed``),
        watch-propagation quantiles, and per-scope scan accounting."""
        with self._lock:
            pass_count = self._pass_count
            pass_seconds = self._pass_seconds
            scopes = sorted(self._scan_scopes)

        phases: dict[str, dict] = {}
        for name in PHASES:
            # One atomic (count, sum) pair per phase: separate accessor
            # calls can tear under concurrent observes (count from after
            # an observe paired with the sum from before it).
            count, seconds = self.phase_duration.sample_stats(name)
            if count == 0:
                continue
            phases[name] = {"count": count, "seconds": seconds}

        reconcile_attributed = sum(
            phases[name]["seconds"]
            for name in RECONCILE_PHASES
            if name in phases
        )
        shares: dict[str, float] = {}
        if pass_seconds > 0:
            for name in RECONCILE_PHASES:
                if name in phases:
                    shares[name] = phases[name]["seconds"] / pass_seconds
            shares[UNATTRIBUTED] = (
                max(pass_seconds - reconcile_attributed, 0.0) / pass_seconds
            )

        propagation: dict[str, dict] = {}
        for stage in PROPAGATION_STAGES:
            # One cumulative read per stage; count and both quantiles
            # derive from the same cut of the histogram.
            counts = self.watch_propagation.cumulative_counts(stage)
            count = counts[-1] if counts else 0
            if count == 0:
                continue
            propagation[stage] = {
                "count": count,
                "p50_seconds": histogram_quantile(
                    self.watch_propagation, 0.50, stage, counts=counts
                ),
                "p99_seconds": histogram_quantile(
                    self.watch_propagation, 0.99, stage, counts=counts
                ),
            }

        scans: dict[str, dict] = {}
        for scope in scopes:
            passes = self.scan_passes.value(scope)
            objects = self.scan_objects.value(scope)
            scans[scope] = {
                "passes": int(passes),
                "objects": int(objects),
                "objects_per_pass": (objects / passes) if passes else 0.0,
            }

        return {
            "reconcile": {"passes": pass_count, "seconds": pass_seconds},
            "phases": phases,
            "reconcile_phase_shares": shares,
            "watch_propagation": propagation,
            "cache_scans": scans,
        }


# ----------------------------------------------------------------------
# One profiler per registry.
# ----------------------------------------------------------------------

_profilers: "weakref.WeakKeyDictionary[metrics.Registry, PhaseProfiler]" = (
    weakref.WeakKeyDictionary()
)
_profilers_lock = threading.Lock()


def profiler_for(registry: metrics.Registry) -> PhaseProfiler:
    """The profiler bound to ``registry``, created on first use.  Callers
    sharing a registry get the same profiler, so metric names register
    exactly once per registry."""
    with _profilers_lock:
        profiler = _profilers.get(registry)
        if profiler is None:
            profiler = PhaseProfiler(registry)
            _profilers[registry] = profiler
        return profiler
