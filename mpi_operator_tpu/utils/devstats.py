"""Fleet device-memory observatory: HBM sampler + per-job MemoryMatrix.

The time-attribution stack (profiler, goodput ledger, step-skew matrix)
is blind to *device memory*: an HBM OOM kills a gang with zero forensics
and no early warning, even though the watermark that predicts it grows
for many windows first.  MLPerf-scale TPU pod training (arxiv
1909.09756) treats HBM headroom as the first-class capacity signal; this
module gives the operator that signal over the same
worker-annotation → informer pipeline the step-skew observatory
(utils/stepstats.py) proved out:

- **worker side** — ``DeviceMemorySampler`` samples per-device HBM at
  each telemetry/heartbeat window (``device.memory_stats()`` with a
  ``live_arrays``-sum fallback and a deterministic fake backend for
  CPU/tests), and utils/telemetry.py emits the sample as a
  ``device_memory`` JSONL record the kubelet sim patches onto the Pod
  as the device-memory annotation;
- **operator side** — ``MemoryMatrix`` joins samples across the gang
  via the pod informer (reusing stepstats' roster/window-closure
  semantics), computes fleet peak/headroom per closed window, runs a
  linear watermark-trend projector, and answers the controller's
  per-sync ``pressure_verdict`` — projected HBM exhaustion within K
  windows raises the ``MemoryPressure`` job condition, recovery flips
  it False;
- **OOM forensics** — when a worker pod dies with the OOM exit code,
  the last joined snapshot is frozen into the flight-recorder timeline
  (kind ``memory``) so the postmortem survives the pod.

Bounds mirror stepstats: tracked jobs are pruned to the flight
recorder's LRU at scrape time (``collect`` also re-derives the
``tpu_operator_job_hbm_peak_bytes`` / ``_headroom_ratio`` gauges), the
per-job window history is a ring, and open windows are capped.  The
monitoring server serves one job's live matrix at
``/debug/jobs/<ns>/<name>/memory``.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Callable, Optional

from ..runtime import locktrace
from . import flightrecorder, metrics
from .stepstats import MAX_OPEN_WINDOW_LAG, MAX_WORKERS_PER_JOB

# Pressure detector defaults: raise MemoryPressure when the linear
# watermark trend projects HBM exhaustion within K closed windows.  K is
# chosen to leave a checkpoint-and-resize window before the OOM lands;
# the trend needs MIN_TREND_WINDOWS points before it projects at all so
# two noisy samples cannot fire the condition.
DEFAULT_PRESSURE_HORIZON_WINDOWS = 6
DEFAULT_TREND_WINDOWS = 8
MIN_TREND_WINDOWS = 3

# Per-job ring: recent closed windows kept for /memory and the trend fit.
DEFAULT_WINDOW_HISTORY = 64

# The OOM-killer exit signature (128 + SIGKILL) — kubelet reports the
# same code for container OOMKilled; the reaper (runtime/podrunner.py)
# surfaces it in containerStatuses.
OOM_EXIT_CODE = 137

# Deterministic fake-backend defaults: one v5e chip's HBM.
DEFAULT_FAKE_LIMIT_BYTES = 16 * 1024**3
DEFAULT_FAKE_BASE_BYTES = 4 * 1024**3


# -- worker side ---------------------------------------------------------


class FakeMemoryBackend:
    """Deterministic ``device.memory_stats()`` stand-in for CPU and
    tests: a fixed base footprint plus an optional window-periodic
    ripple, a pure function of the window index so same-seed bench runs
    replay bit-identically."""

    def __init__(
        self,
        *,
        limit_bytes: int = DEFAULT_FAKE_LIMIT_BYTES,
        base_bytes: int = DEFAULT_FAKE_BASE_BYTES,
        ripple_bytes: int = 0,
    ):
        if limit_bytes <= 0:
            raise ValueError(f"limit_bytes must be > 0, got {limit_bytes!r}")
        if not 0 <= base_bytes <= limit_bytes:
            raise ValueError(
                f"base_bytes must be in [0, limit_bytes], got {base_bytes!r}"
            )
        self.limit_bytes = int(limit_bytes)
        self.base_bytes = int(base_bytes)
        self.ripple_bytes = int(ripple_bytes)

    def stats(self, window: int) -> dict:
        # A small deterministic ripple (period 4) models allocator churn
        # without a trend, so the control arm never drifts upward.
        ripple = self.ripple_bytes * ((window % 4) - 1)
        in_use = max(self.base_bytes + ripple, 0)
        return {
            "bytes_in_use": in_use,
            "peak_bytes_in_use": in_use,
            "bytes_limit": self.limit_bytes,
        }


def _leak_bytes_from_env() -> int:
    """The chaos MemoryLeak fault's worker-side half: the injected
    per-window increment (runtime/podrunner.py child env)."""
    import os

    from ..api.v2beta1 import constants

    raw = os.environ.get(constants.ENV_MEM_LEAK_BYTES, "")
    if not raw:
        return 0
    try:
        return max(int(raw), 0)
    except ValueError:
        return 0


def _compile_cache_entries() -> int:
    """Best-effort size of jax's jit lowering cache — a proxy for
    compile-time memory the serving tier will budget against.  Gated:
    any jax-internal drift degrades to 0, never an exception."""
    try:
        from jax._src import pjit  # type: ignore

        return int(pjit._create_pjit_jaxpr.cache_info().currsize)
    except Exception:
        return 0


class DeviceMemorySampler:
    """Per-window HBM watermark sampler for the training worker.

    Resolution order per sample: an explicitly injected backend
    (tests/bench), else real ``jax.local_devices()[i].memory_stats()``
    summed per stat, else the ``jax.live_arrays()`` byte sum (limit
    unknown → 0, so the matrix reports watermarks but never projects
    exhaustion from them).  The chaos leak increment
    (``TPU_MEM_LEAK_BYTES``) inflates the *reported* bytes-in-use by
    ``leak × (window + 1)`` — the detector path sees a real linear
    trend without the worker allocating anything.
    """

    def __init__(
        self,
        *,
        backend: Optional[FakeMemoryBackend] = None,
        leak_bytes_per_window: Optional[int] = None,
        compile_cache_fn: Callable[[], int] = _compile_cache_entries,
    ):
        self._backend = backend
        self._leak = (
            _leak_bytes_from_env()
            if leak_bytes_per_window is None
            else max(int(leak_bytes_per_window), 0)
        )
        self._compile_cache_fn = compile_cache_fn
        self._peak = 0

    @property
    def leak_bytes_per_window(self) -> int:
        return self._leak

    def _device_stats(self) -> dict:
        try:
            import jax

            devices = jax.local_devices()
        except Exception:
            return {"bytes_in_use": 0, "peak_bytes_in_use": 0,
                    "bytes_limit": 0}
        in_use = peak = limit = 0
        have_stats = False
        for device in devices:
            try:
                stats = device.memory_stats()
            except Exception:
                stats = None
            if not stats:
                continue
            have_stats = True
            in_use += int(stats.get("bytes_in_use", 0) or 0)
            peak += int(stats.get("peak_bytes_in_use", 0) or 0)
            limit += int(stats.get("bytes_limit", 0) or 0)
        if have_stats:
            return {"bytes_in_use": in_use, "peak_bytes_in_use": peak,
                    "bytes_limit": limit}
        # CPU backend: no allocator stats; the live-array byte sum is
        # the honest lower bound (limit unknown).
        try:
            import jax

            live = sum(int(x.nbytes) for x in jax.live_arrays())
        except Exception:
            live = 0
        return {"bytes_in_use": live, "peak_bytes_in_use": live,
                "bytes_limit": 0}

    def sample(self, window: int) -> dict:
        """One ``device_memory`` record for a closed telemetry window."""
        window = int(window)
        if self._backend is not None:
            stats = self._backend.stats(window)
        else:
            stats = self._device_stats()
        in_use = int(stats.get("bytes_in_use", 0) or 0)
        peak = int(stats.get("peak_bytes_in_use", 0) or 0)
        limit = int(stats.get("bytes_limit", 0) or 0)
        if self._leak:
            in_use += self._leak * (window + 1)
        self._peak = max(self._peak, peak, in_use)
        try:
            cache_entries = int(self._compile_cache_fn())
        except Exception:
            cache_entries = 0
        return {
            "event": "device_memory",
            "window": window,
            "hbm_bytes_in_use": in_use,
            "hbm_peak_bytes": self._peak,
            "hbm_limit_bytes": limit,
            "compile_cache_entries": cache_entries,
        }


# -- operator side -------------------------------------------------------


def _roster_entry(worker: str, pod: str) -> dict:
    """Membership placeholder for a worker the informer has seen but
    that has not reported a device-memory sample yet (window -1 orders
    before any real sample)."""
    return {
        "worker": worker,
        "hostname": "",
        "pod": pod,
        "window": -1,
        "hbm_bytes_in_use": 0,
        "hbm_peak_bytes": 0,
        "hbm_limit_bytes": 0,
        "compile_cache_entries": 0,
    }


def _slope(points: list[tuple[float, float]]) -> float:
    """Least-squares slope of y over x — bytes per window for the
    watermark trend."""
    n = len(points)
    mean_x = sum(x for x, _ in points) / n
    mean_y = sum(y for _, y in points) / n
    denom = sum((x - mean_x) ** 2 for x, _ in points)
    if denom <= 0:
        return 0.0
    num = sum((x - mean_x) * (y - mean_y) for x, y in points)
    return num / denom


class _JobMemory:
    """One job's join state: latest sample per worker, open windows
    awaiting the full gang, closed-window ring, projector state."""

    __slots__ = (
        "workers", "open_windows", "closed", "pressure",
        "projected_windows", "frozen", "last_closed_window",
    )

    def __init__(self, history: int):
        self.workers: dict[str, dict] = {}
        self.open_windows: dict[int, dict[str, dict]] = {}
        self.closed: deque = deque(maxlen=history)
        self.pressure = False
        self.projected_windows: Optional[float] = None
        self.frozen: set[str] = set()  # workers already OOM-frozen
        self.last_closed_window = -1


class MemoryMatrix:
    """Joins per-worker device-memory samples into per-job fleet
    watermarks, a linear exhaustion projection, and OOM forensics.

    ``observe_pod`` is the single write path (wired as a pod informer
    handler); everything else reads.  All numbers derive from sample
    content, never wall clocks, so a simulated-clock bench replays
    bit-identically.
    """

    def __init__(
        self,
        flight_recorder: flightrecorder.FlightRecorder,
        registry: Optional[metrics.Registry] = None,
        clock: Callable[[], float] = time.time,
        *,
        pressure_horizon_windows: int = DEFAULT_PRESSURE_HORIZON_WINDOWS,
        trend_windows: int = DEFAULT_TREND_WINDOWS,
        window_history: int = DEFAULT_WINDOW_HISTORY,
    ):
        if pressure_horizon_windows < 1:
            raise ValueError(
                f"pressure_horizon_windows must be >= 1, "
                f"got {pressure_horizon_windows!r}"
            )
        if trend_windows < MIN_TREND_WINDOWS:
            raise ValueError(
                f"trend_windows must be >= {MIN_TREND_WINDOWS}, "
                f"got {trend_windows!r}"
            )
        self._recorder = flight_recorder
        self._clock = clock
        self.pressure_horizon_windows = pressure_horizon_windows
        self.trend_windows = trend_windows
        self._history = max(window_history, trend_windows)
        self._lock = locktrace.lock("devstats")
        self._jobs: dict[tuple[str, str], _JobMemory] = {}

        self.hbm_peak = None
        if registry is not None:
            self.hbm_peak = metrics.new_gauge(
                "tpu_operator_job_hbm_peak_bytes",
                "Fleet HBM peak bytes per TPUJob (max worker peak over "
                "the latest joined device-memory window)",
                ("namespace", "tpujob"),
                registry,
            )
            self.hbm_headroom = metrics.new_gauge(
                "tpu_operator_job_hbm_headroom_ratio",
                "Fleet HBM headroom per TPUJob ((limit - in_use) / limit "
                "for the worst worker in the latest joined window; 1.0 "
                "when the limit is unknown)",
                ("namespace", "tpujob"),
                registry,
            )
            registry.on_scrape(self.collect)

    # -- write path ------------------------------------------------------

    def observe_pod(self, pod: dict) -> None:
        """Fold one pod event into the owning job's matrix.

        Mirrors stepstats.StepMatrix.observe_pod: worker pods without a
        device-memory annotation still register gang membership, a
        terminal pod leaves the roster, and folds are idempotent per
        (worker, window).  Additionally, a terminal pod carrying the OOM
        exit code freezes the last joined snapshot into the flight
        recorder before the roster forgets it."""
        import json

        from ..api.v2beta1 import constants

        meta = pod.get("metadata") or {}
        labels = meta.get("labels") or {}
        job_name = labels.get(constants.JOB_NAME_LABEL)
        if not job_name:
            return
        if labels.get(constants.JOB_ROLE_LABEL) != constants.ROLE_WORKER:
            return
        namespace = meta.get("namespace", "")
        worker = labels.get(constants.REPLICA_INDEX_LABEL)
        if worker is None:
            worker = meta.get("name", "")
        worker = str(worker)
        phase = (pod.get("status") or {}).get("phase", "")
        terminal = phase in ("Succeeded", "Failed")

        raw = (meta.get("annotations") or {}).get(
            constants.DEVICE_MEMORY_ANNOTATION
        )
        if not raw:
            with self._lock:
                job = self._jobs.get((namespace, job_name))
                if terminal:
                    if job is not None:
                        self._freeze_if_oom(
                            namespace, job_name, job, worker, pod
                        )
                        if worker in job.workers:
                            del job.workers[worker]
                            self._close_ready_windows(job)
                    return
                if job is None:
                    job = self._jobs[(namespace, job_name)] = _JobMemory(
                        self._history
                    )
                if (
                    worker not in job.workers
                    and len(job.workers) < MAX_WORKERS_PER_JOB
                ):
                    job.workers[worker] = _roster_entry(
                        worker, meta.get("name", "")
                    )
            return
        try:
            record = json.loads(raw)
        except ValueError:
            return
        if not isinstance(record, dict):
            return
        window = record.get("window")
        in_use = record.get("hbm_bytes_in_use")
        if not isinstance(window, int) or not isinstance(
            in_use, (int, float)
        ):
            return

        sample = {
            "worker": worker,
            "hostname": str(record.get("hostname", "")),
            "pod": meta.get("name", ""),
            "window": window,
            "hbm_bytes_in_use": int(in_use),
            "hbm_peak_bytes": int(
                record.get("hbm_peak_bytes", in_use) or in_use
            ),
            "hbm_limit_bytes": int(record.get("hbm_limit_bytes", 0) or 0),
            "compile_cache_entries": int(
                record.get("compile_cache_entries", 0) or 0
            ),
        }
        with self._lock:
            job = self._jobs.get((namespace, job_name))
            if job is None:
                job = self._jobs[(namespace, job_name)] = _JobMemory(
                    self._history
                )
            known = job.workers.get(worker)
            if known is not None and known["window"] >= window:
                if terminal:
                    self._freeze_if_oom(namespace, job_name, job, worker, pod)
                    if worker in job.workers:
                        del job.workers[worker]
                        self._close_ready_windows(job)
                return  # stale or duplicate delivery
            if known is None and len(job.workers) >= MAX_WORKERS_PER_JOB:
                return
            job.workers[worker] = sample
            if window > job.last_closed_window:
                job.open_windows.setdefault(window, {})[worker] = sample
            if terminal:
                # The final flush of a finished worker: fold it, freeze
                # the OOM postmortem if that is how it died, then leave
                # the roster so later windows can close without it.
                self._freeze_if_oom(namespace, job_name, job, worker, pod)
                del job.workers[worker]
            self._close_ready_windows(job)

    @staticmethod
    def _is_oom(pod: dict) -> bool:
        for cs in (pod.get("status") or {}).get("containerStatuses") or []:
            terminated = (cs.get("state") or {}).get("terminated") or {}
            if terminated.get("exitCode") == OOM_EXIT_CODE:
                return True
            if terminated.get("reason") == "OOMKilled":
                return True
        return False

    def _freeze_if_oom(
        self,
        namespace: str,
        job_name: str,
        job: _JobMemory,
        worker: str,
        pod: dict,
    ) -> None:
        """OOM forensics: freeze the last joined fleet snapshot (plus
        the dying worker's own last sample) into the flight-recorder
        timeline, once per worker.  Caller holds the lock."""
        if worker in job.frozen or not self._is_oom(pod):
            return
        job.frozen.add(worker)
        attrs: dict = {"worker": worker}
        last = job.workers.get(worker)
        if last is not None:
            attrs["worker_window"] = last["window"]
            attrs["worker_hbm_bytes_in_use"] = last["hbm_bytes_in_use"]
            attrs["worker_hbm_peak_bytes"] = last["hbm_peak_bytes"]
        if job.closed:
            fleet = job.closed[-1]
            attrs["window"] = fleet["window"]
            attrs["hbm_bytes_in_use"] = fleet["hbm_bytes_in_use"]
            attrs["hbm_peak_bytes"] = fleet["hbm_peak_bytes"]
            attrs["hbm_limit_bytes"] = fleet["hbm_limit_bytes"]
            attrs["headroom_ratio"] = fleet["headroom_ratio"]
            attrs["top_worker"] = fleet["top_worker"]
        pod_name = ((pod.get("metadata") or {}).get("name", ""))
        self._recorder.record(
            namespace,
            job_name,
            flightrecorder.MEMORY,
            reason="OOMKilled",
            message=(
                f"worker {worker} (pod {pod_name}) died with the OOM exit "
                f"code {OOM_EXIT_CODE}; last joined device-memory snapshot "
                f"frozen"
            ),
            **attrs,
        )

    def _close_ready_windows(self, job: _JobMemory) -> None:
        """stepstats' closure contract verbatim: close every open window
        the whole known gang has reported, plus any window lagging more
        than MAX_OPEN_WINDOW_LAG behind the newest; windows close in
        order.  Caller holds the lock."""
        if not job.open_windows:
            return
        newest = max(job.open_windows)
        for window in sorted(job.open_windows):
            members = job.open_windows[window]
            full = len(members) >= len(job.workers)
            lagged = window <= newest - MAX_OPEN_WINDOW_LAG
            if not (full or lagged):
                break
            del job.open_windows[window]
            if members:
                self._close_window(job, window, members)
            job.last_closed_window = max(job.last_closed_window, window)

    def _close_window(
        self, job: _JobMemory, window: int, members: dict[str, dict]
    ) -> None:
        """One joined window: fleet watermark (worst worker), tightest
        limit, headroom, then re-run the trend projector.  Caller holds
        the lock."""
        top = max(
            sorted(members), key=lambda w: members[w]["hbm_bytes_in_use"]
        )
        in_use = members[top]["hbm_bytes_in_use"]
        peak = max(s["hbm_peak_bytes"] for s in members.values())
        limits = [
            s["hbm_limit_bytes"]
            for s in members.values()
            if s["hbm_limit_bytes"] > 0
        ]
        limit = min(limits) if limits else 0
        headroom = (
            round((limit - in_use) / limit, 6) if limit > 0 else 1.0
        )
        job.closed.append({
            "window": window,
            "workers": len(members),
            "hbm_bytes_in_use": in_use,
            "hbm_peak_bytes": peak,
            "hbm_limit_bytes": limit,
            "headroom_ratio": headroom,
            "top_worker": top,
        })
        self._project(job)

    def _project(self, job: _JobMemory) -> None:
        """Linear watermark-trend projector over the recent closed
        windows: windows-to-exhaustion = headroom / slope.  Needs
        MIN_TREND_WINDOWS limit-bearing points and a rising trend;
        otherwise no projection and no pressure.  Caller holds the
        lock."""
        recent = [
            w for w in list(job.closed)[-self.trend_windows:]
            if w["hbm_limit_bytes"] > 0
        ]
        if len(recent) < MIN_TREND_WINDOWS:
            job.pressure = False
            job.projected_windows = None
            return
        latest = recent[-1]
        remaining = latest["hbm_limit_bytes"] - latest["hbm_bytes_in_use"]
        if remaining <= 0:
            job.pressure = True
            job.projected_windows = 0.0
            return
        points = [
            (float(w["window"]), float(w["hbm_bytes_in_use"]))
            for w in recent
        ]
        slope = _slope(points)
        if slope <= 0:
            job.pressure = False
            job.projected_windows = None
            return
        projected = remaining / slope
        job.projected_windows = round(projected, 3)
        job.pressure = projected <= self.pressure_horizon_windows

    # -- read paths ------------------------------------------------------

    def pressure_verdict(self, namespace: str, name: str) -> Optional[dict]:
        """The controller's per-sync question: None when the matrix has
        no joined windows for the job yet (insufficient data — say
        nothing); else whether the trend projects exhaustion within the
        horizon, how soon, and who is at the watermark."""
        with self._lock:
            job = self._jobs.get((namespace, name))
            if job is None or not job.closed:
                return None
            latest = job.closed[-1]
            return {
                "pressure": job.pressure,
                "projected_windows": job.projected_windows,
                "headroom_ratio": latest["headroom_ratio"],
                "hbm_peak_bytes": latest["hbm_peak_bytes"],
                "hbm_limit_bytes": latest["hbm_limit_bytes"],
                "top_worker": latest["top_worker"],
                "window": latest["window"],
            }

    def job_snapshot(self, namespace: str, name: str) -> Optional[dict]:
        """The ``/debug/jobs/<ns>/<name>/memory`` payload, or None when
        the job has never produced a sample (the endpoint's 404)."""
        with self._lock:
            job = self._jobs.get((namespace, name))
            if job is None:
                return None
            latest = job.closed[-1] if job.closed else None
            return {
                "namespace": namespace,
                "name": name,
                "pressure": job.pressure,
                "projected_windows": job.projected_windows,
                "pressure_horizon_windows": self.pressure_horizon_windows,
                "hbm_peak_bytes": (
                    latest["hbm_peak_bytes"] if latest else 0
                ),
                "hbm_limit_bytes": (
                    latest["hbm_limit_bytes"] if latest else 0
                ),
                "headroom_ratio": (
                    latest["headroom_ratio"] if latest else 1.0
                ),
                "top_worker": latest["top_worker"] if latest else None,
                "oom_workers": sorted(job.frozen),
                "workers": {
                    worker: dict(sample)
                    for worker, sample in sorted(job.workers.items())
                },
                "windows": list(job.closed),
            }

    def jobs(self) -> list:
        with self._lock:
            return list(self._jobs.keys())

    def __len__(self) -> int:
        with self._lock:
            return len(self._jobs)

    # -- scrape hook -----------------------------------------------------

    def collect(self) -> None:
        """Scrape-time recompute + pruning (the stepstats contract): the
        HBM gauges are re-derived from live state with stale series
        dropped, and any job the flight recorder has LRU-evicted loses
        its matrix too."""
        known = set(self._recorder.jobs())
        with self._lock:
            for key in [k for k in self._jobs if k not in known]:
                del self._jobs[key]
            latest = {
                key: job.closed[-1]
                for key, job in self._jobs.items()
                if job.closed
            }
        if self.hbm_peak is None:
            return
        self.hbm_peak.remove_matching()
        self.hbm_headroom.remove_matching()
        for (namespace, name), window in latest.items():
            self.hbm_peak.set(
                float(window["hbm_peak_bytes"]), namespace, name
            )
            self.hbm_headroom.set(
                float(window["headroom_ratio"]), namespace, name
            )
