"""Bounded per-job flight recorder: one ordered timeline per TPUJob.

Kubernetes has no single object that answers "what happened to this job,
in order" — you reconstruct it by joining Events, status conditions, and
pod phases by hand, and Events expire after an hour.  The flight recorder
maintains that join live, in memory, bounded: every condition transition
(controller), recorded Event (utils/events subscription), scheduling
decision (scheduler core), and pod phase flip (podrunner) lands as one
timeline entry under the owning job, and the monitoring server serves it
as JSON at ``/debug/jobs/<ns>/<name>/timeline``.

Bounds: a ring buffer per job (``capacity_per_job``) and an LRU cap on
the number of jobs tracked (``max_jobs``) — a long-running operator keeps
recent history for recent jobs and nothing grows without limit.  Entries
survive job deletion (post-mortem is the whole point) until evicted.
"""

from __future__ import annotations

import itertools
import json
import threading
import time
from collections import OrderedDict, deque
from typing import Optional

DEFAULT_CAPACITY_PER_JOB = 256
DEFAULT_MAX_JOBS = 256

# Entry kinds (the subscribed sources).
CONDITION = "condition"
EVENT = "event"
SCHEDULING = "scheduling"
POD = "pod"
# Chaos faults targeting a job's workers (chaos/podchaos.py injectors)
# land on the victim job's timeline under the engine's fault-kind
# vocabulary, and the device-memory observatory (utils/devstats.py)
# freezes its last joined snapshot as a MEMORY entry when a pod dies
# with the OOM exit code.
SLOW_WORKER = "slow_worker"
MEM_LEAK = "mem_leak"
MEMORY = "memory"
TORN_WRITE = "torn_write"
KINDS = (
    CONDITION, EVENT, SCHEDULING, POD, SLOW_WORKER, MEM_LEAK, MEMORY,
    TORN_WRITE,
)


class FlightRecorder:
    def __init__(
        self,
        capacity_per_job: int = DEFAULT_CAPACITY_PER_JOB,
        max_jobs: int = DEFAULT_MAX_JOBS,
        clock=time.time,
    ):
        self._capacity = capacity_per_job
        self._max_jobs = max_jobs
        self._clock = clock
        self._lock = threading.Lock()
        # Insertion/touch order == LRU order for job eviction.
        self._jobs: "OrderedDict[tuple[str, str], deque]" = OrderedDict()
        # Monotonic order key: entries sort stably even when the clock's
        # resolution collapses adjacent timestamps.
        self._seq = itertools.count(1)

    def record(
        self,
        namespace: str,
        name: str,
        kind: str,
        reason: str = "",
        message: str = "",
        **attrs,
    ) -> dict:
        entry = {
            "seq": next(self._seq),
            "ts": round(self._clock(), 6),
            "kind": kind,
            "reason": reason,
            "message": message,
        }
        for k, v in attrs.items():
            # JSON-safe like span attrs: repr anything exotic.
            entry[k] = (
                v if isinstance(v, (str, int, float, bool, type(None)))
                else repr(v)
            )
        with self._lock:
            timeline = self._jobs.get((namespace, name))
            if timeline is None:
                timeline = self._jobs[(namespace, name)] = deque(
                    maxlen=self._capacity
                )
                while len(self._jobs) > self._max_jobs:
                    self._jobs.popitem(last=False)
            else:
                self._jobs.move_to_end((namespace, name))
            timeline.append(entry)
        return entry

    def observe_event(self, ev) -> None:
        """utils/events.EventRecorder subscriber: fold recorded Events for
        TPUJob-kind involved objects into the owning job's timeline."""
        if getattr(ev, "involved_kind", "") != "TPUJob":
            return
        self.record(
            ev.involved_namespace,
            ev.involved_name,
            EVENT,
            reason=ev.reason,
            message=ev.message,
            type=ev.type,
            count=getattr(ev, "count", 1),
        )

    def timeline(
        self,
        namespace: str,
        name: str,
        *,
        kind: Optional[str] = None,
        limit: Optional[int] = None,
    ) -> Optional[list]:
        """Ordered entries for one job; None when the job was never seen
        (distinguishes 404 from an empty-but-known timeline).  ``kind``
        keeps only entries of that kind; ``limit`` keeps the *newest* N
        after filtering (the tail is what post-mortems read first)."""
        with self._lock:
            timeline = self._jobs.get((namespace, name))
            if timeline is None:
                return None
            entries = list(timeline)
        if kind is not None:
            entries = [e for e in entries if e.get("kind") == kind]
        if limit is not None and limit >= 0:
            entries = entries[-limit:] if limit > 0 else []
        return entries

    def timeline_object(
        self,
        namespace: str,
        name: str,
        *,
        kind: Optional[str] = None,
        limit: Optional[int] = None,
    ) -> Optional[dict]:
        entries = self.timeline(namespace, name, kind=kind, limit=limit)
        if entries is None:
            return None
        return {"namespace": namespace, "name": name, "entries": entries}

    def to_json(
        self,
        namespace: str,
        name: str,
        *,
        kind: Optional[str] = None,
        limit: Optional[int] = None,
    ) -> Optional[str]:
        obj = self.timeline_object(namespace, name, kind=kind, limit=limit)
        return None if obj is None else json.dumps(obj, sort_keys=True)

    def jobs(self) -> list:
        with self._lock:
            return list(self._jobs.keys())

    def forget(self, namespace: str, name: str) -> None:
        with self._lock:
            self._jobs.pop((namespace, name), None)

    def __len__(self) -> int:
        with self._lock:
            return len(self._jobs)
