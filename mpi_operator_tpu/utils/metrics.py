"""Prometheus-style metrics registry (no external dependency).

Reference analog: the promauto counters/gauges in
/root/reference/v2/pkg/controller/mpi_job_controller.go:120-136 and the
/metrics endpoint in v2/cmd/mpi-operator/main.go:29-40.  Same metric names
with the ``tpu_operator_`` prefix, exposed in Prometheus text format.

Three metric kinds:

- ``Counter``: monotonic, with ``mirror_total`` for externally-owned totals;
- ``Gauge``: settable, with per-label-set removal (stale-series control);
- ``Histogram``: cumulative buckets + ``_sum``/``_count`` in the upstream
  client_golang layout (``le`` label, ``+Inf`` bucket), the substrate for
  every latency metric (workqueue, reconcile, train-step).

Naming contract (enforced by tests/test_lint.py): every registered name
starts with ``tpu_operator_``, counters end in ``_total``, histograms in
``_seconds``.
"""

from __future__ import annotations

import threading
from typing import Optional, Sequence

# client_golang's prometheus.DefBuckets: tuned for request latencies in
# seconds, which is exactly what every histogram here measures.
DEFAULT_BUCKETS = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)


def escape_label_value(value: str) -> str:
    """Text exposition format escaping for label values: backslash,
    double-quote, and line feed must be escaped (in that order, so the
    backslashes the other two introduce are not re-escaped)."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def escape_help(text: str) -> str:
    """HELP lines escape backslash and line feed (not double-quote)."""
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


def _format_value(value: float) -> str:
    if value == float("inf"):
        return "+Inf"
    return str(value)


class _Metric:
    def __init__(self, name: str, help_: str, registry: Optional["Registry"]):
        self.name = name
        self.help = help_
        self._lock = threading.Lock()
        self._values: dict[tuple[str, ...], float] = {}
        self._label_names: tuple[str, ...] = ()
        if registry is not None:
            registry.register(self)

    def _set_labels(self, label_names: tuple[str, ...]) -> None:
        self._label_names = tuple(label_names)

    @property
    def label_names(self) -> tuple[str, ...]:
        return self._label_names

    def _samples(self) -> list[tuple[tuple[str, ...], float]]:
        with self._lock:
            if not self._values and not self._label_names:
                return [((), 0.0)]
            return sorted(self._values.items())

    def _label_str(self, labels: Sequence[str]) -> str:
        return ",".join(
            f'{n}="{escape_label_value(v)}"'
            for n, v in zip(self._label_names, labels)
        )

    def expose(self) -> str:
        lines = [
            f"# HELP {self.name} {escape_help(self.help)}",
            f"# TYPE {self.name} {self.kind}",
        ]
        for labels, value in self._samples():
            if labels:
                lines.append(f"{self.name}{{{self._label_str(labels)}}} {value}")
            else:
                lines.append(f"{self.name} {value}")
        return "\n".join(lines)


class Counter(_Metric):
    kind = "counter"

    def inc(self, amount: float = 1.0, *labels: str) -> None:
        with self._lock:
            self._values[labels] = self._values.get(labels, 0.0) + amount

    def mirror_total(self, value: float, *labels: str) -> None:
        """Overwrite with an externally-accumulated monotonic total (a
        counter whose source of truth lives elsewhere, e.g. the REST
        client's retry count, mirrored on scrape). Never decreases —
        counter semantics survive a racy double-set."""
        with self._lock:
            if value > self._values.get(labels, 0.0):
                self._values[labels] = value

    def value(self, *labels: str) -> float:
        with self._lock:
            return self._values.get(labels, 0.0)


class Gauge(_Metric):
    kind = "gauge"

    def labels(self, *label_values: str) -> "_GaugeView":
        return _GaugeView(self, label_values)

    def set(self, value: float, *labels: str) -> None:
        with self._lock:
            self._values[labels] = value

    def remove(self, *labels: str) -> None:
        """Drop a label set (prevents unbounded stale series)."""
        with self._lock:
            self._values.pop(labels, None)

    def remove_matching(self, *label_prefix: str) -> None:
        """Drop every series whose leading label values equal the given
        prefix — the bulk form of ``remove`` for when the caller knows
        the identity labels (namespace, job) but not the tail (e.g.
        condition type)."""
        with self._lock:
            for labels in [
                ls
                for ls in self._values
                if ls[: len(label_prefix)] == label_prefix
            ]:
                del self._values[labels]

    def value(self, *labels: str) -> float:
        with self._lock:
            return self._values.get(labels, 0.0)


class _GaugeView:
    def __init__(self, gauge: Gauge, label_values: tuple[str, ...]):
        self._gauge = gauge
        self._labels = label_values

    def set(self, value: float) -> None:
        self._gauge.set(value, *self._labels)


class _HistogramSeries:
    __slots__ = ("bucket_counts", "sum", "count")

    def __init__(self, n_buckets: int):
        self.bucket_counts = [0] * n_buckets  # per-bucket, not cumulative
        self.sum = 0.0
        self.count = 0


class Histogram(_Metric):
    """Cumulative histogram (client_golang layout).

    Exposes ``<name>_bucket{...,le="..."}`` series (cumulative, ending in
    ``le="+Inf"``), ``<name>_sum`` and ``<name>_count`` per label set.
    ``observe`` is O(log buckets); buckets are fixed at construction.
    """

    kind = "histogram"

    def __init__(
        self,
        name: str,
        help_: str,
        registry: Optional["Registry"],
        buckets: Sequence[float] = DEFAULT_BUCKETS,
    ):
        super().__init__(name, help_, registry)
        bounds = sorted(set(float(b) for b in buckets))
        if bounds and bounds[-1] == float("inf"):
            bounds.pop()  # +Inf is implicit
        if not bounds:
            raise ValueError("histogram needs at least one finite bucket")
        self._bounds: tuple[float, ...] = tuple(bounds)
        self._series: dict[tuple[str, ...], _HistogramSeries] = {}

    @property
    def buckets(self) -> tuple[float, ...]:
        return self._bounds

    def observe(self, value: float, *labels: str) -> None:
        import bisect

        idx = bisect.bisect_left(self._bounds, value)
        with self._lock:
            series = self._series.get(labels)
            if series is None:
                series = self._series[labels] = _HistogramSeries(
                    len(self._bounds) + 1
                )
            series.bucket_counts[idx] += 1
            series.sum += value
            series.count += 1

    def time(self, *labels: str) -> "_HistogramTimer":
        """``with hist.time("label"): ...`` observes the block's wall time."""
        return _HistogramTimer(self, labels)

    # -- accessors (tests/debugging) ------------------------------------

    def sample_sum(self, *labels: str) -> float:
        with self._lock:
            series = self._series.get(labels)
            return series.sum if series else 0.0

    def sample_count(self, *labels: str) -> int:
        with self._lock:
            series = self._series.get(labels)
            return series.count if series else 0

    def sample_stats(self, *labels: str) -> tuple[int, float]:
        """(count, sum) from ONE lock acquisition.  Reading the two
        separate accessors back-to-back can pair a newer count with an
        older sum when an observe lands between them — callers deriving
        means or shares need the consistent pair."""
        with self._lock:
            series = self._series.get(labels)
            return (series.count, series.sum) if series else (0, 0.0)

    def cumulative_counts(self, *labels: str) -> list[int]:
        """Bucket counts as exposed: cumulative, last entry == count."""
        with self._lock:
            series = self._series.get(labels)
            counts = series.bucket_counts if series else [0] * (
                len(self._bounds) + 1
            )
            out, running = [], 0
            for c in counts:
                running += c
                out.append(running)
            return out

    def expose(self) -> str:
        lines = [
            f"# HELP {self.name} {escape_help(self.help)}",
            f"# TYPE {self.name} {self.kind}",
        ]
        with self._lock:
            items = sorted(
                (labels, s.bucket_counts[:], s.sum, s.count)
                for labels, s in self._series.items()
            )
        if not items and not self._label_names:
            items = [((), [0] * (len(self._bounds) + 1), 0.0, 0)]
        bounds = list(self._bounds) + [float("inf")]
        for labels, counts, sum_, count in items:
            base = self._label_str(labels)
            running = 0
            for bound, c in zip(bounds, counts):
                running += c
                le = f'le="{_format_value(bound)}"'
                label_str = f"{base},{le}" if base else le
                lines.append(f"{self.name}_bucket{{{label_str}}} {running}")
            suffix = f"{{{base}}}" if base else ""
            lines.append(f"{self.name}_sum{suffix} {sum_}")
            lines.append(f"{self.name}_count{suffix} {count}")
        return "\n".join(lines)


class _HistogramTimer:
    def __init__(self, hist: Histogram, labels: tuple[str, ...]):
        self._hist = hist
        self._labels = labels

    def __enter__(self) -> "_HistogramTimer":
        import time

        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        import time

        self._hist.observe(time.perf_counter() - self._t0, *self._labels)


class Registry:
    def __init__(self):
        self._metrics: list[_Metric] = []
        self._hooks: list = []
        self._lock = threading.Lock()

    def register(self, metric: _Metric) -> None:
        with self._lock:
            self._metrics.append(metric)

    def on_scrape(self, fn) -> None:
        """Run ``fn`` at the top of every ``expose`` — the pull-model
        hook for values that live outside the metric objects (e.g. the
        REST client's retry/throttle counters)."""
        with self._lock:
            self._hooks.append(fn)

    def expose(self) -> str:
        with self._lock:
            hooks = list(self._hooks)
        for fn in hooks:  # outside the lock: hooks may set() metrics
            fn()
        with self._lock:
            return "\n".join(m.expose() for m in self._metrics) + "\n"


DEFAULT_REGISTRY = Registry()


def new_counter(
    name: str,
    help_: str,
    label_names: tuple[str, ...] = (),
    registry: Optional[Registry] = None,
) -> Counter:
    counter = Counter(name, help_, registry or DEFAULT_REGISTRY)
    counter._set_labels(label_names)
    return counter


def new_gauge(
    name: str,
    help_: str,
    label_names: tuple[str, ...] = (),
    registry: Optional[Registry] = None,
) -> Gauge:
    gauge = Gauge(name, help_, registry or DEFAULT_REGISTRY)
    gauge._set_labels(label_names)
    return gauge


def new_histogram(
    name: str,
    help_: str,
    label_names: tuple[str, ...] = (),
    registry: Optional[Registry] = None,
    buckets: Sequence[float] = DEFAULT_BUCKETS,
) -> Histogram:
    hist = Histogram(name, help_, registry or DEFAULT_REGISTRY, buckets=buckets)
    hist._set_labels(label_names)
    return hist
