"""Prometheus-style metrics registry (no external dependency).

Reference analog: the promauto counters/gauges in
/root/reference/v2/pkg/controller/mpi_job_controller.go:120-136 and the
/metrics endpoint in v2/cmd/mpi-operator/main.go:29-40.  Same metric names
with the ``tpu_operator_`` prefix, exposed in Prometheus text format.
"""

from __future__ import annotations

import threading
from typing import Optional


class _Metric:
    def __init__(self, name: str, help_: str, registry: Optional["Registry"]):
        self.name = name
        self.help = help_
        self._lock = threading.Lock()
        self._values: dict[tuple[str, ...], float] = {}
        self._label_names: tuple[str, ...] = ()
        if registry is not None:
            registry.register(self)

    def _set_labels(self, label_names: tuple[str, ...]) -> None:
        self._label_names = label_names

    def _samples(self) -> list[tuple[tuple[str, ...], float]]:
        with self._lock:
            if not self._values and not self._label_names:
                return [((), 0.0)]
            return sorted(self._values.items())

    def expose(self) -> str:
        lines = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} {self.kind}"]
        for labels, value in self._samples():
            if labels:
                label_str = ",".join(
                    f'{n}="{v}"' for n, v in zip(self._label_names, labels)
                )
                lines.append(f"{self.name}{{{label_str}}} {value}")
            else:
                lines.append(f"{self.name} {value}")
        return "\n".join(lines)


class Counter(_Metric):
    kind = "counter"

    def inc(self, amount: float = 1.0, *labels: str) -> None:
        with self._lock:
            self._values[labels] = self._values.get(labels, 0.0) + amount

    def mirror_total(self, value: float, *labels: str) -> None:
        """Overwrite with an externally-accumulated monotonic total (a
        counter whose source of truth lives elsewhere, e.g. the REST
        client's retry count, mirrored on scrape). Never decreases —
        counter semantics survive a racy double-set."""
        with self._lock:
            if value > self._values.get(labels, 0.0):
                self._values[labels] = value

    def value(self, *labels: str) -> float:
        with self._lock:
            return self._values.get(labels, 0.0)


class Gauge(_Metric):
    kind = "gauge"

    def labels(self, *label_values: str) -> "_GaugeView":
        return _GaugeView(self, label_values)

    def set(self, value: float, *labels: str) -> None:
        with self._lock:
            self._values[labels] = value

    def remove(self, *labels: str) -> None:
        """Drop a label set (prevents unbounded stale series)."""
        with self._lock:
            self._values.pop(labels, None)

    def value(self, *labels: str) -> float:
        with self._lock:
            return self._values.get(labels, 0.0)


class _GaugeView:
    def __init__(self, gauge: Gauge, label_values: tuple[str, ...]):
        self._gauge = gauge
        self._labels = label_values

    def set(self, value: float) -> None:
        self._gauge.set(value, *self._labels)


class Registry:
    def __init__(self):
        self._metrics: list[_Metric] = []
        self._hooks: list = []
        self._lock = threading.Lock()

    def register(self, metric: _Metric) -> None:
        with self._lock:
            self._metrics.append(metric)

    def on_scrape(self, fn) -> None:
        """Run ``fn`` at the top of every ``expose`` — the pull-model
        hook for values that live outside the metric objects (e.g. the
        REST client's retry/throttle counters)."""
        with self._lock:
            self._hooks.append(fn)

    def expose(self) -> str:
        with self._lock:
            hooks = list(self._hooks)
        for fn in hooks:  # outside the lock: hooks may set() metrics
            fn()
        with self._lock:
            return "\n".join(m.expose() for m in self._metrics) + "\n"


DEFAULT_REGISTRY = Registry()


def new_counter(name: str, help_: str, registry: Optional[Registry] = None) -> Counter:
    return Counter(name, help_, registry or DEFAULT_REGISTRY)


def new_gauge(
    name: str,
    help_: str,
    label_names: tuple[str, ...] = (),
    registry: Optional[Registry] = None,
) -> Gauge:
    gauge = Gauge(name, help_, registry or DEFAULT_REGISTRY)
    gauge._set_labels(label_names)
    return gauge
