"""Fleet step-skew observatory: the per-job StepMatrix.

Synchronous allreduce training runs at the speed of its slowest host —
the TPU-pod scaling papers (arxiv 1909.09756, 2011.03641) treat
cross-host step-time skew as the first-order scaling loss — yet per-pod
telemetry alone cannot see it: every worker's own step clock looks
healthy while the whole gang waits on one straggler.  The worker side
(utils/telemetry.py) emits windowed ``step_heartbeat`` records; the
kubelet sim (runtime/podrunner.py) patches them onto each worker's Pod
as the step-heartbeat annotation; the ordinary pod informer watch then
delivers them here, where the ``StepMatrix`` joins heartbeats *across*
workers per job:

- **fleet skew** — max/median step-wall ratio per closed window, the
  slowest-host attribution, and the per-window skew histogram
  ``tpu_operator_job_step_skew``;
- **straggler detection** — a worker whose window p50 exceeds
  ``k × median`` for ``windows`` consecutive closed windows is a
  straggler: the controller surfaces the ``Straggling`` job condition
  (+ flight-recorder entry), and ``tpu_operator_job_stragglers`` gauges
  the live count per job;
- **skew-wait attribution** — per closed window, the gang's wall-clock
  excess over the typical worker ((max − median) p50 × steps)
  accumulates as ``skew_wait_seconds``, which the goodput ledger
  (utils/goodput.py) carves out of the job's ``productive`` phase so
  skew is priced, not hidden.

Bounds mirror the goodput ledger's pruning contract: tracked jobs are
bounded by the flight recorder's own LRU (``collect`` drops any job the
recorder no longer knows, and ``remove_matching`` clears its gauge
series), per-job window history is a ring, and open (unjoined) windows
are capped.  The monitoring server serves one job's live matrix at
``/debug/jobs/<ns>/<name>/steps``.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Callable, Optional

from ..runtime import locktrace
from . import flightrecorder, metrics

# Straggler detector defaults: a worker slower than 1.5x the gang median
# for 3 consecutive closed windows is straggling.  k is chosen above
# ordinary jitter (input stalls, GC) but below the 2x the chaos bench
# injects; 3 windows filters one-off hiccups without sitting on a real
# straggler for long.
DEFAULT_SKEW_THRESHOLD = 1.5
DEFAULT_CONSECUTIVE_WINDOWS = 3

# Per-job rings/caps: recent closed windows kept for /steps, open
# windows allowed to lag before force-closing, workers tracked per job.
DEFAULT_WINDOW_HISTORY = 64
MAX_OPEN_WINDOW_LAG = 4
MAX_WORKERS_PER_JOB = 512

# Skew is a unitless max/median ratio >= 1; buckets resolve the region
# around the detection threshold and the chaos factors.
SKEW_BUCKETS = (1.02, 1.05, 1.1, 1.2, 1.35, 1.5, 1.75, 2.0, 3.0, 5.0, 10.0)


def _median(values: list[float]) -> float:
    ordered = sorted(values)
    n = len(ordered)
    mid = n // 2
    if n % 2:
        return ordered[mid]
    return (ordered[mid - 1] + ordered[mid]) / 2.0


def _roster_entry(worker: str, pod: str) -> dict:
    """Membership placeholder for a worker the informer has seen but
    that has not heartbeated yet (window -1 orders before any real
    heartbeat)."""
    return {
        "worker": worker,
        "hostname": "",
        "pod": pod,
        "window": -1,
        "step": 0,
        "steps": 0,
        "step_wall_p50_ms": 0.0,
        "step_wall_max_ms": 0.0,
        "wait_share": 0.0,
    }


class _JobMatrix:
    """One job's join state: latest heartbeat per worker, open windows
    awaiting the full gang, closed-window ring, detector counters."""

    __slots__ = (
        "workers", "open_windows", "closed", "consecutive", "straggling",
        "skew_wait_s", "last_closed_window",
    )

    def __init__(self, history: int):
        self.workers: dict[str, dict] = {}
        self.open_windows: dict[int, dict[str, dict]] = {}
        self.closed: deque = deque(maxlen=history)
        self.consecutive: dict[str, int] = {}
        self.straggling: set[str] = set()
        self.skew_wait_s = 0.0
        self.last_closed_window = -1


class StepMatrix:
    """Joins per-worker step heartbeats into per-job skew, straggler
    verdicts, and skew-wait seconds.

    ``observe_pod`` is the single write path (wired as a pod informer
    handler); everything else reads.  All numbers derive from heartbeat
    content, never wall clocks, so a simulated-clock bench replays
    bit-identically.
    """

    def __init__(
        self,
        flight_recorder: flightrecorder.FlightRecorder,
        registry: Optional[metrics.Registry] = None,
        clock: Callable[[], float] = time.time,
        *,
        skew_threshold: float = DEFAULT_SKEW_THRESHOLD,
        consecutive_windows: int = DEFAULT_CONSECUTIVE_WINDOWS,
        window_history: int = DEFAULT_WINDOW_HISTORY,
    ):
        if skew_threshold <= 1.0:
            raise ValueError(
                f"skew_threshold must be > 1, got {skew_threshold!r}"
            )
        if consecutive_windows < 1:
            raise ValueError(
                f"consecutive_windows must be >= 1, got {consecutive_windows!r}"
            )
        self._recorder = flight_recorder
        self._clock = clock
        self.skew_threshold = skew_threshold
        self.consecutive_windows = consecutive_windows
        self._history = max(window_history, 1)
        self._lock = locktrace.lock("stepstats")
        self._jobs: dict[tuple[str, str], _JobMatrix] = {}

        self.step_skew = None
        if registry is not None:
            # Unitless max/median ratio — the one deliberate exception to
            # the histograms-are-seconds convention (rule TPU103): skew IS
            # the quantity, and scaling it into seconds would tie the
            # series to the workload's step time.
            self.step_skew = metrics.new_histogram(  # noqa: TPU103
                "tpu_operator_job_step_skew",
                "Per-window fleet step skew (max/median worker step-wall "
                "p50) across joined heartbeat windows",
                (),
                registry,
                buckets=SKEW_BUCKETS,
            )
            self.stragglers = metrics.new_gauge(
                "tpu_operator_job_stragglers",
                "Workers currently flagged as stragglers per TPUJob "
                "(window p50 > k x gang median for M consecutive windows)",
                ("namespace", "tpujob"),
                registry,
            )
            registry.on_scrape(self.collect)

    # -- write path ------------------------------------------------------

    def observe_pod(self, pod: dict) -> None:
        """Fold one pod event into the owning job's matrix.

        Worker pods *without* a heartbeat annotation still register gang
        membership: the informer knows the gang's roster before the
        first heartbeat lands, so the first window only closes when the
        whole gang has reported it — not when the first arrival happens
        to be the only worker seen so far.  A terminal pod leaves the
        roster (a dead worker must not wedge window closure for the
        living).  Heartbeat folds are idempotent per (worker, window):
        informer resyncs and duplicate MODIFIED events never
        double-count."""
        import json

        from ..api.v2beta1 import constants

        meta = pod.get("metadata") or {}
        labels = meta.get("labels") or {}
        job_name = labels.get(constants.JOB_NAME_LABEL)
        if not job_name:
            return
        if labels.get(constants.JOB_ROLE_LABEL) != constants.ROLE_WORKER:
            return
        namespace = meta.get("namespace", "")
        # Replica index first: unlike TPU_WORKER_ID (which repeats per
        # slice in multislice jobs), it is unique across the whole gang.
        worker = labels.get(constants.REPLICA_INDEX_LABEL)
        if worker is None:
            worker = meta.get("name", "")
        worker = str(worker)
        phase = (pod.get("status") or {}).get("phase", "")

        raw = (meta.get("annotations") or {}).get(
            constants.STEP_HEARTBEAT_ANNOTATION
        )
        if not raw:
            with self._lock:
                job = self._jobs.get((namespace, job_name))
                if phase in ("Succeeded", "Failed"):
                    if job is not None and worker in job.workers:
                        del job.workers[worker]
                        self._close_ready_windows(job)
                    return
                if job is None:
                    job = self._jobs[(namespace, job_name)] = _JobMatrix(
                        self._history
                    )
                if (
                    worker not in job.workers
                    and len(job.workers) < MAX_WORKERS_PER_JOB
                ):
                    job.workers[worker] = _roster_entry(
                        worker, meta.get("name", "")
                    )
            return
        try:
            record = json.loads(raw)
        except ValueError:
            return
        if not isinstance(record, dict):
            return
        window = record.get("window")
        p50_ms = record.get("step_wall_p50_ms")
        if not isinstance(window, int) or not isinstance(
            p50_ms, (int, float)
        ):
            return

        hb = {
            "worker": worker,
            "hostname": str(record.get("hostname", "")),
            "pod": meta.get("name", ""),
            "window": window,
            "step": int(record.get("step", 0) or 0),
            "steps": int(record.get("steps", 0) or 0),
            "step_wall_p50_ms": float(p50_ms),
            "step_wall_max_ms": float(
                record.get("step_wall_max_ms", p50_ms) or p50_ms
            ),
            "wait_share": float(record.get("wait_share", 0.0) or 0.0),
        }
        with self._lock:
            job = self._jobs.get((namespace, job_name))
            if job is None:
                job = self._jobs[(namespace, job_name)] = _JobMatrix(
                    self._history
                )
            known = job.workers.get(worker)
            if known is not None and known["window"] >= window:
                return  # stale or duplicate delivery
            if (
                known is None
                and len(job.workers) >= MAX_WORKERS_PER_JOB
            ):
                return
            job.workers[worker] = hb
            if window > job.last_closed_window:
                job.open_windows.setdefault(window, {})[worker] = hb
            if phase in ("Succeeded", "Failed"):
                # The final flush of a finished worker: fold it, then
                # leave the roster so later windows can close without it.
                del job.workers[worker]
            self._close_ready_windows(job)

    def _close_ready_windows(self, job: _JobMatrix) -> None:
        """Close every open window the whole known gang has reported,
        plus any window lagging more than MAX_OPEN_WINDOW_LAG behind the
        newest (a dead worker must not wedge detection for the living).
        Caller holds the lock."""
        if not job.open_windows:
            return
        newest = max(job.open_windows)
        for window in sorted(job.open_windows):
            members = job.open_windows[window]
            full = len(members) >= len(job.workers)
            lagged = window <= newest - MAX_OPEN_WINDOW_LAG
            if not (full or lagged):
                # Windows close in order: an unready window blocks the
                # ones after it, keeping the detector's "consecutive"
                # counters aligned to a single window sequence.
                break
            del job.open_windows[window]
            if len(members) >= 2:
                self._close_window(job, window, members)
            job.last_closed_window = max(job.last_closed_window, window)

    def _close_window(
        self, job: _JobMatrix, window: int, members: dict[str, dict]
    ) -> None:
        """One joined window: skew ratio, slowest-host attribution,
        skew-wait accrual, detector update.  Caller holds the lock."""
        p50s = {w: hb["step_wall_p50_ms"] for w, hb in members.items()}
        med = _median(list(p50s.values()))
        slowest = max(sorted(p50s), key=lambda w: p50s[w])
        ratio = p50s[slowest] / med if med > 0 else 1.0
        steps = max(hb["steps"] for hb in members.values())
        # Price only above-threshold skew: ordinary step-time jitter
        # (input stalls, GC) stays inside productive — otherwise every
        # healthy gang would bleed skew_wait from the noise floor, and
        # the "skew_wait > 0 iff straggling" invariant the bench gates
        # on would be meaningless.
        wait_s = 0.0
        if ratio > self.skew_threshold:
            wait_s = max(0.0, (p50s[slowest] - med) / 1000.0) * steps
        job.skew_wait_s += wait_s
        job.closed.append({
            "window": window,
            "workers": len(members),
            "skew_ratio": round(ratio, 6),
            "slowest_worker": slowest,
            "median_p50_ms": round(med, 3),
            "max_p50_ms": round(p50s[slowest], 3),
            "skew_wait_s": round(wait_s, 6),
        })
        if self.step_skew is not None:
            self.step_skew.observe(ratio)
        for worker, p50 in p50s.items():
            if p50 > self.skew_threshold * med:
                job.consecutive[worker] = job.consecutive.get(worker, 0) + 1
                if job.consecutive[worker] >= self.consecutive_windows:
                    job.straggling.add(worker)
            else:
                job.consecutive[worker] = 0
                job.straggling.discard(worker)

    # -- read paths ------------------------------------------------------

    def straggler_verdict(self, namespace: str, name: str) -> Optional[dict]:
        """The controller's per-sync question: None when the matrix has
        no joined windows for the job yet (insufficient data — say
        nothing); else whether the gang currently has stragglers, who,
        and at what skew."""
        with self._lock:
            job = self._jobs.get((namespace, name))
            if job is None or not job.closed:
                return None
            latest = job.closed[-1]
            return {
                "straggling": bool(job.straggling),
                "workers": sorted(job.straggling),
                "skew_ratio": latest["skew_ratio"],
                "slowest_worker": latest["slowest_worker"],
                "window": latest["window"],
            }

    def skew_wait_seconds(self, namespace: str, name: str) -> float:
        """Cumulative gang wall-clock seconds lost to step skew — the
        goodput ledger's ``skew_wait`` carve (utils/goodput.py)."""
        with self._lock:
            job = self._jobs.get((namespace, name))
            return job.skew_wait_s if job is not None else 0.0

    def job_snapshot(self, namespace: str, name: str) -> Optional[dict]:
        """The ``/debug/jobs/<ns>/<name>/steps`` payload, or None when
        the job has never produced a heartbeat (the endpoint's 404)."""
        with self._lock:
            job = self._jobs.get((namespace, name))
            if job is None:
                return None
            latest = job.closed[-1] if job.closed else None
            return {
                "namespace": namespace,
                "name": name,
                "straggling": bool(job.straggling),
                "stragglers": sorted(job.straggling),
                "skew_ratio": latest["skew_ratio"] if latest else 0.0,
                "slowest_worker": (
                    latest["slowest_worker"] if latest else None
                ),
                "skew_wait_seconds": round(job.skew_wait_s, 6),
                "skew_threshold": self.skew_threshold,
                "consecutive_windows": self.consecutive_windows,
                "workers": {
                    worker: {
                        **hb,
                        "consecutive_slow_windows": job.consecutive.get(
                            worker, 0
                        ),
                        "straggling": worker in job.straggling,
                    }
                    for worker, hb in sorted(job.workers.items())
                },
                "windows": list(job.closed),
            }

    def jobs(self) -> list:
        with self._lock:
            return list(self._jobs.keys())

    def __len__(self) -> int:
        with self._lock:
            return len(self._jobs)

    # -- scrape hook -----------------------------------------------------

    def collect(self) -> None:
        """Scrape-time recompute + pruning (the goodput-ledger contract):
        the straggler gauge is re-derived from live state with stale
        series dropped, and any job the flight recorder has LRU-evicted
        loses its matrix too — the recorder's ``max_jobs`` bounds this
        table transitively."""
        known = set(self._recorder.jobs())
        with self._lock:
            for key in [k for k in self._jobs if k not in known]:
                del self._jobs[key]
            counts = {
                key: len(job.straggling) for key, job in self._jobs.items()
            }
        if self.step_skew is None:
            return
        self.stragglers.remove_matching()
        for (namespace, name), count in counts.items():
            self.stragglers.set(float(count), namespace, name)
