"""Job-lifecycle goodput ledger: per-phase wall-clock attribution.

``utils/telemetry.py`` knows what fraction of a *process's* wall time was
productive step time; nothing accounts for the hours a TPUJob loses
*outside* the training loop — queue wait, scheduling, pod startup,
rendezvous, restart downtime — exactly the accounting the MLPerf TPU-pod
papers (arxiv 1909.09756, 2011.03641) show dominates time-to-train at
scale.  The flight recorder (utils/flightrecorder.py) already captures
every raw event needed: condition transitions (controller + queue
manager), scheduling decisions (scheduler core), and pod phase flips
(pod runner).  The ``GoodputLedger`` joins them into the missing
job-level layer.

Each job's wall clock decomposes into a **closed phase vocabulary**
(PhaseProfiler-style exclusive accounting — phases tile the wall time):

- ``queue_wait``        suspended/unadmitted (quota pending, evicted,
                        queue missing, user-suspended);
- ``scheduling``        admitted, gang not yet placed;
- ``pod_pending``       gang bound / pods created, none running yet;
- ``bootstrap``         first pod running → whole gang running
                        (rendezvous, image pull, device init);
- ``productive``        gang running (minus checkpoint time reported by
                        training telemetry);
- ``checkpoint``        durable-save time carved out of productive,
                        joined from train_telemetry records;
- ``restart_downtime``  a worker died / the gang was preempted →
                        back to whole-gang running;
- ``unattributed``      residue (clock skew, rounding) — kept explicit
                        so the sum is exactly the wall time.

The ledger is scrape-driven like utils/statemetrics.py: per-job goodput
gauges and fleet aggregates are recomputed on ``Registry.on_scrape``;
terminal jobs additionally land in per-phase histograms exactly once.
The monitoring server serves ``/debug/jobs/<ns>/<name>/goodput`` and the
fleet ``/debug/goodput`` rollup from the same snapshots, and
``bench_goodput.py`` drives the whole stack under seeded chaos to emit
the goodput-vs-kill-rate curve.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from . import flightrecorder, metrics

# -- phase vocabulary (closed; schema consumers key on it) ---------------

PHASE_QUEUE_WAIT = "queue_wait"
PHASE_SCHEDULING = "scheduling"
PHASE_POD_PENDING = "pod_pending"
PHASE_BOOTSTRAP = "bootstrap"
PHASE_PRODUCTIVE = "productive"
PHASE_CHECKPOINT = "checkpoint"
# Gang wall time lost waiting on the slowest worker each step — carved
# out of productive by the step-skew join (utils/stepstats.py), the same
# way checkpoint seconds are carved by the telemetry join.
PHASE_SKEW_WAIT = "skew_wait"
PHASE_RESTART_DOWNTIME = "restart_downtime"
UNATTRIBUTED = "unattributed"

GOODPUT_PHASES = (
    PHASE_QUEUE_WAIT,
    PHASE_SCHEDULING,
    PHASE_POD_PENDING,
    PHASE_BOOTSTRAP,
    PHASE_PRODUCTIVE,
    PHASE_CHECKPOINT,
    PHASE_SKEW_WAIT,
    PHASE_RESTART_DOWNTIME,
    UNATTRIBUTED,
)

# Terminal pseudo-state: no phase accrues past the terminal condition.
_ENDED = "_ended"

# Job phases run from seconds (tests) to days (real pods): much wider
# buckets than server-latency defaults.
PHASE_BUCKETS = (
    0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0, 600.0, 1800.0,
    3600.0, 14400.0, 86400.0,
)

# The live states a timeline walks through (everything but checkpoint,
# which is carved out of productive by the telemetry join, and
# unattributed, which is the residue).
_LIVE_STATES = (
    PHASE_QUEUE_WAIT,
    PHASE_SCHEDULING,
    PHASE_POD_PENDING,
    PHASE_BOOTSTRAP,
    PHASE_PRODUCTIVE,
    PHASE_RESTART_DOWNTIME,
)


def _next_state(state: str, entry: dict) -> str:
    """Transition function over flight-recorder entries.  Guards keep
    re-recorded or out-of-order entries from bouncing the state machine:
    e.g. a pod flip during restart downtime stays downtime until the
    controller re-asserts the whole-gang Running condition."""
    if state == _ENDED:
        # Terminal is absorbing: post-mortem entries (late pod flips,
        # condition rewrites) must never resurrect a finished job — the
        # phases-sum-to-wall invariant depends on charging stopping for
        # good at the terminal timestamp.
        return _ENDED
    kind = entry.get("kind")
    reason = entry.get("reason", "")
    if kind == flightrecorder.CONDITION:
        type_ = entry.get("type", "")
        is_true = entry.get("status", "True") == "True"
        if type_ in ("Succeeded", "Failed") and is_true:
            return _ENDED
        if type_ == "Running" and is_true:
            return PHASE_PRODUCTIVE
        if type_ == "Restarting" and is_true:
            return PHASE_RESTART_DOWNTIME
        if type_ == "Suspended":
            if is_true:
                return PHASE_QUEUE_WAIT
            return PHASE_SCHEDULING if state == PHASE_QUEUE_WAIT else state
        if type_ == "QuotaReserved":
            if not is_true:  # Pending / Evicted
                return PHASE_QUEUE_WAIT
            # Admitted: only forward motion — a re-assert while the gang
            # is already placed or running must not rewind the state.
            if state in (PHASE_QUEUE_WAIT, PHASE_SCHEDULING):
                return PHASE_SCHEDULING
            return state
        if type_ == "QueueNotFound" and is_true:
            return PHASE_QUEUE_WAIT
        if type_ == "Scheduled":
            if is_true:
                if state in (PHASE_QUEUE_WAIT, PHASE_SCHEDULING):
                    return PHASE_POD_PENDING
                return state
            # Unschedulable: back to the scheduling queue.
            if state == PHASE_POD_PENDING:
                return PHASE_SCHEDULING
            return state
        return state
    if kind == flightrecorder.SCHEDULING:
        if reason == "Scheduled" and state in (
            PHASE_QUEUE_WAIT, PHASE_SCHEDULING
        ):
            return PHASE_POD_PENDING
        if reason == "Preempted" and state in (
            PHASE_POD_PENDING, PHASE_BOOTSTRAP, PHASE_PRODUCTIVE
        ):
            return PHASE_RESTART_DOWNTIME
        return state  # FailedScheduling et al.: still scheduling
    if kind == flightrecorder.POD:
        phase = entry.get("phase", "")
        if phase == "Pending" and state == PHASE_SCHEDULING:
            return PHASE_POD_PENDING
        if phase == "Running" and state in (
            PHASE_SCHEDULING, PHASE_POD_PENDING
        ):
            return PHASE_BOOTSTRAP
        if phase == "Failed" and state in (
            PHASE_POD_PENDING, PHASE_BOOTSTRAP, PHASE_PRODUCTIVE
        ):
            return PHASE_RESTART_DOWNTIME
        return state
    # EVENT entries duplicate condition/scheduling information; the state
    # machine keys off the authoritative sources only.
    return state


def attribute_timeline(entries: list, now: Optional[float] = None) -> dict:
    """Decompose one flight-recorder timeline into per-phase seconds.

    Exclusive accounting: the interval between consecutive entries is
    charged to the state the job was in *during* that interval, so the
    phases sum to the wall time by construction.  A terminal condition
    freezes the clock — post-mortem timeline entries (and ``now``) never
    extend a finished job's wall time.
    """
    phases = {p: 0.0 for p in GOODPUT_PHASES}
    entries = sorted(entries, key=lambda e: e.get("seq", 0))
    if not entries:
        return {
            "phases": phases, "wall_seconds": 0.0, "terminal": False,
            "restarts": 0, "start_ts": None, "end_ts": None,
        }
    t0 = float(entries[0].get("ts", 0.0))
    state = PHASE_SCHEDULING
    prev_ts = t0
    restarts = 0
    terminal_ts: Optional[float] = None
    for entry in entries:
        # Monotonic guard: seq order is authoritative; a timestamp that
        # runs backwards (clock skew) charges zero, never negative.
        ts = max(float(entry.get("ts", prev_ts)), prev_ts)
        if state != _ENDED:
            phases[state] += ts - prev_ts
        prev_ts = ts
        new = _next_state(state, entry)
        if new == _ENDED and terminal_ts is None:
            terminal_ts = ts
        if new == PHASE_RESTART_DOWNTIME and state != PHASE_RESTART_DOWNTIME:
            restarts += 1
        state = new
    if state == _ENDED and terminal_ts is not None:
        wall = terminal_ts - t0
        end_ts = terminal_ts
    else:
        end_ts = prev_ts if now is None else max(float(now), prev_ts)
        phases[state] += end_ts - prev_ts
        wall = end_ts - t0
    return {
        "phases": phases,
        "wall_seconds": wall,
        "terminal": state == _ENDED,
        "restarts": restarts,
        "start_ts": t0,
        "end_ts": end_ts,
    }


class GoodputLedger:
    """Joins flight-recorder timelines and training telemetry into
    per-job and fleet goodput, exposed three ways: scrape-time metrics,
    the ``/debug`` endpoints, and the bench artifact."""

    def __init__(
        self,
        flight_recorder: flightrecorder.FlightRecorder,
        registry: Optional[metrics.Registry] = None,
        clock: Callable[[], float] = time.time,
        skew_provider: Optional[Callable[[str, str], float]] = None,
    ):
        self._recorder = flight_recorder
        self._clock = clock
        # (namespace, name) -> cumulative skew-wait seconds; the operator
        # wires StepMatrix.skew_wait_seconds here so gang stall time is
        # carved out of productive (zero-arg default: no observatory).
        self._skew_provider = skew_provider
        self._lock = threading.Lock()
        # Latest train_telemetry record per job (checkpoint_s join).
        self._telemetry: dict[tuple[str, str], dict] = {}
        # Terminal jobs already observed into the phase histograms.
        self._finalized: set[tuple[str, str]] = set()

        self.goodput_ratio = None
        if registry is not None:
            self.goodput_ratio = metrics.new_gauge(
                "tpu_operator_job_goodput_ratio",
                "Productive wall-time fraction per TPUJob (flight-recorder "
                "phase attribution)",
                ("namespace", "tpujob"),
                registry,
            )
            self.phase_seconds = metrics.new_histogram(
                "tpu_operator_job_phase_seconds",
                "Per-phase wall seconds of terminal TPUJobs (observed once "
                "per job at completion)",
                ("phase",),
                registry,
                buckets=PHASE_BUCKETS,
            )
            self.fleet_goodput = metrics.new_gauge(
                "tpu_operator_job_goodput_fleet_ratio",
                "Fleet goodput: sum of productive seconds over sum of wall "
                "seconds across tracked TPUJobs",
                (),
                registry,
            )
            self.fleet_phase_seconds = metrics.new_gauge(
                "tpu_operator_job_phase_fleet_seconds",
                "Fleet-aggregate wall seconds by lifecycle phase",
                ("phase",),
                registry,
            )
            registry.on_scrape(self.collect)

    # -- telemetry join --------------------------------------------------

    def observe_telemetry(self, namespace: str, name: str, record: dict) -> None:
        """Feed one ``train_telemetry`` record (utils/telemetry.py
        snapshot shape).  ``checkpoint_s`` is carved out of the job's
        productive time; later records replace earlier ones (the fields
        are cumulative)."""
        with self._lock:
            self._telemetry[(namespace, name)] = dict(record)

    # -- snapshots -------------------------------------------------------

    def job_snapshot(
        self, namespace: str, name: str, now: Optional[float] = None
    ) -> Optional[dict]:
        """Per-job decomposition, or None when the flight recorder has
        never seen the job (the endpoint's 404 signal)."""
        entries = self._recorder.timeline(namespace, name)
        if entries is None:
            return None
        if now is None:
            now = self._clock()
        att = attribute_timeline(entries, now=now)
        phases = att["phases"]
        with self._lock:
            tel = self._telemetry.get((namespace, name))
        checkpoint_s = float((tel or {}).get("checkpoint_s", 0.0) or 0.0)
        carve = min(checkpoint_s, phases[PHASE_PRODUCTIVE])
        phases[PHASE_CHECKPOINT] += carve
        phases[PHASE_PRODUCTIVE] -= carve
        # Skew-wait carve mirrors the checkpoint one: both are wall time
        # the job spent nominally "training" but not making progress, and
        # both are clamped so the tiling invariant (phases sum to wall)
        # survives a noisy estimate.
        skew_s = (
            float(self._skew_provider(namespace, name))
            if self._skew_provider is not None
            else 0.0
        )
        skew_carve = min(max(skew_s, 0.0), phases[PHASE_PRODUCTIVE])
        phases[PHASE_SKEW_WAIT] += skew_carve
        phases[PHASE_PRODUCTIVE] -= skew_carve
        wall = att["wall_seconds"]
        attributed = sum(phases[p] for p in GOODPUT_PHASES if p != UNATTRIBUTED)
        phases[UNATTRIBUTED] += max(0.0, wall - attributed)
        goodput = phases[PHASE_PRODUCTIVE] / wall if wall > 0 else 0.0
        return {
            "namespace": namespace,
            "name": name,
            "wall_seconds": round(wall, 6),
            "goodput_ratio": round(goodput, 6),
            "terminal": att["terminal"],
            "restarts": att["restarts"],
            "phases": {p: round(phases[p], 6) for p in GOODPUT_PHASES},
            "phase_shares": {
                p: round(phases[p] / wall, 6) if wall > 0 else 0.0
                for p in GOODPUT_PHASES
            },
        }

    def fleet_snapshot(self, now: Optional[float] = None) -> dict:
        """Fleet rollup across every job the recorder tracks: aggregate
        goodput (Σ productive / Σ wall), per-phase totals and shares,
        plus a compact per-job table for the ``/debug/goodput`` page."""
        if now is None:
            now = self._clock()
        snaps = []
        for namespace, name in self._recorder.jobs():
            snap = self.job_snapshot(namespace, name, now=now)
            if snap is not None:
                snaps.append(snap)
        total_wall = sum(s["wall_seconds"] for s in snaps)
        phase_seconds = {
            p: round(sum(s["phases"][p] for s in snaps), 6)
            for p in GOODPUT_PHASES
        }
        productive = phase_seconds[PHASE_PRODUCTIVE]
        return {
            "job_count": len(snaps),
            "terminal_jobs": sum(1 for s in snaps if s["terminal"]),
            "restarts": sum(s["restarts"] for s in snaps),
            "wall_seconds": round(total_wall, 6),
            "goodput_ratio": round(
                productive / total_wall if total_wall > 0 else 0.0, 6
            ),
            "phase_seconds": phase_seconds,
            "phase_shares": {
                p: round(v / total_wall, 6) if total_wall > 0 else 0.0
                for p, v in phase_seconds.items()
            },
            "jobs": [
                {
                    "namespace": s["namespace"],
                    "name": s["name"],
                    "goodput_ratio": s["goodput_ratio"],
                    "wall_seconds": s["wall_seconds"],
                    "terminal": s["terminal"],
                    "restarts": s["restarts"],
                }
                for s in snaps
            ],
        }

    # -- scrape hook -----------------------------------------------------

    def collect(self) -> None:
        """statemetrics-style full recompute per scrape: drop every
        per-job goodput series and re-derive from the recorder, so
        evicted jobs never leave stale series behind.  Terminal jobs
        land in the per-phase histograms exactly once."""
        if self.goodput_ratio is None:
            return
        now = self._clock()
        known: set[tuple[str, str]] = set()
        snaps = []
        for namespace, name in self._recorder.jobs():
            snap = self.job_snapshot(namespace, name, now=now)
            if snap is not None:
                known.add((namespace, name))
                snaps.append(snap)

        self.goodput_ratio.remove_matching()
        total_wall = 0.0
        phase_totals = {p: 0.0 for p in GOODPUT_PHASES}
        for snap in snaps:
            key = (snap["namespace"], snap["name"])
            self.goodput_ratio.set(
                snap["goodput_ratio"], snap["namespace"], snap["name"]
            )
            total_wall += snap["wall_seconds"]
            for p in GOODPUT_PHASES:
                phase_totals[p] += snap["phases"][p]
            if snap["terminal"]:
                with self._lock:
                    fresh = key not in self._finalized
                    if fresh:
                        self._finalized.add(key)
                if fresh:
                    for p in GOODPUT_PHASES:
                        self.phase_seconds.observe(snap["phases"][p], p)
        self.fleet_goodput.set(
            round(phase_totals[PHASE_PRODUCTIVE] / total_wall, 6)
            if total_wall > 0 else 0.0
        )
        for p in GOODPUT_PHASES:
            self.fleet_phase_seconds.set(round(phase_totals[p], 6), p)
        with self._lock:
            # Evicted jobs can never be re-observed (timeline() is None),
            # so dropping their keys keeps both tables bounded by the
            # recorder's own max_jobs LRU.
            self._finalized &= known
            for key in [k for k in self._telemetry if k not in known]:
                del self._telemetry[key]
