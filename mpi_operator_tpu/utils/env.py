"""Environment plumbing for CPU-virtual-device subprocesses."""

from __future__ import annotations

import os

# Env vars that can hand a subprocess the real accelerator. The first
# is the image's sitecustomize trigger: if it survives into the child,
# the axon TPU platform registers at interpreter startup — BEFORE the
# child's own JAX_PLATFORMS takes effect — and a down tunnel then
# wedges backend init (or worse, a live one gets grabbed mid-bench).
TPU_ENV_VARS = (
    "PALLAS_AXON_POOL_IPS",
    "TPU_LIBRARY_PATH",
    "PJRT_DEVICE",
    "TPU_NAME",
)


def cpu_subprocess_env(n_devices: int) -> dict:
    """A copy of ``os.environ`` pinned to ``n_devices`` virtual CPU
    devices, with every way of grabbing a real TPU scrubbed.

    The single source of truth for chipless subprocess harnesses
    (``__graft_entry__.dryrun_multichip``, ``hack/wedge_repro.py``):
    the scrub list must stay in lockstep across them, or a stage grabs
    the real chip and can wedge the tunnel for hours."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = [
        f for f in env.get("XLA_FLAGS", "").split()
        if not f.startswith("--xla_force_host_platform_device_count")
    ]
    flags.append(f"--xla_force_host_platform_device_count={n_devices}")
    env["XLA_FLAGS"] = " ".join(flags)
    for var in TPU_ENV_VARS:
        env.pop(var, None)
    return env
