"""Small networking helpers shared by tests, benchmarks, and tools."""

from __future__ import annotations

import socket


def free_port_pair() -> int:
    """A free port p whose p+1 is also free.

    The gang barrier binds coordinatorPort+1 next to jax.distributed's
    coordinatorPort, so anything allocating a rendezvous port must probe
    both — a half-free pair hangs worker 0 at bind time.
    """
    for _ in range(64):
        with socket.socket() as a:
            a.bind(("127.0.0.1", 0))
            p = a.getsockname()[1]
        if p + 1 >= 65536:
            continue
        try:
            with socket.socket() as b:
                b.bind(("127.0.0.1", p + 1))
            return p
        except OSError:
            continue
    raise RuntimeError("no adjacent free port pair found")
