"""Lightweight span tracer: context-manager spans into a JSONL ring buffer.

The operator's answer to "where did that reconcile spend its time" without
an OpenTelemetry dependency: every instrumented section opens a span
(``with trace.span("reconcile", key=key):``), child spans started on the
same thread inherit the parent/trace ids, and completed spans land in a
bounded ring buffer that the monitoring server serves verbatim at
``/debug/trace`` (one JSON object per line, newest last).

Design points:

- **Thread-local span stack** — parentage needs no plumbing through call
  signatures, so builders/bootstrap/barrier code just opens spans.
- **Ring buffer** — ``maxlen`` bounds memory; a hot controller keeps the
  most recent few thousand spans, which is exactly the window a human
  debugging a live incident wants.
- **Spans record on exit** — an abandoned span (crashed thread) never
  corrupts the buffer; errors are captured on the span before re-raise.
"""

from __future__ import annotations

import itertools
import json
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Iterator, Optional

DEFAULT_CAPACITY = 2048


class TraceContext:
    """Serializable (trace id, parent span id) pair — the W3C traceparent
    analog that crosses process boundaries in ``TPU_TRACE_CONTEXT``.

    The controller encodes the context of its open builder span into pod
    env; launcher/train parse it back and :func:`adopt_context` it, after
    which every *root* span the process opens inherits the trace id and
    parents under the stamping span.  Span ids stay process-local (they
    are per-tracer counters); the trace id is the cross-process join key.
    """

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: str):
        self.trace_id = trace_id
        self.span_id = span_id

    def encode(self) -> str:
        return f"{self.trace_id}-{self.span_id}"

    def __repr__(self) -> str:
        return f"TraceContext({self.encode()!r})"

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, TraceContext)
            and other.trace_id == self.trace_id
            and other.span_id == self.span_id
        )

    @classmethod
    def parse(cls, value: Optional[str]) -> Optional["TraceContext"]:
        """Decode ``"<trace_id>-<span_id>"``; None on anything malformed
        (propagation is best-effort — a garbled env var must never break
        worker startup)."""
        if not value or not isinstance(value, str):
            return None
        parts = value.strip().split("-")
        if len(parts) != 2 or not parts[0] or not parts[1]:
            return None
        return cls(parts[0], parts[1])

    @classmethod
    def from_environ(cls, environ=None) -> Optional["TraceContext"]:
        """Read the propagation env var (``constants.ENV_TRACE_CONTEXT``)."""
        import os

        from ..api.v2beta1 import constants

        env = os.environ if environ is None else environ
        return cls.parse(env.get(constants.ENV_TRACE_CONTEXT))


# Process-level inherited context (set once on startup from the pod env).
# Root spans opened while this is set parent under the stamping process's
# span instead of starting a fresh trace.
_propagated: Optional[TraceContext] = None


def adopt_context(ctx: Optional[TraceContext]) -> Optional[TraceContext]:
    """Install ``ctx`` as the process-level inherited trace context and
    return the previous one (so tests can restore; pass None to clear)."""
    global _propagated
    prev = _propagated
    _propagated = ctx
    return prev


def adopt_from_environ(environ=None) -> Optional[TraceContext]:
    """Adopt the trace context from the environment if one is present —
    the launcher/train startup hook.  Returns the adopted context."""
    ctx = TraceContext.from_environ(environ)
    if ctx is not None:
        adopt_context(ctx)
    return ctx


def propagated_context() -> Optional[TraceContext]:
    return _propagated


class Span:
    """One timed section. Mutable while open: ``span.annotate(k=v)`` adds
    attributes mid-flight (e.g. how many workers a reconcile created)."""

    __slots__ = (
        "name", "span_id", "parent_id", "trace_id", "start", "end",
        "attrs", "error",
    )

    def __init__(
        self,
        name: str,
        span_id: str,
        parent_id: Optional[str],
        trace_id: str,
        start: float,
        attrs: dict,
    ):
        self.name = name
        self.span_id = span_id
        self.parent_id = parent_id
        self.trace_id = trace_id
        self.start = start
        self.end: Optional[float] = None
        self.attrs = attrs
        self.error: Optional[str] = None

    def annotate(self, **attrs) -> None:
        self.attrs.update(attrs)

    @property
    def duration_ms(self) -> Optional[float]:
        if self.end is None:
            return None
        return (self.end - self.start) * 1000.0

    def to_dict(self) -> dict:
        out = {
            "name": self.name,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "trace_id": self.trace_id,
            "start": round(self.start, 6),
            "duration_ms": (
                round(self.duration_ms, 3) if self.end is not None else None
            ),
        }
        if self.attrs:
            # Attributes stay JSON-safe: repr anything exotic.
            out["attrs"] = {
                k: v if isinstance(v, (str, int, float, bool, type(None)))
                else repr(v)
                for k, v in self.attrs.items()
            }
        if self.error is not None:
            out["error"] = self.error
        return out


class Tracer:
    def __init__(
        self,
        capacity: int = DEFAULT_CAPACITY,
        clock=time.time,
    ):
        self._buf: deque[dict] = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._local = threading.local()
        self._ids = itertools.count(1)
        self._clock = clock

    def _next_id(self) -> str:
        return f"{next(self._ids):08x}"

    def _stack(self) -> list[Span]:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def current(self) -> Optional[Span]:
        stack = self._stack()
        return stack[-1] if stack else None

    @contextmanager
    def span(self, name: str, **attrs) -> Iterator[Span]:
        stack = self._stack()
        parent = stack[-1] if stack else None
        sid = self._next_id()
        if parent is not None:
            parent_id, trace_id = parent.span_id, parent.trace_id
        elif _propagated is not None:
            # Root span in a process that adopted a cross-process context:
            # continue the inherited trace instead of starting a new one.
            parent_id, trace_id = _propagated.span_id, _propagated.trace_id
        else:
            parent_id, trace_id = None, sid
        sp = Span(name, sid, parent_id, trace_id, self._clock(), attrs)
        stack.append(sp)
        # While this span is open, module-level trace.span() calls on this
        # thread record into THIS tracer — library code (builders,
        # launcher) nests under whichever tracer its caller opened,
        # without threading a tracer through every signature.
        prev_active = getattr(_active, "tracer", None)
        _active.tracer = self
        try:
            yield sp
        except BaseException as e:
            sp.error = f"{type(e).__name__}: {e}"
            raise
        finally:
            _active.tracer = prev_active
            sp.end = self._clock()
            # Pop by identity: a mismatched pop (exotic generator abuse)
            # must not unwind someone else's span.
            if stack and stack[-1] is sp:
                stack.pop()
            elif sp in stack:
                stack.remove(sp)
            with self._lock:
                self._buf.append(sp.to_dict())

    def spans(self) -> list[dict]:
        """Completed spans, oldest first."""
        with self._lock:
            return list(self._buf)

    def to_jsonl(self) -> str:
        return "\n".join(json.dumps(s, sort_keys=True) for s in self.spans())

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._buf)


DEFAULT_TRACER = Tracer()

# The innermost tracer with an open span on this thread (see Tracer.span).
_active = threading.local()


def current_tracer() -> Tracer:
    """The tracer library code should record into: the one whose span is
    open on this thread, else the process default."""
    # Explicit None check: Tracer defines __len__, so an empty tracer is
    # falsy and ``tracer or DEFAULT_TRACER`` would wrongly discard it.
    tracer = getattr(_active, "tracer", None)
    return DEFAULT_TRACER if tracer is None else tracer


def span(name: str, **attrs):
    """Open a span on the active tracer (nests under the caller's open
    span when there is one; the process-default tracer otherwise)."""
    return current_tracer().span(name, **attrs)


def current_context() -> Optional[TraceContext]:
    """The context to propagate (or log) right now: the innermost open
    span on this thread, else the process-level adopted context, else
    None.  Builders call this to stamp pod env; the structured logger
    calls it to attach ``trace_id`` to every record."""
    sp = current_tracer().current()
    if sp is not None:
        return TraceContext(sp.trace_id, sp.span_id)
    return _propagated
