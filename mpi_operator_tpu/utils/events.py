"""Kubernetes Event recording.

Reference analog: the record.EventRecorder created in
/root/reference/v2/pkg/controller/mpi_job_controller.go:262-267 and used as
the user-facing audit trail at every anomaly (:489, :497, :575, :608...),
with message truncation to 1024 chars (:1565-1571).
"""

from __future__ import annotations

import itertools
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable

EVENT_TYPE_NORMAL = "Normal"
EVENT_TYPE_WARNING = "Warning"

# Buffer/aggregation bounds (client-go event correlator analogs): the
# in-process buffer is a ring so a long-running operator cannot grow
# memory without limit, and identical events inside the similarity
# window collapse into one Event with an incremented ``count`` (kube's
# EventSeries/aggregation behavior; its aggregator also uses a
# 10-minute window).
DEFAULT_EVENT_BUFFER = 1000
DEFAULT_AGGREGATION_WINDOW = 600.0

# Scheduler event reasons (kube-scheduler vocabulary).
SCHEDULED_REASON = "Scheduled"
FAILED_SCHEDULING_REASON = "FailedScheduling"
PREEMPTED_REASON = "Preempted"

# eventMessageLimit, mpi_job_controller.go:116 analog.
MESSAGE_LIMIT = 1024


def format_failed_scheduling(total_nodes: int, reasons) -> str:
    """Render kube-scheduler's FailedScheduling message shape:
    ``0/4 nodes are available: 3 Insufficient google.com/tpu, 1 node(s)
    had mismatched TPU generation.`` — ``reasons`` is a mapping of
    reason string -> node count."""
    if not reasons:
        detail = "no nodes registered" if total_nodes == 0 else "no reason recorded"
        return f"0/{total_nodes} nodes are available: {detail}."
    parts = ", ".join(
        f"{count} {reason}" for reason, count in sorted(reasons.items())
    )
    return f"0/{total_nodes} nodes are available: {parts}."


def truncate_message(message: str) -> str:
    """Truncate to the apiserver-friendly limit (:1565-1571 analog)."""
    if len(message) <= MESSAGE_LIMIT:
        return message
    suffix = "..."
    return message[: MESSAGE_LIMIT - len(suffix)] + suffix


@dataclass
class Event:
    type: str
    reason: str
    message: str
    involved_kind: str
    involved_name: str
    involved_namespace: str
    timestamp: float
    source: str
    # Event-series fields: ``timestamp`` stays the first occurrence;
    # aggregated repeats bump ``count`` and ``last_timestamp``.
    count: int = 1
    last_timestamp: float = 0.0

    def to_object(self, name: str) -> dict:
        return {
            "apiVersion": "v1",
            "kind": "Event",
            "metadata": {"name": name, "namespace": self.involved_namespace},
            "type": self.type,
            "reason": self.reason,
            "message": self.message,
            "involvedObject": {
                "kind": self.involved_kind,
                "name": self.involved_name,
                "namespace": self.involved_namespace,
            },
            "source": {"component": self.source},
            "eventTime": self.timestamp,
            "count": self.count,
            "lastTimestamp": self.last_timestamp or self.timestamp,
        }


class EventRecorder:
    """Records Events against an API server and keeps them inspectable.

    ``api`` may be None, in which case events are only buffered in-process
    (fixture mode, like the fake record.FakeRecorder).
    """

    def __init__(
        self,
        api=None,
        source: str = "tpu-job-controller",
        clock=time.time,
        capacity: int = DEFAULT_EVENT_BUFFER,
        aggregation_window: float = DEFAULT_AGGREGATION_WINDOW,
    ):
        self._api = api
        self.source = source
        self._clock = clock
        self._seq = itertools.count(1)
        self.events: deque[Event] = deque(maxlen=capacity)
        self._window = aggregation_window
        # (involvedObject, type, reason, message) -> (Event, apiserver
        # object name) for the live aggregation window.
        self._recent: dict[tuple, tuple[Event, str]] = {}
        self._subscribers: list[Callable[[Event], None]] = []

    def subscribe(self, fn: Callable[[Event], None]) -> None:
        """Register an observer called once per recorded occurrence (new
        Events AND aggregated repeats, with the up-to-date Event)."""
        self._subscribers.append(fn)

    def _notify(self, ev: Event) -> None:
        for fn in self._subscribers:
            try:
                fn(ev)
            except Exception:  # observers must never break reconciliation
                pass

    def event(self, obj: Any, type_: str, reason: str, message: str) -> None:
        meta = obj.metadata if hasattr(obj, "metadata") else None
        if meta is not None:
            kind = getattr(obj, "kind", "")
            name, namespace = meta.name, meta.namespace
        else:  # plain dict object
            kind = obj.get("kind", "")
            m = obj.get("metadata") or {}
            name, namespace = m.get("name", ""), m.get("namespace", "")
        message = truncate_message(message)
        now = self._clock()
        key = (kind, namespace, name, type_, reason, message)

        # Lazy window prune: keys whose last occurrence aged out.
        for k in [
            k for k, (e, _) in self._recent.items()
            if now - (e.last_timestamp or e.timestamp) > self._window
        ]:
            del self._recent[k]

        aggregated = self._recent.get(key)
        if aggregated is not None:
            ev, event_name = aggregated
            ev.count += 1
            ev.last_timestamp = now
            if self._api is not None:
                try:
                    stored = self._api.get("events", namespace, event_name)
                    stored["count"] = ev.count
                    stored["lastTimestamp"] = now
                    self._api.update("events", stored)
                except Exception:  # events must never break reconciliation
                    pass
            self._notify(ev)
            return

        ev = Event(
            type=type_,
            reason=reason,
            message=message,
            involved_kind=kind,
            involved_name=name,
            involved_namespace=namespace,
            timestamp=now,
            source=self.source,
            last_timestamp=now,
        )
        event_name = f"{name}.{next(self._seq):08x}"
        self.events.append(ev)
        self._recent[key] = (ev, event_name)
        if self._api is not None:
            try:
                self._api.create("events", ev.to_object(event_name))
            except Exception:  # events must never break reconciliation
                pass
        self._notify(ev)

    def eventf(self, obj: Any, type_: str, reason: str, fmt: str, *args: Any) -> None:
        self.event(obj, type_, reason, fmt % args if args else fmt)
