"""kube-state-metrics-style gauges computed at scrape time.

Push-model metrics (the controller's counters) say what the operator
*did*; state metrics say what the world currently *looks like*.  In the
kube-state-metrics idiom every series is derived from watched object
state — ``_info`` gauges carry identity as labels with a constant value
of 1, ``by_phase`` gauges count objects per lifecycle phase — and here
they are recomputed on every scrape from the informer caches via
``Registry.on_scrape``: no bookkeeping on the reconcile path, no stale
series after deletes, never ahead of (or behind) what the informers have
actually observed.
"""

from __future__ import annotations

from ..api.v2beta1 import constants
from . import metrics

# TPUJob lifecycle phases, derived from status conditions with terminal
# states taking precedence (kube-state-metrics derives Job/Pod phase the
# same way: latest decisive condition wins).
JOB_PHASES = (
    "Pending",
    "Created",
    "Running",
    "Restarting",
    "Suspended",
    "Succeeded",
    "Failed",
)

POD_PHASES = ("Pending", "Running", "Succeeded", "Failed", "Unknown")

# Condition precedence for phase derivation, most decisive first.
_PHASE_PRECEDENCE = (
    ("Succeeded", "Succeeded"),
    ("Failed", "Failed"),
    ("Suspended", "Suspended"),
    ("Restarting", "Restarting"),
    ("Running", "Running"),
    ("Created", "Created"),
)


def job_phase(job: dict) -> str:
    """One phase per job: the most decisive condition with status True.
    A job with no conditions yet (created but not reconciled) is
    Pending."""
    held = {
        c.get("type"): c.get("status")
        for c in ((job.get("status") or {}).get("conditions") or [])
    }
    for cond_type, phase in _PHASE_PRECEDENCE:
        if held.get(cond_type) == "True":
            return phase
    return "Pending"


class StateMetrics:
    """Registers the state-metric family and recomputes it per scrape.

    ``job_lister``/``pod_lister`` are informer listers (deep-copied cache
    reads), so a scrape observes exactly the informer's view — the same
    view the reconciler acts on.
    """

    def __init__(self, registry, job_lister, pod_lister):
        self._job_lister = job_lister
        self._pod_lister = pod_lister
        self.job_info = metrics.new_gauge(
            "tpu_operator_job_info",
            "Identity of each TPUJob known to the informer cache (value 1)",
            ("namespace", "tpujob", "launcher", "accelerator_type",
             "num_slices", "queue"),
            registry,
        )
        self.jobs_by_phase = metrics.new_gauge(
            "tpu_operator_jobs_by_phase",
            "TPUJobs in the informer cache by derived lifecycle phase",
            ("phase",),
            registry,
        )
        self.pods_by_phase = metrics.new_gauge(
            "tpu_operator_pods_by_phase",
            "Pods in the informer cache by status phase",
            ("phase",),
            registry,
        )
        self.job_condition = metrics.new_gauge(
            "tpu_operator_job_condition",
            "TPUJob status conditions (1 = True, 0 = False/Unknown)",
            ("namespace", "tpujob", "type"),
            registry,
        )
        registry.on_scrape(self.collect)

    def collect(self) -> None:
        """Full recompute: drop every series, then re-derive from the
        caches.  remove_matching() with an empty prefix clears all label
        sets, so deleted objects can never leave stale series behind."""
        jobs = self._job_lister.list()

        self.job_info.remove_matching()
        self.job_condition.remove_matching()
        job_counts = {phase: 0 for phase in JOB_PHASES}
        for job in jobs:
            meta = job.get("metadata") or {}
            ns = meta.get("namespace", "")
            name = meta.get("name", "")
            spec = job.get("spec") or {}
            tpu = spec.get("tpu") or {}
            has_launcher = "Launcher" in (spec.get("tpuReplicaSpecs") or {})
            scheduling = (
                (spec.get("runPolicy") or {}).get("schedulingPolicy") or {}
            )
            self.job_info.set(
                1.0,
                ns,
                name,
                (name + constants.LAUNCHER_SUFFIX) if has_launcher else "",
                tpu.get("acceleratorType", ""),
                str(tpu.get("numSlices", 1)),
                scheduling.get("queue", ""),
            )
            phase = job_phase(job)
            job_counts[phase] = job_counts.get(phase, 0) + 1
            for cond in (job.get("status") or {}).get("conditions") or []:
                self.job_condition.set(
                    1.0 if cond.get("status") == "True" else 0.0,
                    ns,
                    name,
                    cond.get("type", ""),
                )
        for phase in JOB_PHASES:
            self.jobs_by_phase.set(float(job_counts.get(phase, 0)), phase)

        pod_counts = {phase: 0 for phase in POD_PHASES}
        for phase, count in self._pod_phase_counts().items():
            if phase not in pod_counts:
                phase = "Unknown"
            pod_counts[phase] += count
        for phase in POD_PHASES:
            self.pods_by_phase.set(float(pod_counts.get(phase, 0)), phase)

    def _pod_phase_counts(self) -> dict[str, int]:
        """Phase counts via the informer's phase index when the lister
        has one (O(phases), no copies); full-scan fallback keeps plain
        list-backed listers (kube backend REST lister) working."""
        if hasattr(self._pod_lister, "index_counts"):
            return self._pod_lister.index_counts("phase")
        counts: dict[str, int] = {}
        for pod in self._pod_lister.list():
            phase = (pod.get("status") or {}).get("phase") or "Pending"
            counts[phase] = counts.get(phase, 0) + 1
        return counts
