"""Cross-cutting utilities: event recording, metrics, logging."""
