"""GangScheduler: the in-process kube-scheduler analog.

One scheduling pass (``schedule_once``) runs the classic pipeline per
*gang*, not per pod — a TPU slice is indivisible, so admission is
all-or-nothing:

1. snapshot  — refresh the node cache from the API server and reconcile
   the chip ledger against live pods (leak-proof even across missed
   watch events);
2. group     — pending pods form gangs by their PodGroup annotation;
   a gang is admissible only once ``minMember`` pods exist;
3. filter/score — every member is placed through the plugin pipeline
   against reserved-aware capacity; any infeasible member rolls the
   whole gang's reservations back;
4. preempt   — if placement failed, whole lower-priority gangs are
   tentatively evicted (never individual workers) until the gang fits
   or candidates run out;
5. bind      — reservations commit one pod at a time through the
   ``Binder``; a bind failure releases every uncommitted reservation.

Incomplete gangs wait on a waitlist holding best-effort reservations;
a gang that stays incomplete past ``gang_wait_timeout`` releases its
hold (and re-queues when the missing members appear).
"""

from __future__ import annotations

import threading
import time
from collections import Counter as TallyCounter
from typing import Optional

from ..api.v2beta1 import constants
from ..utils import events as ev
from ..utils import flightrecorder, metrics, profiling
from ..utils.logging import get_logger
from ..runtime import locktrace
from .binder import Binder, BindError
from .cache import NodeInfo, PodKey, SchedulerCache, is_standby_pod, pod_chips
from .plugins import (
    DEFAULT_PLUGINS,
    Plugin,
    SchedulingContext,
    run_filters,
    run_scores,
)

DEFAULT_SCHEDULER_NAME = "tpu-gang-scheduler"
GROUP_ANNOTATION = "scheduling.k8s.io/group-name"

# Priority classes the scheduler understands out of the box; jobs map a
# class onto a gang via their PodGroup's ``priorityClassName``.  Unknown
# classes score 0 (between the built-in low and high bands).
DEFAULT_PRIORITIES: dict[str, int] = {
    "system-critical": 2000,
    "high-priority": 1000,
    "low-priority": -100,
}

def pod_key(pod: dict) -> PodKey:
    meta = pod.get("metadata") or {}
    return (meta.get("namespace", ""), meta.get("name", ""))


def gang_of(pod: dict) -> tuple[str, str]:
    """Gang identity: the PodGroup annotation, else a singleton per pod
    (an unannotated pod is its own gang of one — kube's default-scheduler
    behaviour falls out of the gang machinery for free)."""
    meta = pod.get("metadata") or {}
    group = (meta.get("annotations") or {}).get(GROUP_ANNOTATION, "")
    if group:
        return (meta.get("namespace", ""), group)
    return (meta.get("namespace", ""), f"pod/{meta.get('name', '')}")


class GangScheduler:
    def __init__(
        self,
        api,
        binder=None,
        recorder: Optional[ev.EventRecorder] = None,
        plugins: tuple[Plugin, ...] = DEFAULT_PLUGINS,
        scheduler_name: str = DEFAULT_SCHEDULER_NAME,
        gang_wait_timeout: float = 30.0,
        priorities: Optional[dict[str, int]] = None,
        clock=time.time,
        interval: float = 0.2,
        registry: Optional[metrics.Registry] = None,
        flight_recorder: Optional[flightrecorder.FlightRecorder] = None,
    ):
        self.api = api
        self.log = get_logger("scheduler")
        # Shared with the controller when the operator wires one through:
        # scheduling decisions land on the owning job's timeline.
        self.flight_recorder = flight_recorder
        registry = registry or metrics.Registry()
        self.registry = registry
        self.scheduling_duration = metrics.new_histogram(
            "tpu_operator_scheduler_scheduling_duration_seconds",
            "Time from first sighting of a gang to its last member binding.",
            ("result",),
            registry,
        )
        self.pending_gangs = metrics.new_gauge(
            "tpu_operator_scheduler_pending_gangs",
            "Gangs with pending pods that are not fully bound.",
            (),
            registry,
        )
        self.binds_total = metrics.new_counter(
            "tpu_operator_scheduler_binds_total",
            "Pods bound to nodes by the gang scheduler.",
            (),
            registry,
        )
        self.preemptions_total = metrics.new_counter(
            "tpu_operator_scheduler_preemptions_total",
            "Whole-gang evictions performed to admit a higher-priority gang.",
            (),
            registry,
        )
        self.chips = metrics.new_gauge(
            "tpu_operator_scheduler_chips",
            "TPU chips in the scheduler cache by accounting state "
            "(capacity, allocated, reserved, free, standby; standby is a "
            "subset of allocated held by parked hot-spare pods).",
            ("state",),
            registry,
        )
        # Shared with whatever else feeds this registry (the operator
        # wires one registry through controller/manager/scheduler).
        self.profiler = profiling.profiler_for(registry)
        self.binder = (
            binder
            if binder is not None
            else Binder(api, clock=clock, profiler=self.profiler)
        )
        self.recorder = recorder or ev.EventRecorder(
            api, source=scheduler_name, clock=clock
        )
        self.plugins = plugins
        self.scheduler_name = scheduler_name
        self.gang_wait_timeout = gang_wait_timeout
        self.priorities = dict(DEFAULT_PRIORITIES if priorities is None else priorities)
        self.cache = SchedulerCache()
        self._clock = clock
        self._interval = interval
        self._lock = locktrace.rlock("scheduler.core")
        self._first_seen: dict[tuple[str, str], float] = {}
        self._wait_expired: set[tuple[str, str]] = set()
        self._last_failure_msg: dict[tuple[str, str], str] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # The cache is only safe under the scheduling lock; scrapes happen
        # on the metrics server's thread, so the pull-model hook takes the
        # lock for one consistent cut of the chip ledger.
        registry.on_scrape(self._update_chip_gauges)

    def _update_chip_gauges(self) -> None:
        with self._lock:
            totals = {
                "capacity": self.cache.total_capacity(),
                "allocated": self.cache.total_allocated(),
                "reserved": self.cache.total_reserved(),
                "free": self.cache.total_free(),
                "standby": self.cache.total_standby(),
            }
        for state, value in totals.items():
            self.chips.set(value, state)

    # -- lifecycle --------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="gang-scheduler", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.schedule_once()
            except Exception as exc:  # the loop must survive transient API races
                self.log.warning(
                    "scheduling pass failed: %s", exc, error=type(exc).__name__
                )
            self._stop.wait(self._interval)

    # -- one pass ---------------------------------------------------------

    def schedule_once(self) -> dict:
        """Run one full scheduling pass; returns a summary for tests."""
        with self._lock:
            return self._schedule_once_locked()

    def _schedule_once_locked(self) -> dict:
        now = self._clock()
        with self.profiler.phase(profiling.PHASE_SCHED_SNAPSHOT):
            self._refresh_nodes()
            all_pods = self.api.list("pods", None)
            self.cache.reconcile(all_pods)
        # Every pass walks the full pod store (the cost the sharded-pass
        # ROADMAP item will attack); make it visible.
        self.profiler.record_scan("scheduler_pods", len(all_pods))

        gangs = self._pending_gangs(all_pods)
        members = self._gang_sizes(all_pods)
        bound_pods = 0
        still_pending = 0
        # Standby (hot-spare) gangs sort behind every live gang of the same
        # priority: spares warm capacity, they must never delay real work.
        order = sorted(
            gangs,
            key=lambda g: (
                -self._gang_priority(g),
                1 if self._is_standby_gang(gangs[g]) else 0,
                self._first_seen.get(g, now),
                g,
            ),
        )
        for gang_key in order:
            pods = gangs[gang_key]
            self._first_seen.setdefault(gang_key, now)
            min_member = self._min_member(gang_key, pods)
            # Completeness counts every live member, bound ones included —
            # a gang mid-recovery from a partial bind is still complete.
            if members.get(gang_key, len(pods)) < min_member:
                self._handle_incomplete(gang_key, pods, min_member, now)
                still_pending += 1
                continue

            assignments, reasons = self._assign(pods)
            if assignments is None:
                assignments = self._preempt(gang_key, pods, all_pods)
            if assignments is None:
                self._mark_unschedulable(gang_key, pods, reasons)
                still_pending += 1
                continue

            if self._bind_gang(gang_key, pods, assignments, now):
                bound_pods += len(assignments)
            else:
                still_pending += 1

        self.pending_gangs.set(still_pending)
        return {"bound": bound_pods, "pending_gangs": still_pending}

    # -- snapshot ---------------------------------------------------------

    def _refresh_nodes(self) -> None:
        live = {
            (n.get("metadata") or {}).get("name", ""): n
            for n in self.api.list("nodes", None)
        }
        self.profiler.record_scan("scheduler_nodes", len(live))
        for name in [n for n in self.cache.nodes if n not in live]:
            self.cache.remove_node(name)
        for name, node in live.items():
            self.cache.add_node(NodeInfo.from_node_object(node))

    def _wants(self, pod: dict) -> bool:
        spec = pod.get("spec") or {}
        if spec.get("nodeName"):
            return False
        if (pod.get("status") or {}).get("phase") in ("Succeeded", "Failed"):
            return False
        if (pod.get("metadata") or {}).get("deletionTimestamp"):
            return False
        return spec.get("schedulerName", "") in ("", self.scheduler_name)

    @staticmethod
    def _is_standby_gang(pods: list[dict]) -> bool:
        """A gang made entirely of parked hot-spare pods (the controller
        puts spares in their own PodGroup, so mixed gangs don't occur)."""
        return bool(pods) and all(is_standby_pod(p) for p in pods)

    def _gang_sizes(self, all_pods: list[dict]) -> dict[tuple[str, str], int]:
        """Live member count per gang, bound members included."""
        sizes: dict[tuple[str, str], int] = {}
        for pod in all_pods:
            spec = pod.get("spec") or {}
            if (pod.get("status") or {}).get("phase") in ("Succeeded", "Failed"):
                continue
            if (pod.get("metadata") or {}).get("deletionTimestamp"):
                continue
            if spec.get("schedulerName", "") not in ("", self.scheduler_name):
                continue
            key = gang_of(pod)
            sizes[key] = sizes.get(key, 0) + 1
        return sizes

    def _pending_gangs(self, all_pods: list[dict]) -> dict[tuple[str, str], list[dict]]:
        gangs: dict[tuple[str, str], list[dict]] = {}
        for pod in all_pods:
            if self._wants(pod):
                gangs.setdefault(gang_of(pod), []).append(pod)
        for pods in gangs.values():
            pods.sort(key=lambda p: (p.get("metadata") or {}).get("name", ""))
        # Drop bookkeeping for gangs that vanished or fully bound.
        for table in (self._first_seen, self._last_failure_msg):
            for key in [k for k in table if k not in gangs]:
                del table[key]
        self._wait_expired &= set(gangs)
        return gangs

    def _podgroup(self, gang_key: tuple[str, str]) -> Optional[dict]:
        from ..runtime.apiserver import NotFoundError

        namespace, name = gang_key
        if name.startswith("pod/"):
            return None
        try:
            return self.api.get("podgroups", namespace, name)
        except NotFoundError:
            return None

    def _min_member(self, gang_key: tuple[str, str], pods: list[dict]) -> int:
        group = self._podgroup(gang_key)
        if group is None:
            return len(pods)
        try:
            return int((group.get("spec") or {}).get("minMember", len(pods)))
        except (TypeError, ValueError):
            return len(pods)

    def _gang_priority(self, gang_key: tuple[str, str]) -> int:
        group = self._podgroup(gang_key)
        if group is None:
            return 0
        cls = (group.get("spec") or {}).get("priorityClassName", "")
        return self.priorities.get(cls, 0)

    def _record_scheduling(
        self, pods: list[dict], reason: str, message: str = "", **attrs
    ) -> None:
        """Flight-recorder hook: one SCHEDULING entry per owning TPUJob
        (gang members all carry the same job-name label)."""
        if self.flight_recorder is None:
            return
        seen: set[tuple[str, str]] = set()
        for pod in pods:
            meta = pod.get("metadata") or {}
            job = (meta.get("labels") or {}).get(constants.JOB_NAME_LABEL)
            if not job:
                continue
            key = (meta.get("namespace", ""), job)
            if key in seen:
                continue
            seen.add(key)
            self.flight_recorder.record(
                key[0],
                key[1],
                flightrecorder.SCHEDULING,
                reason=reason,
                message=message,
                **attrs,
            )

    # -- placement --------------------------------------------------------

    def _assign(
        self, pods: list[dict]
    ) -> tuple[Optional[dict[PodKey, str]], TallyCounter]:
        """Reserve a node for every member, or roll back and report why
        the first unplaceable member failed on each node."""
        with self.profiler.phase(profiling.PHASE_SCHED_RESERVE):
            return self._assign_locked(pods)

    def _assign_locked(
        self, pods: list[dict]
    ) -> tuple[Optional[dict[PodKey, str]], TallyCounter]:
        gang_key = gang_of(pods[0])
        ctx = SchedulingContext(
            gang_name=gang_key[1],
            remaining_chips=sum(pod_chips(p) for p in pods),
        )
        slice_names = {n.slice_name for n in self.cache.nodes.values() if n.slice_name}
        ctx.slice_free = {s: self.cache.slice_free(s) for s in slice_names}

        assignments: dict[PodKey, str] = {}
        for pod in pods:
            reasons: TallyCounter = TallyCounter()
            feasible: list[NodeInfo] = []
            for node in sorted(self.cache.nodes.values(), key=lambda n: n.name):
                reason = run_filters(self.plugins, ctx, pod, node)
                if reason is None:
                    feasible.append(node)
                else:
                    reasons[reason] += 1
            if not feasible:
                for key in assignments:
                    self.cache.release(key)
                return None, reasons
            # max() keeps the first maximum, so the name sort above makes
            # ties deterministic.
            best = max(feasible, key=lambda n: run_scores(self.plugins, ctx, pod, n))
            key = pod_key(pod)
            chips = pod_chips(pod)
            self.cache.reserve(key, best.name, chips)
            assignments[key] = best.name
            ctx.remaining_chips -= chips
            if best.slice_name:
                ctx.slice_free[best.slice_name] -= chips
                if not ctx.chosen_slice:
                    ctx.chosen_slice = best.slice_name
        return assignments, TallyCounter()

    # -- preemption -------------------------------------------------------

    def _preempt(
        self,
        gang_key: tuple[str, str],
        pods: list[dict],
        all_pods: list[dict],
    ) -> Optional[dict[PodKey, str]]:
        """Evict whole lower-priority gangs (cheapest first) until this
        gang fits; never evicts individual workers — a decapitated TPU
        gang is pure waste."""
        my_priority = self._gang_priority(gang_key)
        victims: dict[tuple[str, str], list[dict]] = {}
        for pod in all_pods:
            if not (pod.get("spec") or {}).get("nodeName"):
                continue
            if (pod.get("status") or {}).get("phase") in ("Succeeded", "Failed"):
                continue
            vkey = gang_of(pod)
            if vkey != gang_key:
                victims.setdefault(vkey, []).append(pod)
        # Victim order: cheapest priority first, and within a priority band
        # standby gangs go before live gangs — evicting parked spares costs
        # zero training progress.
        candidates = sorted(
            (
                (self._gang_priority(vk), vk, vpods)
                for vk, vpods in victims.items()
                if self._gang_priority(vk) < my_priority
            ),
            key=lambda t: (t[0], 0 if self._is_standby_gang(t[2]) else 1, t[1]),
        )
        if not candidates:
            return None

        released: list[tuple[PodKey, tuple[str, int]]] = []
        evicting: list[tuple[tuple[str, str], list[dict]]] = []
        assignments: Optional[dict[PodKey, str]] = None
        for _, vkey, vpods in candidates:
            for vpod in vpods:
                token = self.cache.release_bound(pod_key(vpod))
                if token is not None:
                    released.append((pod_key(vpod), token))
            evicting.append((vkey, vpods))
            assignments, _ = self._assign(pods)
            if assignments is not None:
                break
        if assignments is None:
            for key, (node_name, chips) in released:
                self.cache.charge_bound(key, node_name, chips)
            return None

        from ..runtime.apiserver import NotFoundError

        for vkey, vpods in evicting:
            for vpod in vpods:
                ns, name = pod_key(vpod)
                self.recorder.eventf(
                    vpod,
                    ev.EVENT_TYPE_WARNING,
                    ev.PREEMPTED_REASON,
                    "Preempted by %s/%s (gang priority %d)",
                    gang_key[0],
                    gang_key[1],
                    my_priority,
                )
                try:
                    self.api.delete("pods", ns, name)
                except NotFoundError:
                    pass
            self.preemptions_total.inc()
            self.log.warning(
                "preempted gang %s/%s for %s/%s", vkey[0], vkey[1],
                gang_key[0], gang_key[1],
            )
            self._record_scheduling(
                vpods,
                ev.PREEMPTED_REASON,
                f"preempted by {gang_key[0]}/{gang_key[1]}",
                by=f"{gang_key[0]}/{gang_key[1]}",
            )
        return assignments

    # -- outcomes ---------------------------------------------------------

    def _bind_gang(
        self,
        gang_key: tuple[str, str],
        pods: list[dict],
        assignments: dict[PodKey, str],
        now: float,
    ) -> bool:
        committed: set[PodKey] = set()
        for key, node_name in assignments.items():
            namespace, name = key
            try:
                bound = self.binder.bind(namespace, name, node_name)
            except BindError as exc:
                # All-or-nothing rollback: every uncommitted reservation is
                # released immediately.  Members already bound stay bound
                # (they hold real API state); the next pass re-admits the
                # gang and binds only the remainder.
                for other in assignments:
                    if other not in committed:
                        self.cache.release(other)
                self.recorder.eventf(
                    {"kind": "Pod", "metadata": {"name": name, "namespace": namespace}},
                    ev.EVENT_TYPE_WARNING,
                    ev.FAILED_SCHEDULING_REASON,
                    "binding rejected: %s",
                    exc,
                )
                return False
            self.cache.commit(key)
            committed.add(key)
            self.binds_total.inc()
            self.recorder.eventf(
                bound,
                ev.EVENT_TYPE_NORMAL,
                ev.SCHEDULED_REASON,
                "Successfully assigned %s/%s to %s",
                namespace,
                name,
                node_name,
            )
        first_seen = self._first_seen.pop(gang_key, now)
        self._wait_expired.discard(gang_key)
        self._last_failure_msg.pop(gang_key, None)
        self.scheduling_duration.observe(max(0.0, now - first_seen), "scheduled")
        nodes = sorted(set(assignments.values()))
        self.log.info(
            "bound gang %s/%s (%d pods)", gang_key[0], gang_key[1],
            len(assignments), nodes=",".join(nodes),
        )
        self._record_scheduling(
            pods,
            ev.SCHEDULED_REASON,
            f"gang {gang_key[1]} bound to {', '.join(nodes)}",
            pod_count=len(assignments),
            wait_seconds=round(max(0.0, now - first_seen), 6),
        )
        return True

    def _handle_incomplete(
        self,
        gang_key: tuple[str, str],
        pods: list[dict],
        min_member: int,
        now: float,
    ) -> None:
        """Waitlist: hold best-effort reservations for the members that
        exist, release them when the wait times out."""
        deadline = self._first_seen[gang_key] + self.gang_wait_timeout
        if now >= deadline:
            if gang_key not in self._wait_expired:
                self._wait_expired.add(gang_key)
                for pod in pods:
                    self.cache.release(pod_key(pod))
                    self.recorder.eventf(
                        pod,
                        ev.EVENT_TYPE_WARNING,
                        ev.FAILED_SCHEDULING_REASON,
                        "gang %s waited %.0fs with %d/%d members; releasing "
                        "reserved capacity",
                        gang_key[1],
                        self.gang_wait_timeout,
                        len(pods),
                        min_member,
                    )
            return
        # Best-effort hold (reservations survive passes via reconcile).
        self._assign(pods)

    def _mark_unschedulable(
        self,
        gang_key: tuple[str, str],
        pods: list[dict],
        reasons: TallyCounter,
    ) -> None:
        message = ev.format_failed_scheduling(len(self.cache.nodes), reasons)
        first_report = self._last_failure_msg.get(gang_key) != message
        self._last_failure_msg[gang_key] = message
        for pod in pods:
            namespace, name = pod_key(pod)
            self.binder.mark_unschedulable(namespace, name, message)
            if first_report:
                self.recorder.event(
                    pod, ev.EVENT_TYPE_WARNING, ev.FAILED_SCHEDULING_REASON, message
                )
        if first_report:
            self.log.warning(
                "gang %s/%s unschedulable: %s", gang_key[0], gang_key[1], message
            )
            self._record_scheduling(pods, ev.FAILED_SCHEDULING_REASON, message)
