"""In-process gang scheduler for TPU slices.

An in-memory kube-scheduler analog with a filter -> score -> reserve ->
bind pipeline, specialised for the one thing TPU training jobs need
that the default scheduler lacks: *all-or-nothing* placement of a whole
slice's worth of workers, with whole-gang preemption and
topology-aware packing.  See ``docs/scheduling.md``.
"""

from .binder import Binder, BindError, FlakyBinder
from .cache import NodeInfo, SchedulerCache, pod_chips
from .core import (
    DEFAULT_PRIORITIES,
    DEFAULT_SCHEDULER_NAME,
    GROUP_ANNOTATION,
    GangScheduler,
    gang_of,
)
from .inventory import (
    InventoryError,
    TPU_RESOURCE,
    build_nodes,
    parse_inventory,
    register_nodes,
)
from .plugins import (
    DEFAULT_PLUGINS,
    CoschedulingPlugin,
    Plugin,
    SchedulingContext,
    TPUCapacityPlugin,
    TopologyPackPlugin,
)

__all__ = [
    "Binder",
    "BindError",
    "FlakyBinder",
    "NodeInfo",
    "SchedulerCache",
    "pod_chips",
    "DEFAULT_PRIORITIES",
    "DEFAULT_SCHEDULER_NAME",
    "GROUP_ANNOTATION",
    "GangScheduler",
    "gang_of",
    "InventoryError",
    "TPU_RESOURCE",
    "build_nodes",
    "parse_inventory",
    "register_nodes",
    "DEFAULT_PLUGINS",
    "CoschedulingPlugin",
    "Plugin",
    "SchedulingContext",
    "TPUCapacityPlugin",
    "TopologyPackPlugin",
]
