"""Scheduling plugins: the filter -> score surface of the framework.

kube-scheduler analog: scheduler-plugins' framework interfaces, reduced
to the two extension points this operator needs.  Every plugin exposes

- ``name``          — stable identifier (profile config, logs, metrics)
- ``filter(ctx, pod, node)`` — None if the node is feasible, else a
  human-readable reason string (aggregated into the kube-style
  ``0/N nodes are available: ...`` event message)
- ``score(ctx, pod, node)``  — additive integer score; higher is better

``SchedulingContext`` carries gang-level state across a single pass so
plugins can coordinate (the topology packer remembers which slice the
gang's earlier members landed on).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..api import topology
from ..api.v2beta1 import constants
from . import inventory
from .cache import NodeInfo, pod_chips


def pod_accelerator_type(pod: dict) -> str:
    """Worker pods carry their slice identity in env (builders stamp
    ``TPU_ACCELERATOR_TYPE``); that is the scheduler's placement hint."""
    containers = (pod.get("spec") or {}).get("containers") or [{}]
    for entry in containers[0].get("env") or []:
        if entry.get("name") == constants.ENV_TPU_ACCELERATOR_TYPE:
            return entry.get("value", "")
    return ""


def pod_generation(pod: dict) -> str:
    accel = pod_accelerator_type(pod)
    if not accel:
        return ""
    try:
        generation, _ = topology.parse_accelerator_type(accel)
    except topology.TopologyError:
        return ""
    return generation


@dataclass
class SchedulingContext:
    """Per-pass state shared by the plugins while one gang is placed."""

    gang_name: str = ""
    # Total chips the gang still needs (decremented as members reserve).
    remaining_chips: int = 0
    # Slice the gang's already-reserved members landed on (packing target).
    chosen_slice: str = ""
    # slice name -> free chips at pass start (for tightest-fit scoring).
    slice_free: dict[str, int] = field(default_factory=dict)


class Plugin:
    """Base plugin: feasible everywhere, indifferent to placement."""

    name = "plugin"

    def filter(self, ctx: SchedulingContext, pod: dict, node: NodeInfo) -> Optional[str]:
        return None

    def score(self, ctx: SchedulingContext, pod: dict, node: NodeInfo) -> int:
        return 0


class TPUCapacityPlugin(Plugin):
    """NodeResourcesFit analog for the single resource that matters:
    ``google.com/tpu`` chips, plus TPU-generation compatibility (a v4
    worker binary cannot initialise v5e hosts)."""

    name = "TPUCapacity"

    def filter(self, ctx: SchedulingContext, pod: dict, node: NodeInfo) -> Optional[str]:
        generation = pod_generation(pod)
        if generation and node.generation and generation != node.generation:
            return "node(s) had mismatched TPU generation"
        if node.free < pod_chips(pod):
            return f"Insufficient {inventory.TPU_RESOURCE}"
        return None

    def score(self, ctx: SchedulingContext, pod: dict, node: NodeInfo) -> int:
        # Mild most-allocated bias: prefer reusing partially-filled hosts
        # over cracking open empty ones, so whole hosts stay free for
        # gangs that need them.
        return node.capacity - node.free


class CoschedulingPlugin(Plugin):
    """Gang gate (scheduler-plugins coscheduling analog).

    All-or-nothing admission itself lives in the core's gang loop — by
    the time a member pod reaches the plugins, the gang has already been
    admitted as a unit.  This plugin contributes the per-node demand
    check: once a gang is mid-placement, a node too small for even one
    member is infeasible regardless of aggregate capacity.
    """

    name = "Coscheduling"

    def filter(self, ctx: SchedulingContext, pod: dict, node: NodeInfo) -> Optional[str]:
        if ctx.gang_name and node.capacity < pod_chips(pod):
            return f"Insufficient {inventory.TPU_RESOURCE}"
        return None


class TopologyPackPlugin(Plugin):
    """Pack a gang onto one contiguous slice block before spilling.

    Scoring tiers (additive with the other plugins' scores):

    - +1000: node belongs to the slice this gang already started filling
      (never fragment a gang across slices if its first member fit);
    - +500:  node's slice can hold the gang's *entire remaining* demand
      (prefer slices the whole gang fits in, so small gangs don't
      poach hosts from the one slice a big gang needs);
    - minus the slice's free chips: tightest-fit, so the emptiest slice
      stays intact for the biggest future gang;
    - minus the host index: earlier hosts first — combined with
      ``topology.host_grid``'s row-major host ordering this yields
      physically contiguous blocks within the slice.
    """

    name = "TopologyPack"

    def score(self, ctx: SchedulingContext, pod: dict, node: NodeInfo) -> int:
        score = 0
        if not node.slice_name:
            return score
        if ctx.chosen_slice and node.slice_name == ctx.chosen_slice:
            score += 1000
        free_in_slice = ctx.slice_free.get(node.slice_name, 0)
        if free_in_slice >= ctx.remaining_chips > 0:
            score += 500
        score -= free_in_slice
        score -= node.host_index
        return score


DEFAULT_PLUGINS: tuple[Plugin, ...] = (
    CoschedulingPlugin(),
    TPUCapacityPlugin(),
    TopologyPackPlugin(),
)


def run_filters(
    plugins: tuple[Plugin, ...], ctx: SchedulingContext, pod: dict, node: NodeInfo
) -> Optional[str]:
    for plugin in plugins:
        reason = plugin.filter(ctx, pod, node)
        if reason is not None:
            return reason
    return None


def run_scores(
    plugins: tuple[Plugin, ...], ctx: SchedulingContext, pod: dict, node: NodeInfo
) -> int:
    return sum(plugin.score(ctx, pod, node) for plugin in plugins)
