"""Scheduler cache: per-node chip accounting with two-phase reservations.

kube-scheduler analog: the scheduler cache + "assume" protocol — a pod's
resources are charged optimistically at reserve time so concurrent gang
placement never double-books a host, then committed at bind or rolled
back if any member of the gang fails placement.  The invariant the
fault-injection tier checks: ``allocated + reserved + free == capacity``
on every node, at every step, including across bind conflicts, node
loss mid-reserve, and whole-gang preemption.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..api.v2beta1 import constants as api_constants
from . import inventory

PodKey = tuple[str, str]  # (namespace, name)


def is_standby_pod(pod: dict) -> bool:
    """Parked hot-spare pod (spec.tpu.hotSpares): holds chips that are
    charged as allocated but are reclaimable by promotion or preemption."""
    annotations = (pod.get("metadata") or {}).get("annotations") or {}
    return annotations.get(api_constants.STANDBY_ANNOTATION) == "true"


def pod_chips(pod: dict) -> int:
    """``google.com/tpu`` request of a pod's first container (builders
    inject it on every worker; launcher pods request none)."""
    containers = (pod.get("spec") or {}).get("containers") or [{}]
    resources = containers[0].get("resources") or {}
    for bound in ("requests", "limits"):
        value = (resources.get(bound) or {}).get(inventory.TPU_RESOURCE)
        if value is not None:
            try:
                return int(value)
            except (TypeError, ValueError):
                return 0
    return 0


@dataclass
class NodeInfo:
    """One TPU host's capacity as the scheduler sees it."""

    name: str
    capacity: int
    accelerator_type: str = ""
    generation: str = ""
    topology: str = ""
    slice_name: str = ""
    host_index: int = 0
    allocated: int = 0  # chips of bound, non-terminal pods
    reserved: int = 0  # chips of in-flight gang reservations
    standby: int = 0  # subset of allocated held by parked hot-spare pods
    labels: dict = field(default_factory=dict)

    @property
    def free(self) -> int:
        return self.capacity - self.allocated - self.reserved

    @classmethod
    def from_node_object(cls, node: dict) -> "NodeInfo":
        meta = node.get("metadata") or {}
        labels = dict(meta.get("labels") or {})
        capacity = (node.get("status") or {}).get("capacity") or {}
        try:
            chips = int(capacity.get(inventory.TPU_RESOURCE, 0))
        except (TypeError, ValueError):
            chips = 0
        try:
            host_index = int(labels.get(inventory.LABEL_HOST_INDEX, 0))
        except (TypeError, ValueError):
            host_index = 0
        return cls(
            name=meta.get("name", ""),
            capacity=chips,
            accelerator_type=labels.get(inventory.LABEL_ACCELERATOR, ""),
            generation=labels.get(inventory.LABEL_GENERATION, ""),
            topology=labels.get(inventory.LABEL_TOPOLOGY, ""),
            slice_name=labels.get(inventory.LABEL_SLICE, ""),
            host_index=host_index,
            labels=labels,
        )


class SchedulerCache:
    """Nodes + the pod->node ledger.  Not thread-safe on its own; the
    GangScheduler serialises access under its scheduling lock."""

    def __init__(self):
        self.nodes: dict[str, NodeInfo] = {}
        self._reserved: dict[PodKey, tuple[str, int]] = {}
        self._bound: dict[PodKey, tuple[str, int]] = {}

    # -- node set --------------------------------------------------------

    def add_node(self, node: NodeInfo) -> None:
        existing = self.nodes.get(node.name)
        if existing is not None:
            # Keep the ledger: only refresh the static identity fields.
            node.allocated = existing.allocated
            node.reserved = existing.reserved
            node.standby = existing.standby
        self.nodes[node.name] = node

    def remove_node(self, name: str) -> None:
        """Node loss: the node's chips vanish *with* every reservation and
        allocation charged to it (nothing to leak — there is no capacity
        left to leak from)."""
        self.nodes.pop(name, None)
        for ledger in (self._reserved, self._bound):
            for key in [k for k, (n, _) in ledger.items() if n == name]:
                del ledger[key]

    # -- reservations (two-phase) ----------------------------------------

    def reserve(self, key: PodKey, node_name: str, chips: int) -> None:
        self.release(key)  # re-reserve replaces, never stacks
        node = self.nodes[node_name]
        if node.free < chips:
            raise RuntimeError(
                f"reserve over capacity on {node_name}: want {chips}, free {node.free}"
            )
        node.reserved += chips
        self._reserved[key] = (node_name, chips)

    def commit(self, key: PodKey) -> None:
        """Reservation -> allocation (the pod is bound)."""
        node_name, chips = self._reserved.pop(key)
        node = self.nodes.get(node_name)
        if node is not None:
            node.reserved -= chips
            node.allocated += chips
        self._bound[key] = (node_name, chips)

    def release(self, key: PodKey) -> None:
        """Undo a reservation or an allocation (idempotent)."""
        for ledger, attr in ((self._reserved, "reserved"), (self._bound, "allocated")):
            entry = ledger.pop(key, None)
            if entry is not None:
                node = self.nodes.get(entry[0])
                if node is not None:
                    setattr(node, attr, getattr(node, attr) - entry[1])

    def assignment(self, key: PodKey) -> Optional[str]:
        for ledger in (self._reserved, self._bound):
            if key in ledger:
                return ledger[key][0]
        return None

    # -- preemption simulation -------------------------------------------

    def release_bound(self, key: PodKey) -> Optional[tuple[str, int]]:
        """Tentatively free a bound pod's chips; returns the undo token."""
        entry = self._bound.pop(key, None)
        if entry is not None:
            node = self.nodes.get(entry[0])
            if node is not None:
                node.allocated -= entry[1]
        return entry

    def charge_bound(self, key: PodKey, node_name: str, chips: int) -> None:
        node = self.nodes.get(node_name)
        if node is not None:
            node.allocated += chips
        self._bound[key] = (node_name, chips)

    # -- reconciliation ---------------------------------------------------

    def reconcile(self, pods: list[dict]) -> None:
        """Rebuild the allocation ledger from live pod state (bound +
        non-terminal = charged), and drop reservations whose pod is gone
        or has since bound.  Guarantees deletions/completions observed
        between scheduling passes re-account their chips — no leaks even
        without a watch stream."""
        for node in self.nodes.values():
            node.allocated = 0
            node.standby = 0
        self._bound.clear()
        present: set[PodKey] = set()
        for pod in pods:
            meta = pod.get("metadata") or {}
            key = (meta.get("namespace", ""), meta.get("name", ""))
            present.add(key)
            node_name = (pod.get("spec") or {}).get("nodeName")
            phase = (pod.get("status") or {}).get("phase", "")
            if not node_name or phase in ("Succeeded", "Failed"):
                continue
            chips = pod_chips(pod)
            node = self.nodes.get(node_name)
            if node is not None:
                node.allocated += chips
                if is_standby_pod(pod):
                    # Informational tally (rebuilt every pass): standby
                    # chips are inside `allocated`, never double-counted.
                    node.standby += chips
                self._bound[key] = (node_name, chips)
        for key in [k for k in self._reserved if k not in present or k in self._bound]:
            self.release(key)

    # -- aggregates (tests, gauges) ---------------------------------------

    def total_capacity(self) -> int:
        return sum(n.capacity for n in self.nodes.values())

    def total_allocated(self) -> int:
        return sum(n.allocated for n in self.nodes.values())

    def total_reserved(self) -> int:
        return sum(n.reserved for n in self.nodes.values())

    def total_standby(self) -> int:
        return sum(n.standby for n in self.nodes.values())

    def total_free(self) -> int:
        return sum(n.free for n in self.nodes.values())

    def slice_free(self, slice_name: str) -> int:
        return sum(n.free for n in self.nodes.values() if n.slice_name == slice_name)
