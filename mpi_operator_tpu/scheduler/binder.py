"""Pod binding: the scheduler's only write path to the API server.

kube-scheduler analog: the bind phase (``pods/binding`` subresource).
The in-memory backend has no binding subresource, so the binder writes
the assignment in two steps ordered for crash-safety against the pod
runner's watch:

1. status first — ``PodScheduled=True`` condition while the phase is
   still ``Pending`` (nobody acts on conditions alone);
2. then ``spec.nodeName`` — the MODIFIED event this write emits is what
   wakes the runner, which flips the phase to Running.  Because the
   condition landed first, the runner's status write can never race a
   half-bound pod.

``FlakyBinder`` wraps a real binder for the fault-injection tier: it
fails chosen bind calls (conflict) and can sabotage the cluster
mid-gang (node loss) via a callback, so tests can prove the gang
reserve rollback never leaks chips.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

from ..runtime import retry
from ..runtime.apiserver import ConflictError, NotFoundError
from ..utils import profiling
from ..utils.logging import get_logger


class BindError(RuntimeError):
    """A bind attempt failed; the caller must roll the gang back."""


def scheduled_condition(
    status: str, reason: str = "", message: str = "",
    now: Optional[float] = None,
) -> dict:
    cond = {"type": "PodScheduled", "status": status}
    if reason:
        cond["reason"] = reason
    if message:
        cond["message"] = message
    if now is not None:
        # Real pod conditions carry lastTransitionTime; downstream
        # consumers (goodput attribution, kubectl-style describes) read
        # scheduling latency straight off the condition.
        cond["lastTransitionTime"] = round(now, 6)
    return cond


def set_pod_condition(pod: dict, cond: dict) -> None:
    status = pod.setdefault("status", {})
    conds = [c for c in status.get("conditions") or [] if c.get("type") != cond["type"]]
    conds.append(cond)
    status["conditions"] = conds


class Binder:
    """Writes assignments to the API server under conflict-retry backoff
    (runtime/retry.retry_on_conflict)."""

    def __init__(self, api, clock=time.time, profiler=None):
        self._api = api
        self._clock = clock
        self._profiler = profiler
        self._log = get_logger("scheduler.binder")

    def bind(self, namespace: str, name: str, node_name: str) -> dict:
        if self._profiler is not None:
            with self._profiler.phase(profiling.PHASE_SCHED_BIND):
                return self._bind(namespace, name, node_name)
        return self._bind(namespace, name, node_name)

    def _bind(self, namespace: str, name: str, node_name: str) -> dict:
        def attempt() -> dict:
            # Each attempt re-reads the pod: a conflict means someone else
            # wrote it, so retrying the stale copy would conflict forever.
            try:
                pod = self._api.get("pods", namespace, name)
            except NotFoundError:
                raise BindError(f"pod {namespace}/{name} vanished before bind")
            if pod.get("spec", {}).get("nodeName"):
                if pod["spec"]["nodeName"] == node_name:
                    return pod  # already bound here (idempotent retry)
                raise BindError(
                    f"pod {namespace}/{name} already bound to "
                    f"{pod['spec']['nodeName']!r}"
                )
            set_pod_condition(
                pod, scheduled_condition("True", now=self._clock())
            )
            pod["status"].setdefault("phase", "Pending")
            pod = self._api.update_status("pods", pod)
            pod["spec"]["nodeName"] = node_name
            return self._api.update("pods", pod)

        try:
            bound = retry.retry_on_conflict(attempt, retry.DEFAULT_RETRY)
        except ConflictError:
            raise BindError(f"conflict binding {namespace}/{name}")
        self._log.debug("bound pod %s/%s to %s", namespace, name, node_name)
        return bound

    def mark_unschedulable(self, namespace: str, name: str, message: str) -> None:
        """Surface ``PodScheduled=False/Unschedulable`` on the pod, the
        condition the controller folds into the job's ``Scheduled``
        condition.  Best-effort: an unschedulable pod is untouched state,
        a write race just means another pass will repeat the verdict."""
        if self._profiler is not None:
            with self._profiler.phase(profiling.PHASE_SCHED_BIND):
                return self._mark_unschedulable(namespace, name, message)
        return self._mark_unschedulable(namespace, name, message)

    def _mark_unschedulable(self, namespace: str, name: str, message: str) -> None:
        try:
            pod = self._api.get("pods", namespace, name)
        except NotFoundError:
            return
        existing = {
            (c.get("type"), c.get("status"), c.get("message"))
            for c in (pod.get("status") or {}).get("conditions") or []
        }
        if ("PodScheduled", "False", message) in existing:
            return  # no-op write would still bump resourceVersion
        set_pod_condition(
            pod, scheduled_condition(
                "False", reason="Unschedulable", message=message,
                now=self._clock(),
            )
        )
        pod["status"].setdefault("phase", "Pending")
        try:
            self._api.update_status("pods", pod)
        except ConflictError:
            return
        self._log.debug("marked pod %s/%s unschedulable", namespace, name)


class FlakyBinder:
    """Fault-injection wrapper: fails selected bind calls, optionally
    running a sabotage callback first (e.g. delete the target node to
    model node loss mid-reserve)."""

    def __init__(
        self,
        inner: Binder,
        fail_calls: Optional[set[int]] = None,
        on_fail: Optional[Callable[[int, str, str, str], None]] = None,
    ):
        self._inner = inner
        self.fail_calls = fail_calls or set()
        self.on_fail = on_fail
        self.calls = 0

    def bind(self, namespace: str, name: str, node_name: str) -> dict:
        self.calls += 1
        if self.calls in self.fail_calls:
            if self.on_fail is not None:
                self.on_fail(self.calls, namespace, name, node_name)
            raise BindError(
                f"injected bind conflict for {namespace}/{name} (call #{self.calls})"
            )
        return self._inner.bind(namespace, name, node_name)

    def mark_unschedulable(self, namespace: str, name: str, message: str) -> None:
        self._inner.mark_unschedulable(namespace, name, message)
