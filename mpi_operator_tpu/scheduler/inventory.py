"""TPU node inventory: the scheduler's capacity model.

A real GKE TPU node pool exposes one Kubernetes Node per TPU host, with
``google.com/tpu`` in ``status.capacity`` and the slice identity in node
labels (``cloud.google.com/gke-tpu-accelerator``/``-topology`` plus the
JobSet/Pathways host-ordinal labels).  The memory backend has no cloud
to discover, so the operator materialises the same shape from a compact
``--node-inventory`` spec:

    v5e-16:2,v4-32          ->  2 slices of v5e-16 (4 hosts each)
                                + 1 slice of v4-32 (8 hosts)
    v5e-16/4x4:1            ->  explicit topology override

Each slice becomes ``num_hosts`` Node objects registered as the ``nodes``
resource on the API server; hosts carry their slice name, host index and
chip-grid coordinate (``api/topology.py`` host-block math) so the
scheduler can score contiguous placement.
"""

from __future__ import annotations

from ..api import topology

# Node label keys (GKE analogs, under one operator-owned prefix).
LABEL_ACCELERATOR = "tpu.operator.kubeflow.org/accelerator-type"
LABEL_GENERATION = "tpu.operator.kubeflow.org/generation"
LABEL_TOPOLOGY = "tpu.operator.kubeflow.org/topology"
LABEL_SLICE = "tpu.operator.kubeflow.org/slice"
LABEL_HOST_INDEX = "tpu.operator.kubeflow.org/host-index"
LABEL_HOST_COORD = "tpu.operator.kubeflow.org/host-coord"

TPU_RESOURCE = "google.com/tpu"


class InventoryError(ValueError):
    pass


def parse_inventory(spec: str) -> list[tuple[topology.SliceShape, int]]:
    """``"v5e-16:2,v4-32"`` -> [(SliceShape(v5e-16), 2), (SliceShape(v4-32), 1)].

    Entry grammar: ``accelType[/topology][:count]``.
    """
    out: list[tuple[topology.SliceShape, int]] = []
    for raw in spec.split(","):
        entry = raw.strip()
        if not entry:
            continue
        count = 1
        if ":" in entry:
            entry, _, count_str = entry.rpartition(":")
            try:
                count = int(count_str)
            except ValueError:
                raise InventoryError(
                    f"bad slice count {count_str!r} in inventory entry {raw!r}"
                ) from None
            if count <= 0:
                raise InventoryError(
                    f"slice count must be positive in inventory entry {raw!r}"
                )
        accel, _, topo = entry.partition("/")
        try:
            shape = topology.resolve(accel, topo)
        except topology.TopologyError as e:
            raise InventoryError(f"inventory entry {raw!r}: {e}") from None
        out.append((shape, count))
    if not out:
        raise InventoryError(f"empty node inventory spec {spec!r}")
    return out


def slice_name(shape: topology.SliceShape, index: int) -> str:
    return f"{shape.accelerator_type}-{index}"


def node_name(shape: topology.SliceShape, slice_index: int, host: int) -> str:
    return f"tpu-{shape.accelerator_type}-s{slice_index}-h{host}"


def build_nodes(spec: str) -> list[dict]:
    """Render the inventory spec into Node objects (one per TPU host)."""
    nodes: list[dict] = []
    slice_counter: dict[str, int] = {}
    for shape, count in parse_inventory(spec):
        grid = topology.host_grid(shape)
        for _ in range(count):
            idx = slice_counter.get(shape.accelerator_type, 0)
            slice_counter[shape.accelerator_type] = idx + 1
            for host in range(shape.num_hosts):
                coord = "-".join(str(c) for c in grid[host])
                nodes.append(
                    {
                        "apiVersion": "v1",
                        "kind": "Node",
                        "metadata": {
                            "name": node_name(shape, idx, host),
                            "labels": {
                                LABEL_ACCELERATOR: shape.accelerator_type,
                                LABEL_GENERATION: shape.generation,
                                LABEL_TOPOLOGY: shape.topology,
                                LABEL_SLICE: slice_name(shape, idx),
                                LABEL_HOST_INDEX: str(host),
                                LABEL_HOST_COORD: coord,
                            },
                        },
                        "status": {
                            "capacity": {TPU_RESOURCE: shape.chips_per_host},
                            "allocatable": {TPU_RESOURCE: shape.chips_per_host},
                        },
                    }
                )
    return nodes


def register_nodes(api, spec: str) -> list[dict]:
    """Create the inventory's Node objects on the API server (idempotent:
    an already-registered node is left as-is, so operator restarts against
    a persistent backend do not duplicate or clobber)."""
    from ..runtime.apiserver import AlreadyExistsError

    created = []
    for node in build_nodes(spec):
        try:
            created.append(api.create("nodes", node))
        except AlreadyExistsError:
            created.append(api.get("nodes", "", node["metadata"]["name"]))
    return created
