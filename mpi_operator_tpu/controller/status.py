"""TPUJob status/conditions engine.

Reference analog: /root/reference/v2/pkg/controller/mpi_job_controller_status.go
(kubeflow-common condition bookkeeping): Created/Running/Restarting/
Suspended/Succeeded/Failed conditions with transition-time preservation and
the mutual-exclusion rules (Running <-> Restarting replace each other;
Failed/Succeeded flip Running to False).
"""

from __future__ import annotations

import time
from typing import Optional

from ..api.v2beta1.types import (
    JOB_FAILED,
    JOB_RESTARTING,
    JOB_RUNNING,
    JOB_SUCCEEDED,
    JOB_SUSPENDED,
    JobCondition,
    JobStatus,
    ReplicaStatus,
    TPUJob,
)

# Event/condition reasons (mpi_job_controller_status.go:25-36 analog).
TPUJOB_CREATED_REASON = "TPUJobCreated"
TPUJOB_SUCCEEDED_REASON = "TPUJobSucceeded"
TPUJOB_RUNNING_REASON = "TPUJobRunning"
TPUJOB_FAILED_REASON = "TPUJobFailed"
TPUJOB_EVICTED_REASON = "TPUJobEvicted"
TPUJOB_RESTARTING_REASON = "TPUJobRestarting"
TPUJOB_SUSPENDED_REASON = "TPUJobSuspended"
TPUJOB_RESUMED_REASON = "TPUJobResumed"
# Gang-scheduler surfacing (kube-scheduler vocabulary, not kubeflow's).
TPUJOB_SCHEDULED_REASON = "TPUJobScheduled"
TPUJOB_UNSCHEDULABLE_REASON = "Unschedulable"
# Step-skew observatory (utils/stepstats.py) verdicts.
TPUJOB_STRAGGLING_REASON = "TPUJobStraggling"
TPUJOB_STRAGGLER_RECOVERED_REASON = "TPUJobStragglerRecovered"
# Device-memory observatory (utils/devstats.py) verdicts.
TPUJOB_MEMORY_PRESSURE_REASON = "TPUJobMemoryPressure"
TPUJOB_MEMORY_RECOVERED_REASON = "TPUJobMemoryRecovered"

CONDITION_TRUE = "True"
CONDITION_FALSE = "False"


def initialize_replica_statuses(job: TPUJob, replica_type: str) -> None:
    """:38-46 analog: reset one replica type's per-sync counters. The
    cumulative ``restarts`` counter survives (it bounds elastic
    replacement via runPolicy.backoffLimit)."""
    prior = job.status.replica_statuses.get(replica_type)
    job.status.replica_statuses[replica_type] = ReplicaStatus(
        restarts=prior.restarts if prior else 0
    )


def new_condition(
    type_: str, reason: str, message: str, status: str = CONDITION_TRUE, now: Optional[float] = None
) -> JobCondition:
    now = time.time() if now is None else now
    return JobCondition(
        type=type_,
        status=status,
        reason=reason,
        message=message,
        last_update_time=now,
        last_transition_time=now,
    )


def get_condition(status: JobStatus, type_: str) -> Optional[JobCondition]:
    for condition in status.conditions:
        if condition.type == type_:
            return condition
    return None


def has_condition(status: JobStatus, type_: str) -> bool:
    return any(
        c.type == type_ and c.status == CONDITION_TRUE for c in status.conditions
    )


def is_succeeded(status: JobStatus) -> bool:
    return has_condition(status, JOB_SUCCEEDED)


def is_failed(status: JobStatus) -> bool:
    return has_condition(status, JOB_FAILED)


def is_finished(status: JobStatus) -> bool:
    return is_succeeded(status) or is_failed(status)


def is_suspended(status: JobStatus) -> bool:
    return has_condition(status, JOB_SUSPENDED)


def update_job_conditions(
    job: TPUJob, type_: str, reason: str, message: str,
    status: str = CONDITION_TRUE, now: Optional[float] = None,
) -> bool:
    """Set one condition; True iff the stored conditions changed (the
    signal observability layers key transition timestamps off)."""
    return set_condition(
        job.status, new_condition(type_, reason, message, status, now)
    )


def set_condition(status: JobStatus, condition: JobCondition) -> bool:
    """:100-117 analog: idempotent set with transition-time preservation.
    Returns True when the condition list actually changed."""
    current = get_condition(status, condition.type)
    if (
        current is not None
        and current.status == condition.status
        and current.reason == condition.reason
    ):
        return False  # nothing changed
    if current is not None and current.status == condition.status:
        condition.last_transition_time = current.last_transition_time
    status.conditions = _filter_out_condition(status.conditions, condition.type) + [
        condition
    ]
    return True


def _filter_out_condition(
    conditions: list[JobCondition], cond_type: str
) -> list[JobCondition]:
    """:119-142 analog: drop same-type (and Running<->Restarting pairs);
    flip Running/Failed to False when a terminal condition lands."""
    out = []
    for c in conditions:
        if cond_type == JOB_RESTARTING and c.type == JOB_RUNNING:
            continue
        if cond_type == JOB_RUNNING and c.type == JOB_RESTARTING:
            continue
        if c.type == cond_type:
            continue
        if cond_type in (JOB_FAILED, JOB_SUCCEEDED) and c.type in (
            JOB_RUNNING,
            JOB_FAILED,
        ):
            c = JobCondition(**{**c.__dict__})
            c.status = CONDITION_FALSE
        out.append(c)
    return out
