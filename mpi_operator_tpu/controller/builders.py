"""Child-object builders for a TPUJob.

Reference analog: the object builders in
/root/reference/v2/pkg/controller/mpi_job_controller.go:1103-1546, with the
SSH/MPI machinery replaced by TPU-native wiring:

- headless workers Service  — identical role (stable DNS for workers);
- ConfigMap                 — carries the worker FQDN list (hostfile analog,
  :1106-1128) and an elastic ``discover_hosts.sh`` (:1131-1145 analog);
- worker Pods               — hostname+subdomain identity (:1262-1263
  analog), plus ``TPU_WORKER_ID``/``TPU_WORKER_HOSTNAMES``/coordinator env
  *instead of* mounted SSH keys, and ``google.com/tpu`` resource injection
  *instead of* ``slotsPerWorker`` env (:1363-1377);
- launcher batch Job        — optional, RunPolicy passthrough (:1306-1325
  analog) minus all mpirun/OMPI env;
- PodGroup                  — gang scheduling with minMember = the whole
  slice (a TPU slice is indivisible, unlike the reference's independent GPU
  workers, :1218-1240 analog).
"""

from __future__ import annotations

import copy
import functools

from ..api import topology
from ..api.v2beta1 import constants
from ..api.v2beta1.types import (
    API_VERSION,
    KIND,
    REPLICA_TYPE_LAUNCHER,
    REPLICA_TYPE_WORKER,
    TPUJob,
)
from ..runtime.objects import KubeObject, ObjectMeta, OwnerReference
from ..utils import trace


def _traced(span_name: str):
    """Open a span on the default tracer around an object builder. Builders
    run inside the controller's ``reconcile`` span on the same thread, so
    these become its children in ``/debug/trace``."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(job: TPUJob, *args, **kwargs):
            with trace.span(span_name, job=f"{job.namespace}/{job.name}"):
                return fn(job, *args, **kwargs)

        return wrapper

    return deco


def controller_ref(job: TPUJob) -> dict:
    return OwnerReference(
        api_version=API_VERSION,
        kind=KIND,
        name=job.metadata.name,
        uid=job.metadata.uid,
        controller=True,
        block_owner_deletion=True,
    ).to_dict()


def default_labels(job_name: str, role: str) -> dict[str, str]:
    # mpi_job_controller.go:1502-1508 analog.
    return {
        constants.OPERATOR_NAME_LABEL: constants.OPERATOR_NAME,
        constants.JOB_NAME_LABEL: job_name,
        constants.JOB_ROLE_LABEL: role,
    }


def worker_selector(job_name: str) -> dict[str, str]:
    return default_labels(job_name, constants.ROLE_WORKER)


def spare_selector(job_name: str) -> dict[str, str]:
    return default_labels(job_name, constants.ROLE_SPARE)


def worker_name(job: TPUJob, index: int) -> str:
    return f"{job.name}{constants.WORKER_SUFFIX}-{index}"


def spare_name(job: TPUJob, index: int) -> str:
    return f"{job.name}{constants.SPARE_SUFFIX}-{index}"


def spare_group_name(job: TPUJob) -> str:
    # The spares form their OWN gang: the worker gang must never wait on
    # standby capacity, and the scheduler can evict the spare gang as the
    # cheapest preemption victim without decapitating the workers.
    return job.name + constants.SPARE_SUFFIX


def hot_spares(job: TPUJob) -> int:
    return max(getattr(job.spec.tpu, "hot_spares", 0) or 0, 0)


def workers_service_name(job: TPUJob) -> str:
    return job.name + constants.WORKER_SUFFIX


def launcher_name(job: TPUJob) -> str:
    return job.name + constants.LAUNCHER_SUFFIX


def config_name(job: TPUJob) -> str:
    return job.name + constants.CONFIG_SUFFIX


def worker_replicas(job: TPUJob) -> int:
    spec = job.spec.replica_specs.get(REPLICA_TYPE_WORKER)
    return spec.replicas if spec and spec.replicas is not None else 0


def worker_fqdn(job: TPUJob, index: int) -> str:
    # "<job>-worker-i.<job>-worker.<ns>.svc" (newConfigMap :1110 analog).
    return f"{worker_name(job, index)}.{workers_service_name(job)}.{job.namespace}.svc"


def coordinator_address(job: TPUJob) -> str:
    # Worker 0 is always the jax.distributed coordinator.
    return f"{worker_fqdn(job, 0)}:{job.spec.jax_distribution.coordinator_port}"


def slice_shape(job: TPUJob) -> topology.SliceShape:
    return topology.resolve(job.spec.tpu.accelerator_type, job.spec.tpu.topology)


@_traced("builders.new_service")
def new_service(job: TPUJob, name: str, selector: dict[str, str]) -> KubeObject:
    """Headless Service (newService :1157-1174 analog)."""
    return KubeObject(
        "v1",
        "Service",
        ObjectMeta(
            name=name,
            namespace=job.namespace,
            labels={"app": job.name},
            owner_references=[OwnerReference.from_dict(controller_ref(job))],
        ),
        spec={"clusterIP": "None", "selector": dict(selector)},
    )


def new_workers_service(job: TPUJob) -> KubeObject:
    return new_service(job, workers_service_name(job), worker_selector(job.name))


@_traced("builders.new_config_map")
def new_config_map(job: TPUJob, replicas: int) -> KubeObject:
    """Worker-hostnames ConfigMap (newConfigMap :1106-1128 analog).

    The reference renders an MPI hostfile; we render the newline-separated
    FQDN list that also feeds ``TPU_WORKER_HOSTNAMES``, so sidecars/debug
    tooling can mount the same source of truth the env wiring used.
    """
    hostnames = "".join(worker_fqdn(job, i) + "\n" for i in range(replicas))
    return KubeObject(
        "v1",
        "ConfigMap",
        ObjectMeta(
            name=config_name(job),
            namespace=job.namespace,
            labels={"app": job.name},
            owner_references=[OwnerReference.from_dict(controller_ref(job))],
        ),
        data={constants.HOSTNAMES_KEY: hostnames},
    )


def update_discover_hosts(
    config_map: KubeObject, job: TPUJob, running_worker_pods: list[dict]
) -> None:
    """Elastic host-discovery script (:1131-1145 analog): echoes the FQDN of
    every *currently Running* worker, sorted, for elastic workloads."""
    names = sorted(p["metadata"]["name"] for p in running_worker_pods)
    script = "#!/bin/sh\n" + "".join(
        f"echo {name}.{workers_service_name(job)}.{job.namespace}.svc\n"
        for name in names
    )
    config_map.data[constants.DISCOVER_HOSTS_KEY] = script


def _worker_env(job: TPUJob, index: int, shape: topology.SliceShape) -> list[dict]:
    """The rendezvous env block — the entire replacement for the reference's
    SSH keys + hostfile + OMPI/I_MPI env (:177-201, :1363-1377).

    The ``TPU_WORKER_*`` variables are *slice-local* (libtpu validates the
    hostname list against one slice's topology), while the ``TPUJOB_*``
    process variables are global across slices (one jax.distributed world).
    """
    replicas = worker_replicas(job)
    num_slices = job.spec.tpu.num_slices
    hosts_per_slice = max(shape.num_hosts, 1)
    slice_id = index // hosts_per_slice
    slice_start = slice_id * hosts_per_slice
    slice_hostnames = ",".join(
        worker_fqdn(job, i)
        for i in range(slice_start, min(slice_start + hosts_per_slice, replicas))
    )
    env = [
        {"name": constants.ENV_TPU_WORKER_ID, "value": str(index % hosts_per_slice)},
        {"name": constants.ENV_TPU_WORKER_HOSTNAMES, "value": slice_hostnames},
        {"name": constants.ENV_TPU_ACCELERATOR_TYPE, "value": shape.accelerator_type},
        {"name": constants.ENV_TPU_TOPOLOGY, "value": shape.topology},
        {"name": constants.ENV_TPU_CHIPS_PER_HOST, "value": str(shape.chips_per_host)},
        {"name": constants.ENV_COORDINATOR_ADDRESS, "value": coordinator_address(job)},
        {"name": constants.ENV_NUM_PROCESSES, "value": str(replicas)},
        {"name": constants.ENV_PROCESS_ID, "value": str(index)},
        {"name": constants.ENV_JOB_NAME, "value": job.name},
        {"name": constants.ENV_JOB_NAMESPACE, "value": job.namespace},
    ]
    ctx = trace.current_context()
    if ctx is not None:
        # Trace propagation: the launcher/worker process adopts this on
        # startup, parenting its spans under the builder span that
        # stamped it (one trace id from reconcile to jax.distributed).
        env.append(
            {"name": constants.ENV_TRACE_CONTEXT, "value": ctx.encode()}
        )
    if num_slices > 1:
        env += [
            {"name": constants.ENV_NUM_SLICES, "value": str(num_slices)},
            {"name": constants.ENV_SLICE_ID, "value": str(slice_id)},
            # DCN wiring: libtpu megascale reads these to stitch slices
            # together (the GKE JobSet contract). Slice 0's host 0
            # coordinates; its stable FQDN exists before any pod runs, so
            # no discovery step is needed.
            {
                "name": constants.ENV_MEGASCALE_COORDINATOR_ADDRESS,
                "value": (
                    f"{worker_fqdn(job, 0)}:{constants.DEFAULT_MEGASCALE_PORT}"
                ),
            },
            {"name": constants.ENV_MEGASCALE_NUM_SLICES, "value": str(num_slices)},
            {"name": constants.ENV_MEGASCALE_SLICE_ID, "value": str(slice_id)},
            {
                "name": constants.ENV_MEGASCALE_PORT,
                "value": str(constants.DEFAULT_MEGASCALE_PORT),
            },
        ]
    return env


@_traced("builders.new_worker")
def new_worker(job: TPUJob, index: int, gang_scheduler_name: str = "") -> KubeObject:
    """Worker Pod (newWorker :1249-1304 analog)."""
    shape = slice_shape(job)
    template = copy.deepcopy(job.spec.replica_specs[REPLICA_TYPE_WORKER].template)
    pod_spec = template.setdefault("spec", {})
    tmeta = template.setdefault("metadata", {})

    labels = dict(tmeta.get("labels") or {})
    labels.update(default_labels(job.name, constants.ROLE_WORKER))
    labels[constants.REPLICA_INDEX_LABEL] = str(index)
    annotations = dict(tmeta.get("annotations") or {})
    # Elastic stamp: which world size this pod's rendezvous env encodes.
    annotations[constants.WORLD_SIZE_ANNOTATION] = str(worker_replicas(job))

    name = worker_name(job, index)
    pod_spec["hostname"] = name
    pod_spec["subdomain"] = workers_service_name(job)  # matches the Service
    if pod_spec.get("hostNetwork"):
        pod_spec["dnsPolicy"] = "ClusterFirstWithHostNet"
    pod_spec["restartPolicy"] = job.spec.replica_specs[
        REPLICA_TYPE_WORKER
    ].restart_policy

    containers = pod_spec.get("containers") or [{}]
    container = containers[0]
    # Default worker command: a jax.distributed collective health check —
    # the TPU-native analog of the reference's default `/usr/sbin/sshd -De`
    # (:1272-1274): something safe every worker can run when the user gives
    # no command. Unlike sshd it *completes*, proving the slice wires up.
    if not container.get("command") and not container.get("args"):
        container["command"] = ["python", "-m", "mpi_operator_tpu.launcher.healthcheck"]
    container.setdefault("env", [])
    container["env"] = list(container["env"]) + _worker_env(job, index, shape)
    # google.com/tpu resource injection (replaces slots env :1363-1377).
    resources = container.setdefault("resources", {})
    for bound in ("limits", "requests"):
        section = resources.setdefault(bound, {})
        section.setdefault(constants.TPU_RESOURCE_NAME, shape.chips_per_host)
    pod_spec["containers"] = containers

    if gang_scheduler_name:
        pod_spec["schedulerName"] = gang_scheduler_name
        annotations["scheduling.k8s.io/group-name"] = job.name

    meta = ObjectMeta(
        name=name,
        namespace=job.namespace,
        labels=labels,
        annotations=annotations,
        owner_references=[OwnerReference.from_dict(controller_ref(job))],
    )
    return KubeObject("v1", "Pod", meta, spec=pod_spec)


@_traced("builders.new_launcher_job")
def new_launcher_job(job: TPUJob, gang_scheduler_name: str = "") -> KubeObject:
    """Launcher batch Job (newLauncherJob :1306-1325 analog), optional in a
    TPUJob: orchestration-only duties (eval loops, logging), never rank
    bootstrap — workers self-assemble via jax.distributed."""
    launcher_spec = job.spec.replica_specs[REPLICA_TYPE_LAUNCHER]
    template = copy.deepcopy(launcher_spec.template)
    pod_spec = template.setdefault("spec", {})
    tmeta = template.setdefault("metadata", {})

    labels = dict(tmeta.get("labels") or {})
    labels.update(default_labels(job.name, constants.ROLE_LAUNCHER))
    # batch/v1 convention label so launcher pods are findable by job name.
    labels["job-name"] = launcher_name(job)
    annotations = dict(tmeta.get("annotations") or {})

    pod_spec["restartPolicy"] = launcher_spec.restart_policy
    containers = pod_spec.get("containers") or [{}]
    container = containers[0]
    container.setdefault("env", [])
    shape = slice_shape(job)
    container["env"] = list(container["env"]) + [
        {"name": constants.ENV_COORDINATOR_ADDRESS, "value": coordinator_address(job)},
        {"name": constants.ENV_NUM_PROCESSES, "value": str(worker_replicas(job))},
        {"name": constants.ENV_TPU_ACCELERATOR_TYPE, "value": shape.accelerator_type},
        {"name": constants.ENV_TPU_TOPOLOGY, "value": shape.topology},
        {"name": constants.ENV_JOB_NAME, "value": job.name},
        {"name": constants.ENV_JOB_NAMESPACE, "value": job.namespace},
    ]
    ctx = trace.current_context()
    if ctx is not None:
        # Same propagation contract as worker pods (_worker_env).
        container["env"] = container["env"] + [
            {"name": constants.ENV_TRACE_CONTEXT, "value": ctx.encode()}
        ]
    pod_spec["containers"] = containers

    if gang_scheduler_name:
        pod_spec["schedulerName"] = gang_scheduler_name
        annotations["scheduling.k8s.io/group-name"] = job.name

    job_spec: dict = {
        "template": {
            "metadata": {"labels": labels, "annotations": annotations},
            "spec": pod_spec,
        }
    }
    rp = job.spec.run_policy
    if rp.ttl_seconds_after_finished is not None:
        job_spec["ttlSecondsAfterFinished"] = rp.ttl_seconds_after_finished
    if rp.active_deadline_seconds is not None:
        job_spec["activeDeadlineSeconds"] = rp.active_deadline_seconds
    if rp.backoff_limit is not None:
        job_spec["backoffLimit"] = rp.backoff_limit

    return KubeObject(
        "batch/v1",
        "Job",
        ObjectMeta(
            name=launcher_name(job),
            namespace=job.namespace,
            labels={"app": job.name},
            owner_references=[OwnerReference.from_dict(controller_ref(job))],
        ),
        spec=job_spec,
    )


@_traced("builders.new_pod_group")
def new_pod_group(job: TPUJob, min_member: int) -> KubeObject:
    """PodGroup (newPodGroup :1218-1240 analog)."""
    priority_class = ""
    for rtype in (REPLICA_TYPE_LAUNCHER, REPLICA_TYPE_WORKER):
        spec = job.spec.replica_specs.get(rtype)
        if spec is not None:
            priority_class = (spec.template.get("spec") or {}).get(
                "priorityClassName", ""
            )
            if priority_class:
                break
    sp = job.spec.run_policy.scheduling_policy
    queue = job.metadata.annotations.get("scheduling.volcano.sh/queue-name", "")
    if sp is not None:
        if sp.min_available is not None:
            min_member = sp.min_available
        if sp.queue:
            queue = sp.queue
        if sp.priority_class:
            priority_class = sp.priority_class
    spec: dict = {"minMember": min_member}
    if queue:
        spec["queue"] = queue
    if priority_class:
        spec["priorityClassName"] = priority_class
    return KubeObject(
        "scheduling.x-k8s.io/v1alpha1",
        "PodGroup",
        ObjectMeta(
            name=job.name,
            namespace=job.namespace,
            owner_references=[OwnerReference.from_dict(controller_ref(job))],
        ),
        spec=spec,
    )


@_traced("builders.new_spare")
def new_spare(job: TPUJob, index: int, gang_scheduler_name: str = "") -> KubeObject:
    """Hot-spare standby Pod (spec.tpu.hotSpares).

    Same template, node shape, and chip footprint as a worker — it must be
    schedulable anywhere a worker is — but it runs the ``park`` launcher
    instead of the user command, so it bootstraps (image pulled, runtime
    warm) and then blocks *before* the collective barrier. Promotion turns
    its reserved node into a pre-bound replacement worker, skipping
    schedule->pending->bootstrap entirely.
    """
    shape = slice_shape(job)
    template = copy.deepcopy(job.spec.replica_specs[REPLICA_TYPE_WORKER].template)
    pod_spec = template.setdefault("spec", {})
    tmeta = template.setdefault("metadata", {})

    labels = dict(tmeta.get("labels") or {})
    labels.update(default_labels(job.name, constants.ROLE_SPARE))
    labels[constants.REPLICA_INDEX_LABEL] = str(index)
    annotations = dict(tmeta.get("annotations") or {})
    annotations[constants.STANDBY_ANNOTATION] = "true"
    annotations[constants.WORLD_SIZE_ANNOTATION] = str(worker_replicas(job))

    name = spare_name(job, index)
    pod_spec["hostname"] = name
    pod_spec["subdomain"] = workers_service_name(job)
    if pod_spec.get("hostNetwork"):
        pod_spec["dnsPolicy"] = "ClusterFirstWithHostNet"
    pod_spec["restartPolicy"] = "Never"

    containers = pod_spec.get("containers") or [{}]
    container = containers[0]
    # A spare must never start training: the user command is replaced with
    # the parking loop unconditionally. The rendezvous env is *not* stamped
    # here — the promoted replacement worker is a fresh pod whose env is
    # restamped by new_worker at promotion time.
    container["command"] = ["python", "-m", "mpi_operator_tpu.launcher.park"]
    container.pop("args", None)
    container.setdefault("env", [])
    container["env"] = list(container["env"]) + [
        {"name": constants.ENV_TPU_ACCELERATOR_TYPE, "value": shape.accelerator_type},
        {"name": constants.ENV_TPU_TOPOLOGY, "value": shape.topology},
        {"name": constants.ENV_TPU_CHIPS_PER_HOST, "value": str(shape.chips_per_host)},
        {"name": constants.ENV_JOB_NAME, "value": job.name},
        {"name": constants.ENV_JOB_NAMESPACE, "value": job.namespace},
    ]
    # Full chip footprint: the spare *holds* a worker-shaped node so the
    # promoted pod can bind to it without a scheduling pass.
    resources = container.setdefault("resources", {})
    for bound in ("limits", "requests"):
        section = resources.setdefault(bound, {})
        section.setdefault(constants.TPU_RESOURCE_NAME, shape.chips_per_host)
    pod_spec["containers"] = containers

    if gang_scheduler_name:
        pod_spec["schedulerName"] = gang_scheduler_name
        annotations["scheduling.k8s.io/group-name"] = spare_group_name(job)

    meta = ObjectMeta(
        name=name,
        namespace=job.namespace,
        labels=labels,
        annotations=annotations,
        owner_references=[OwnerReference.from_dict(controller_ref(job))],
    )
    return KubeObject("v1", "Pod", meta, spec=pod_spec)


@_traced("builders.new_spare_group")
def new_spare_group(job: TPUJob) -> KubeObject:
    """PodGroup for the spare gang.

    Inherits the job's priorityClassName so a high-priority job pre-reserves
    standby capacity at its own priority; minMember is the spare count (the
    worker gang never waits on spares).
    """
    priority_class = ""
    for rtype in (REPLICA_TYPE_LAUNCHER, REPLICA_TYPE_WORKER):
        rspec = job.spec.replica_specs.get(rtype)
        if rspec is not None:
            priority_class = (rspec.template.get("spec") or {}).get(
                "priorityClassName", ""
            )
            if priority_class:
                break
    sp = job.spec.run_policy.scheduling_policy
    if sp is not None and sp.priority_class:
        priority_class = sp.priority_class
    spec: dict = {"minMember": hot_spares(job)}
    if priority_class:
        spec["priorityClassName"] = priority_class
    return KubeObject(
        "scheduling.x-k8s.io/v1alpha1",
        "PodGroup",
        ObjectMeta(
            name=spare_group_name(job),
            namespace=job.namespace,
            owner_references=[OwnerReference.from_dict(controller_ref(job))],
        ),
        spec=spec,
    )
