"""The TPUJob reconciler: status engine, object builders, controller."""
